"""Fleet telemetry plane: cross-process collection, trace stitching, and
fleet-aggregated SLOs.

Everything PRs 5/8 built — the ring exporter, the SLO engine, the flight
recorder — is a per-process island: the sidecar's real ``sidecar.pack``
spans live in the sidecar's OWN ring and reach the controller only as
grafted timing records, and no endpoint anywhere can answer "where did
this solve's 160ms go, fleet-wide". This module is the missing plane,
three pieces:

- **Flush**: every process (controller replicas AND sidecars) periodically
  publishes a member payload — completed span trees, the SLO engine's
  mergeable histogram snapshot (``SloEngine.histogram_snapshot``), and the
  profiler's fold summary — to a shared backend. The file backend is a
  flock'd per-member dir with atomic tmp+rename (the launch-journal
  discipline: each member owns ONE file, so a crashed writer can never
  corrupt a peer's); the HTTP backend instead PULLS members' existing
  ``/debug/traces`` + ``/debug/slo`` + ``/debug/profile`` endpoints, so a
  deployment with no shared volume still aggregates.

- **Stitch**: a sidecar's ``sidecar.pack`` tree is a local ROOT carrying
  the controller's trace id and the dispatch-time span id as its
  ``parent_id`` (the traceparent the v3 wire already carries).
  :func:`stitch` re-joins those roots into their controller trees —
  preferring the ``solver.wire`` transport span that wall-overlaps the
  sidecar's work, whose grafted ``sidecar.*`` stage RECORDS it replaces
  with the real subtree — and REBASES the foreign perf_counter timeline
  into the parent's (clocks never agree across processes; wall stamps on
  the same machine do). The result is ONE fleet-wide tree whose
  ``critical_path`` splits wire vs sidecar admission-queue vs device time.

- **Aggregate**: the PR-8 log-linear histograms are mergeable by
  construction (fixed GROWTH bucket geometry), so member SLO windows merge
  bucket-by-bucket into fleet-wide quantiles and burn rates, judged by the
  same objective grammar. ``GET /debug/fleet`` serves the member inventory
  (with staleness), the fleet SLO verdicts, and the stitched-trace index.
"""

from __future__ import annotations

import contextlib
import copy
import fcntl
import glob
import json
import logging
import math
import os
import re
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.obs.slo import Histogram, Objective, MIN_WINDOW_EVENTS

logger = logging.getLogger("karpenter.obs")

PAYLOAD_VERSION = 1
# how many of the newest ring trees a flush ships; the collector keeps only
# each member's latest payload, so this bounds the fleet-wide working set
FLUSH_TREE_LIMIT = 64
DEFAULT_FLUSH_INTERVAL_S = 10.0
# a member is STALE once its last flush is older than this many intervals —
# crashed, partitioned, or wedged; its data still shows, flagged
STALE_INTERVALS = 3.0
# wall-clock slack when matching a sidecar tree to its wire span: same-host
# clocks agree to well under this; cross-host NTP skew gets the benefit of
# the doubt (a miss degrades to the anchor span, never a wrong trace)
WALL_SLACK_S = 0.25

# the transport spans a foreign sidecar.pack tree prefers as its parent,
# and the wire-trailer stage RECORDS the real subtree replaces
WIRE_PARENT_NAMES = ("solver.wire",)
GRAFT_RECORD_NAMES = ("sidecar.solve", "sidecar.fetch", "sidecar.serialize")

_SAFE_IDENT = re.compile(r"[^A-Za-z0-9_.-]")


def _walk(tree: Dict[str, Any]):
    stack = [tree]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(node.get("children") or [])


# ---------------------------------------------------------------------------
# stitching
# ---------------------------------------------------------------------------


def _wall_interval(span: Dict[str, Any]) -> Tuple[float, float]:
    w0 = float(span.get("wall_start") or 0.0)
    return w0, w0 + float(span.get("duration_ms") or 0.0) / 1e3


def _wall_overlaps(a: Dict[str, Any], b: Dict[str, Any], slack: float) -> bool:
    a0, a1 = _wall_interval(a)
    b0, b1 = _wall_interval(b)
    return a0 - slack < b1 and b0 - slack < a1


def _rebase(root: Dict[str, Any], parent: Dict[str, Any]) -> None:
    """Shift a foreign subtree's perf_counter stamps into the parent's
    timeline (positioned by the wall clocks both processes share), then
    clamp every span inside the parent's bounds — the stitched tree must
    stay monotonic-consistent for critical_path/overlap analysis even
    under wall skew. ``duration_ms`` keeps the MEASURED value."""
    p0 = float(parent.get("t0") or 0.0)
    p1 = float(parent.get("t1") or p0)
    dur = max(float(root.get("t1") or 0.0) - float(root.get("t0") or 0.0), 0.0)
    offset = (float(root.get("wall_start") or 0.0)
              - float(parent.get("wall_start") or 0.0))
    new_t0 = p0 + max(offset, 0.0)
    # keep the subtree inside the parent: a child reported longer than its
    # parent (clock skew) pins to the parent's bounds
    new_t0 = min(max(new_t0, p0), max(p1 - dur, p0))
    shift = new_t0 - float(root.get("t0") or 0.0)
    for node in _walk(root):
        node["t0"] = min(max(float(node.get("t0") or 0.0) + shift, p0), p1)
        node["t1"] = min(max(float(node.get("t1") or 0.0) + shift, p0), p1)


def stitch(
    trees: Sequence[Dict[str, Any]],
    wall_slack_s: float = WALL_SLACK_S,
) -> Tuple[List[Dict[str, Any]], int]:
    """Join foreign-rooted span trees into the trees holding their parent
    spans. Returns ``(roots, joins)``: the surviving root trees (joined
    subtrees removed from the top level) and how many joins happened.

    A foreign root is any tree whose root carries a ``parent_id`` (a
    remote-parented local root — the sidecar's ``sidecar.pack``, the cloud
    wire's ``cloudapi.request``). Its anchor is the span with that id in
    another tree of the SAME trace. ``sidecar.pack`` roots prefer a
    ``solver.wire`` span of the trace that wall-overlaps them — that is
    the RPC they rode — and replace its grafted ``sidecar.*`` stage
    records (childless, wire-trailer provenance) with the real subtree so
    nothing double-counts. Inputs are never mutated."""
    trees = [copy.deepcopy(t) for t in trees]
    index: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for t in trees:
        for s in _walk(t):
            sid = s.get("span_id")
            if sid:
                # first writer wins: a span id duplicated across payload
                # generations keeps ONE anchor
                index.setdefault((t.get("trace_id"), sid), s)
    joins = 0
    attached: List[int] = []
    pending = [t for t in trees if t.get("parent_id")]
    pending.sort(key=lambda t: float(t.get("wall_start") or 0.0))
    for root in pending:
        trace_id = root.get("trace_id")
        anchor = index.get((trace_id, root.get("parent_id")))
        if anchor is None or anchor is root:
            continue  # the other half never flushed (yet): stays a root
        if any(s is anchor for s in _walk(root)):
            continue  # cycle guard: never attach a tree into itself
        parent = anchor
        if root.get("name") == "sidecar.pack":
            candidates = [
                s for (tid, _), s in index.items()
                if tid == trace_id
                and s.get("name") in WIRE_PARENT_NAMES
                and not any(x is s for x in _walk(root))
                and _wall_overlaps(s, root, wall_slack_s)
            ]
            if candidates:
                parent = min(
                    candidates,
                    key=lambda s: abs(
                        float(s.get("wall_start") or 0.0)
                        - float(root.get("wall_start") or 0.0)
                    ),
                )
                parent["children"] = [
                    c for c in (parent.get("children") or [])
                    if not (
                        c.get("name") in GRAFT_RECORD_NAMES
                        and not c.get("children")
                    )
                ]
        _rebase(root, parent)
        root["stitched"] = True
        parent.setdefault("children", []).append(root)
        attached.append(id(root))
        joins += 1
    roots = [t for t in trees if id(t) not in attached]
    return roots, joins


def wire_attribution(tree: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Split the slowest ``solver.wire`` leg of a (stitched) tree into
    wire transport vs sidecar admission-queue vs device time — the
    attribution ROADMAP item 2 (streaming transport) needs before anyone
    touches the hot path. ``None`` when the tree never crossed the wire."""
    wires = [s for s in _walk(tree) if s.get("name") in WIRE_PARENT_NAMES]
    if not wires:
        return None
    wire = max(wires, key=lambda s: float(s.get("duration_ms") or 0.0))
    total_ms = float(wire.get("duration_ms") or 0.0)
    pack = next(
        (c for c in (wire.get("children") or [])
         if c.get("name") == "sidecar.pack"),
        None,
    )
    if pack is not None:
        # the wire span measures the BLOCKING residual (the double-buffered
        # client dispatches at pack_begin and waits later), so the sidecar's
        # work can wall-precede and even exceed it; the honest RPC envelope
        # is the union of the two intervals
        w0, w1 = _wall_interval(wire)
        p0, p1 = _wall_interval(pack)
        total_ms = max(total_ms, (max(w1, p1) - min(w0, p0)) * 1e3)
    if pack is not None:
        device_ms = sum(
            float(c.get("duration_ms") or 0.0)
            for c in (pack.get("children") or [])
            if c.get("name") in ("sidecar.solve", "sidecar.fetch")
        )
        # the admission gate is entered BEFORE the pack span opens (a
        # backdated child would corrupt self-time), so queue time rides
        # the span as an attribute
        try:
            queue_ms = float(
                (pack.get("attrs") or {}).get("admission_wait_s") or 0.0
            ) * 1e3
        except (TypeError, ValueError):
            queue_ms = 0.0
        sidecar_ms = float(pack.get("duration_ms") or 0.0) + queue_ms
        stitched = bool(pack.get("stitched"))
    else:
        # unstitched: only the wire-trailer grafts to go by
        records = [
            c for c in (wire.get("children") or [])
            if c.get("name") in GRAFT_RECORD_NAMES
        ]
        device_ms = sum(
            float(c.get("duration_ms") or 0.0) for c in records
            if c.get("name") in ("sidecar.solve", "sidecar.fetch")
        )
        queue_ms = 0.0
        sidecar_ms = sum(float(c.get("duration_ms") or 0.0) for c in records)
        stitched = False
    wire_ms = max(total_ms - sidecar_ms, 0.0)
    return {
        "total_ms": round(total_ms, 3),
        "wire_ms": round(wire_ms, 3),
        "sidecar_queue_ms": round(queue_ms, 3),
        "device_ms": round(device_ms, 3),
        "stitched": stitched,
        "wire_share_pct": round(wire_ms / total_ms * 100, 1) if total_ms else None,
    }


# ---------------------------------------------------------------------------
# fleet SLO aggregation
# ---------------------------------------------------------------------------


def _burn_rate(h: Histogram, budget: float) -> float:
    """Merged-window burn rate, same volume guard as the per-process
    engine: a fleet window under MIN_WINDOW_EVENTS never burns."""
    if h.events() < MIN_WINDOW_EVENTS:
        return 0.0
    return (h.bad / h.events()) / budget


def merge_objective_snapshots(
    members: Dict[str, Dict[str, Any]],
) -> Dict[str, Any]:
    """Merge per-member ``SloEngine.histogram_snapshot`` payloads into
    fleet-wide verdicts: per objective name, bucket-add every member's
    fast/slow windows (fixed geometry makes this exact), then re-judge the
    merged sketch with the shared grammar. Objectives present on only some
    members (controller vs sidecar sets) merge over whoever reports them."""
    merged: Dict[str, Dict[str, Any]] = {}
    for identity, snap in members.items():
        for name, obj in (snap.get("objectives") or {}).items():
            slot = merged.setdefault(name, {
                "expr": obj.get("expr"),
                "fast": Histogram(),
                "slow": Histogram(),
                "members": [],
                "breach": None,
            })
            slot["fast"].merge(obj.get("fast") or {})
            slot["slow"].merge(obj.get("slow") or {})
            slot["members"].append(identity)
            if obj.get("breach"):
                slot["breach"] = obj["breach"]
    out: Dict[str, Any] = {}
    for name, slot in merged.items():
        try:
            obj = Objective(slot["expr"])
        except (ValueError, TypeError):
            continue  # a member shipped an expr this build can't parse
        fast: Histogram = slot["fast"]
        slow: Histogram = slot["slow"]
        if obj.kind == "latency":
            value = fast.quantile(obj.quantile) if obj.quantile is not None else fast.mean()
        else:
            value = (fast.good / fast.events()) if fast.events() else None
        burn_fast = _burn_rate(fast, obj.budget)
        burn_slow = _burn_rate(slow, obj.budget)
        out[name] = {
            "expr": obj.expr,
            "kind": obj.kind,
            "threshold": obj.threshold,
            "value": value,
            "ok": obj.evaluate(value),
            "burn_rate": {
                "fast": round(burn_fast, 4), "slow": round(burn_slow, 4),
            },
            "burning": burn_fast >= 1.0 and burn_slow >= 1.0,
            "events": {"fast": fast.events(), "slow": slow.events()},
            "members": sorted(slot["members"]),
            "breach": slot["breach"],
        }
    return out


# ---------------------------------------------------------------------------
# backends
# ---------------------------------------------------------------------------


class FileTelemetryBackend:
    """Shared-directory backend: each member owns ``member-<identity>.json``
    and replaces it whole with atomic tmp+rename under a directory flock —
    the launch-journal discipline, minus the RMW (one writer per file means
    publish is replace, not read-modify-write; the flock serializes dir
    maintenance and keeps a poll from reading mid-sweep)."""

    def __init__(self, directory: str, identity: Optional[str] = None):
        self.directory = directory
        self.identity = identity or f"{os.uname().nodename}-{os.getpid()}"
        os.makedirs(directory, exist_ok=True)

    @contextlib.contextmanager
    def _locked(self):
        lock_path = os.path.join(self.directory, ".telemetry.flock")
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _member_path(self, identity: str) -> str:
        return os.path.join(
            self.directory, f"member-{_SAFE_IDENT.sub('_', identity)}.json"
        )

    def publish(self, payload: Dict[str, Any]) -> None:
        path = self._member_path(str(payload.get("identity") or self.identity))
        tmp = f"{path}.{os.getpid()}.tmp"
        body = json.dumps(payload)
        with self._locked():
            # sweep temp files a crashed writer left between write & rename
            horizon = time.time() - 60.0
            for stale in glob.glob(os.path.join(glob.escape(self.directory), "*.tmp")):
                try:
                    if os.path.getmtime(stale) < horizon:
                        os.remove(stale)
                except OSError:
                    pass
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(body)
            os.replace(tmp, path)

    def poll(self) -> List[Dict[str, Any]]:
        with self._locked():
            names = sorted(
                glob.glob(os.path.join(glob.escape(self.directory), "member-*.json"))
            )
            out = []
            for name in names:
                try:
                    with open(name, encoding="utf-8") as f:
                        doc = json.load(f)
                    if isinstance(doc, dict):
                        out.append(doc)
                except (OSError, json.JSONDecodeError):
                    continue  # a racer's half-state never poisons the poll
        return out


class HttpTelemetryBackend:
    """Pull mode: scrape members' EXISTING debug endpoints — no shared
    volume needed. Each peer is ``<base url>`` or ``<name>=<base url>``;
    one poll GETs ``/debug/traces`` (+ ``/debug/slo``, ``/debug/profile``,
    best-effort) and assembles the same member payload the file backend
    carries. An unreachable peer contributes nothing this round; the
    collector's staleness accounting surfaces it."""

    def __init__(self, peers: Sequence[str], timeout: float = 2.0):
        self.peers: List[Tuple[str, str]] = []
        for peer in peers:
            peer = peer.strip()
            if not peer:
                continue
            if "=" in peer.split("://", 1)[0]:
                name, _, url = peer.partition("=")
            else:
                name, url = peer, peer
            self.peers.append((name, url.rstrip("/")))
        self.timeout = timeout

    def _get_json(self, url: str) -> Optional[Dict[str, Any]]:
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except Exception:
            return None

    def poll(self) -> List[Dict[str, Any]]:
        out = []
        for name, url in self.peers:
            traces = self._get_json(f"{url}/debug/traces?limit={FLUSH_TREE_LIMIT}")
            if traces is None:
                continue  # unreachable: staleness accounting shows it
            slo = self._get_json(f"{url}/debug/slo") or {}
            profile = self._get_json(f"{url}/debug/profile") or {}
            decisions = self._get_json(f"{url}/debug/decisions?limit=16") or {}
            incidents = self._get_json(f"{url}/debug/incidents?limit=8") or {}
            out.append({
                "version": PAYLOAD_VERSION,
                "identity": name,
                "role": "scraped",
                "flushed_at": time.time(),
                "traces": traces.get("traces") or [],
                "slo": slo.get("histograms") or {},
                "profile": profile.get("profile") or {},
                "decisions": decisions.get("decisions") or [],
                "incidents": incidents.get("incidents") or [],
            })
        return out


# ---------------------------------------------------------------------------
# the plane: flusher + collector
# ---------------------------------------------------------------------------


def member_payload(identity: str, role: str) -> Dict[str, Any]:
    """This process's flush body: newest ring trees, the SLO engine's
    mergeable histogram snapshot, the profiler's fold summary, and the
    decision audit log's bounded summaries (a dead replica's decisions
    survive it in /debug/fleet through these)."""
    from karpenter_tpu import obs

    eng = obs.slo_engine()
    prof = obs.profiler()
    exp = obs.exporter()
    sent = obs.sentinel()
    return {
        "version": PAYLOAD_VERSION,
        "identity": identity,
        "role": role,
        "flushed_at": time.time(),
        # NEWEST first: the limit slices from the head, so a full ring
        # ships the latest solves, not traffic from 192 solves ago
        "traces": exp.snapshot(limit=FLUSH_TREE_LIMIT, newest_first=True),
        "slo": eng.histogram_snapshot() if eng is not None else {},
        "profile": prof.snapshot(top_n=10) if prof is not None else {},
        "decisions": obs.decision_log().summaries(),
        # bounded sentinel incident summaries: a dead member's regressions
        # stay visible in /debug/fleet as long as its last payload does
        "incidents": (
            sent.incidents.summaries(limit=8) if sent is not None else []
        ),
    }


class TelemetryCollector:
    """Aggregates member payloads from any set of backends; owns the
    stitched-trace cache and the ``/debug/fleet`` body."""

    def __init__(
        self,
        backends: Sequence[Any],
        flush_interval: float = DEFAULT_FLUSH_INTERVAL_S,
        clock: Callable[[], float] = time.time,
        extra_trees: Optional[Callable[[], List[Dict[str, Any]]]] = None,
    ):
        self.backends = list(backends)
        self.flush_interval = flush_interval
        self._clock = clock
        # the collector's OWN process may not flush to any backend (pull
        # deployments): extra_trees contributes its local ring directly
        self._extra_trees = extra_trees
        self._lock = threading.Lock()
        self._members: Dict[str, Dict[str, Any]] = {}  # guarded-by: self._lock
        # stitched-span keys CURRENTLY visible in member payloads — the
        # idempotence set for the stitched-traces counter. Replaced (not
        # grown) every recompute: a key only re-appears while its flushed
        # tree is still in some member's window, so swapping to the
        # current set both stays bounded and never double-counts.
        self._stitched_seen: set = set()  # guarded-by: self._lock
        self._last_refresh = 0.0  # guarded-by: self._lock
        # stitch cache: /debug/fleet re-polled faster than the refresh
        # window must not deep-copy + re-stitch an identical working set
        # per request (the health-server thread pays it)
        self._stitch_roots: Optional[List[Dict[str, Any]]] = None  # guarded-by: self._lock
        self._stitch_at = -math.inf  # guarded-by: self._lock

    def refresh(self) -> None:
        payloads: List[Dict[str, Any]] = []
        for backend in self.backends:
            try:
                payloads.extend(backend.poll())
            except Exception:
                logger.debug("telemetry backend poll failed", exc_info=True)
        with self._lock:
            for p in payloads:
                identity = str(p.get("identity") or "")
                if not identity:
                    continue
                cur = self._members.get(identity)
                if cur is None or (
                    float(p.get("flushed_at") or 0.0)
                    >= float(cur.get("flushed_at") or 0.0)
                ):
                    self._members[identity] = p
            self._last_refresh = self._clock()

    def _refresh_if_stale(self) -> None:
        with self._lock:
            fresh = self._clock() - self._last_refresh < 1.0
        if not fresh:
            self.refresh()

    def members(self) -> List[Dict[str, Any]]:
        """Inventory with staleness: who has flushed, how long ago, and
        whether they have gone quiet past the stale horizon."""
        now = self._clock()
        horizon = self.flush_interval * STALE_INTERVALS
        with self._lock:
            payloads = list(self._members.values())
        out = []
        for p in payloads:
            age = max(now - float(p.get("flushed_at") or 0.0), 0.0)
            prof = p.get("profile") or {}
            out.append({
                "identity": p.get("identity"),
                "role": p.get("role"),
                "age_s": round(age, 1),
                "stale": age > horizon,
                "trees": len(p.get("traces") or []),
                "profile_samples": prof.get("samples", 0),
            })
        return sorted(out, key=lambda m: str(m["identity"]))

    def _all_trees(self) -> List[Dict[str, Any]]:
        with self._lock:
            payloads = list(self._members.items())
        trees: List[Dict[str, Any]] = []
        seen: set = set()
        for identity, p in payloads:
            for t in p.get("traces") or []:
                key = (t.get("trace_id"), t.get("span_id"))
                if key in seen:
                    continue
                seen.add(key)
                t = dict(t)
                t["member"] = identity
                trees.append(t)
        if self._extra_trees is not None:
            for t in self._extra_trees():
                key = (t.get("trace_id"), t.get("span_id"))
                if key in seen:
                    continue
                seen.add(key)
                trees.append(t)
        return trees

    def stitched(self) -> Tuple[List[Dict[str, Any]], int]:
        """Stitch everything currently collected; counts NEW joins on
        ``karpenter_telemetry_stitched_traces_total`` (re-stitching the
        same flushed tree on the next poll is not a new stitch). The
        result is cached for the refresh window (callers treat the trees
        as read-only) so a hot /debug/fleet poller pays one stitch per
        window, not per request."""
        with self._lock:
            if (
                self._stitch_roots is not None
                and self._clock() - self._stitch_at < 1.0
            ):
                return self._stitch_roots, 0
        roots, _ = stitch(self._all_trees())
        current = {
            (s.get("trace_id"), s.get("span_id"))
            for root in roots
            for s in _walk(root)
            if s.get("stitched")
        }
        with self._lock:
            new = len(current - self._stitched_seen)
            # swap, don't grow: keys vanish with their flushed trees and
            # never return, so the set stays bounded by the working set
            self._stitched_seen = current
            self._stitch_roots = roots
            self._stitch_at = self._clock()
        if new:
            try:
                from karpenter_tpu import metrics

                metrics.TELEMETRY_STITCHED.inc(new)
            except Exception:
                pass
        return roots, new

    def fleet_slo(self) -> Dict[str, Any]:
        with self._lock:
            snaps = {
                identity: p.get("slo") or {}
                for identity, p in self._members.items()
                if p.get("slo")
            }
        return merge_objective_snapshots(snaps)

    def fleet_decisions(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Cross-member decision index, newest first: every member's
        flushed decision summaries tagged with who recorded them. A dead
        replica's rounds stay visible for as long as its last payload
        does — exactly the flight-recorder property the per-process
        /debug/decisions ring cannot give."""
        with self._lock:
            payloads = list(self._members.items())
        out: List[Dict[str, Any]] = []
        seen: set = set()
        for identity, p in payloads:
            for d in p.get("decisions") or []:
                did = d.get("id")
                if not did or did in seen:
                    continue
                seen.add(did)
                out.append({**d, "member": identity})
        out.sort(key=lambda d: -float(d.get("recorded_at") or 0.0))
        return out[:limit]

    def fleet_incidents(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Cross-member regression-incident index, newest first — the
        fleet twin of :meth:`fleet_decisions`: every member's flushed
        sentinel incident summaries tagged with who opened them, deduped
        by incident id (a scraped member can also flush to the file
        backend)."""
        with self._lock:
            payloads = list(self._members.items())
        out: List[Dict[str, Any]] = []
        seen: set = set()
        for identity, p in payloads:
            for inc in p.get("incidents") or []:
                iid = inc.get("id")
                if not iid or iid in seen:
                    continue
                seen.add(iid)
                out.append({**inc, "member": identity})
        out.sort(key=lambda i: -float(i.get("opened_at") or 0.0))
        return out[:limit]

    def fleet_payload(self) -> Dict[str, Any]:
        """The ``GET /debug/fleet`` body."""
        self._refresh_if_stale()
        roots, _ = self.stitched()
        index = []
        worst = None
        worst_ms = -1.0
        for root in roots:
            stitched_members = sorted({
                s.get("member") for s in _walk(root) if s.get("member")
            } - {None})
            has_join = any(s.get("stitched") for s in _walk(root))
            dur = float(root.get("duration_ms") or 0.0)
            index.append({
                "trace_id": root.get("trace_id"),
                "name": root.get("name"),
                "duration_ms": dur,
                "members": stitched_members,
                "stitched": has_join,
            })
            if has_join and dur > worst_ms:
                worst_ms = dur
                worst = root
        index.sort(key=lambda e: -e["duration_ms"])
        out: Dict[str, Any] = {
            "members": self.members(),
            "slo": self.fleet_slo(),
            "decisions": self.fleet_decisions(),
            "incidents": self.fleet_incidents(),
            "traces": {
                "roots": len(roots),
                "stitched": sum(1 for e in index if e["stitched"]),
                "index": index[:50],
            },
        }
        if worst is not None:
            from karpenter_tpu.obs.export import critical_path

            out["worst_stitched"] = {
                "trace_id": worst.get("trace_id"),
                "duration_ms": worst_ms,
                "critical_path": critical_path(worst),
                "wire": wire_attribution(worst),
            }
        return out


class TelemetryPlane:
    """One process's telemetry wiring: the periodic flusher (when a
    publishing backend is configured) plus the collector. Installed via
    ``obs.configure_telemetry``; ``Runtime.stop`` / sidecar shutdown call
    :meth:`stop`."""

    def __init__(
        self,
        identity: str,
        role: str = "controller",
        directory: str = "",
        peers: Sequence[str] = (),
        flush_interval: float = DEFAULT_FLUSH_INTERVAL_S,
        clock: Callable[[], float] = time.time,
    ):
        if flush_interval <= 0:
            raise ValueError("telemetry flush interval must be positive seconds")
        self.identity = identity
        self.role = role
        self.flush_interval = flush_interval
        self._file_backend = (
            FileTelemetryBackend(directory, identity=identity) if directory else None
        )
        backends: List[Any] = []
        if self._file_backend is not None:
            backends.append(self._file_backend)
        if peers:
            backends.append(HttpTelemetryBackend(peers))
        self.collector = TelemetryCollector(
            backends,
            flush_interval=flush_interval,
            clock=clock,
            # the collector's own ring rides along even when this process
            # publishes nowhere (pure pull mode)
            extra_trees=self._local_trees,
        )
        self.flushes = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _local_trees(self) -> List[Dict[str, Any]]:
        from karpenter_tpu import obs

        trees = obs.exporter().snapshot(limit=FLUSH_TREE_LIMIT, newest_first=True)
        for t in trees:
            t["member"] = self.identity
        return trees

    def flush(self) -> None:
        """Publish this process's payload now (the loop's body; tests and
        shutdown call it directly)."""
        if self._file_backend is None:
            return
        try:
            self._file_backend.publish(member_payload(self.identity, self.role))
            self.flushes += 1
            try:
                from karpenter_tpu import metrics

                metrics.TELEMETRY_FLUSHES.inc()
            except Exception:
                pass
        except Exception:
            logger.debug("telemetry flush failed", exc_info=True)

    def start(self) -> "TelemetryPlane":
        if self._thread is not None or self._file_backend is None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="obs-telemetry-flush", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
        # one final flush so a clean shutdown's last window isn't lost
        self.flush()

    def fleet_payload(self) -> Dict[str, Any]:
        return self.collector.fleet_payload()
