"""TTL cache (the go-cache analog the reference uses for preference
relaxation and cloud-provider catalog caching).

Expiry is computed against an injectable clock so tests can fast-forward.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


class TTLCache:
    def __init__(self, ttl: float, clock: Optional[Callable[[], float]] = None):
        self.ttl = ttl
        self.clock = clock or time.time
        self._lock = threading.Lock()
        self._items: Dict[Any, Tuple[float, Any]] = {}  # key -> (expiry, value); guarded-by: self._lock

    def get(self, key) -> Optional[Any]:
        now = self.clock()
        with self._lock:
            entry = self._items.get(key)
            if entry is None:
                return None
            expiry, value = entry
            if now >= expiry:
                del self._items[key]
                return None
            return value

    def set(self, key, value, ttl: Optional[float] = None) -> None:
        with self._lock:
            self._items[key] = (self.clock() + (ttl if ttl is not None else self.ttl), value)

    def delete(self, key) -> None:
        with self._lock:
            self._items.pop(key, None)

    def get_or_compute(self, key, compute: Callable[[], Any]) -> Any:
        cached = self.get(key)
        if cached is not None:
            return cached
        value = compute()
        self.set(key, value)
        return value

    def keys(self):
        now = self.clock()
        with self._lock:
            return [k for k, (exp, _) in self._items.items() if now < exp]

    def clear(self) -> None:
        with self._lock:
            self._items.clear()
