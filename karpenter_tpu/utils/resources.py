"""Resource-quantity parsing and ResourceList arithmetic.

Mirrors the semantics of ``pkg/utils/resources/resources.go`` (RequestsForPods
sums container requests and adds a ``pods`` count; ``fits`` is an elementwise
<=) but stores quantities as floats, and provides the fixed-order vector
encoding the TPU solver consumes: every ResourceList maps onto a float32
vector with one slot per supported resource dimension.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Mapping, Optional, Sequence

import numpy as np

# Canonical resource names (match kubernetes resource names).
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"
NVIDIA_GPU = "nvidia.com/gpu"
AMD_GPU = "amd.com/gpu"
AWS_NEURON = "aws.amazon.com/neuron"
AWS_POD_ENI = "vpc.amazonaws.com/pod-eni"

# The fixed dimension order for the solver's dense encoding. Keep CPU and
# MEMORY first: the FFD sort key is (cpu desc, memory desc)
# (reference: scheduler.go:116-137).
RESOURCE_AXES: List[str] = [
    CPU,
    MEMORY,
    PODS,
    EPHEMERAL_STORAGE,
    NVIDIA_GPU,
    AMD_GPU,
    AWS_NEURON,
    AWS_POD_ENI,
]
AXIS_INDEX = {name: i for i, name in enumerate(RESOURCE_AXES)}
NUM_RESOURCE_AXES = len(RESOURCE_AXES)

ResourceList = Dict[str, float]

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][+-]?\d+)?)(?P<suffix>(?:[KMGTPE]i?|[mkun])?)$"
)

_SUFFIX_MULTIPLIERS = {
    "": 1.0,
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "k": 1e3,
    "K": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
    "Ki": 2.0**10,
    "Mi": 2.0**20,
    "Gi": 2.0**30,
    "Ti": 2.0**40,
    "Pi": 2.0**50,
    "Ei": 2.0**60,
}


def parse_quantity(value) -> float:
    """Parse a kubernetes-style quantity ('100m', '2Gi', 1.5) into a float."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"cannot parse quantity {value!r}")
    num = float(m.group("num"))
    if m.group("sign") == "-":
        num = -num
    return num * _SUFFIX_MULTIPLIERS[m.group("suffix")]


def parse_resource_list(raw: Optional[Mapping[str, object]]) -> ResourceList:
    return {k: parse_quantity(v) for k, v in (raw or {}).items()}


def merge(*lists: Mapping[str, float]) -> ResourceList:
    """Sum resource lists key-wise (reference: resources.go:51-64)."""
    out: ResourceList = {}
    for rl in lists:
        for name, qty in rl.items():
            out[name] = out.get(name, 0.0) + qty
    return out


def fits(candidate: Mapping[str, float], total: Mapping[str, float]) -> bool:
    """Candidate fits iff every requested quantity <= total's (missing keys
    in total count as zero; reference: resources.go:83-90). Comparison is in
    integer milli-units, matching Go's resource.Quantity exact arithmetic —
    float drift from summing parsed quantities (e.g. 0.1+0.25 > 0.35 in
    binary) must not flip a fit decision."""
    return all(
        round(qty * 1000.0) <= round(total.get(name, 0.0) * 1000.0)
        for name, qty in candidate.items()
    )


def requests_for_pods(*pods) -> ResourceList:
    """Total requests of the pods plus a `pods` count
    (reference: resources.go:25-35).

    The single-pod case is memoized on the pod object (keyed by the identity
    of its containers list, which scheduling never mutates): a 10k-pod solve
    calls this twice per pod (FFD sort + encode) and the repeated merges were
    a top-3 profile entry."""
    if len(pods) == 1:
        pod = pods[0]
        containers = pod.spec.containers
        cached = getattr(pod, "_requests_memo", None)
        if cached is not None and cached[0] is containers:
            return dict(cached[1])
        out = merge(*(c.requests for c in containers))
        out[PODS] = out.get(PODS, 0.0) + 1.0
        try:
            pod._requests_memo = (containers, dict(out))
        except AttributeError:
            pass  # slotted/frozen pod types just skip the memo
        return out
    out = merge(*(p.resource_requests() for p in pods))
    out[PODS] = out.get(PODS, 0.0) + float(len(pods))
    return out


def limits_for_pods(*pods) -> ResourceList:
    out = merge(*(p.resource_limits() for p in pods))
    out[PODS] = out.get(PODS, 0.0) + float(len(pods))
    return out


def cmp_quantity(lhs: float, rhs: float) -> int:
    if lhs < rhs:
        return -1
    if lhs > rhs:
        return 1
    return 0


def to_string(rl: Mapping[str, float]) -> str:
    if not rl:
        return "{}"
    return "{" + ", ".join(f"{k}: {rl[k]:g}" for k in sorted(rl)) + "}"


# -- dense encoding for the solver ----------------------------------------

# Per-axis scale factors chosen so realistic quantities become integers that
# float32 represents exactly (mantissa 2^24): cpu in milli-cores, memory and
# ephemeral storage in Mi, counts as-is, extended resources in milli. The
# solver's granularity contract: quantities milli-cpu / Mi-memory granular
# compare exactly; sub-Mi memory differences are quantized on device.
AXIS_SCALES = {
    CPU: 1000.0,
    MEMORY: 1.0 / (2.0**20),
    PODS: 1.0,
    EPHEMERAL_STORAGE: 1.0 / (2.0**20),
}
_DEFAULT_SCALE = 1000.0


def axis_scales(extra_axes: Sequence[str] = ()) -> np.ndarray:
    scales = [AXIS_SCALES.get(name, _DEFAULT_SCALE) for name in RESOURCE_AXES]
    scales += [_DEFAULT_SCALE] * len(extra_axes)
    return np.array(scales, dtype=np.float64)


def to_scaled_vector(rl: Mapping[str, float], extra_axes: Sequence[str] = ()) -> np.ndarray:
    """Encode for device arithmetic: scaled per AXIS_SCALES and rounded to
    integers so float32 sums and compares stay exact."""
    vec = to_vector(rl, extra_axes).astype(np.float64) * axis_scales(extra_axes)
    return np.rint(vec).astype(np.float32)


def to_vector(rl: Mapping[str, float], extra_axes: Sequence[str] = ()) -> np.ndarray:
    """Encode a ResourceList as a float32 vector in RESOURCE_AXES order,
    optionally extended with per-solve extra resource names.

    Unknown resource names without a reserved or extra axis raise, so a solve
    can never silently drop a constraint dimension.
    """
    n = NUM_RESOURCE_AXES + len(extra_axes)
    vec = np.zeros((n,), dtype=np.float32)
    extra_index = {name: NUM_RESOURCE_AXES + i for i, name in enumerate(extra_axes)}
    for name, qty in rl.items():
        if name in AXIS_INDEX:
            vec[AXIS_INDEX[name]] = qty
        elif name in extra_index:
            vec[extra_index[name]] = qty
        else:
            raise KeyError(f"resource {name!r} has no encoding axis")
    return vec


def collect_extra_axes(lists: Iterable[Mapping[str, float]]) -> List[str]:
    """Discover resource names outside the reserved axes, in sorted order, so
    a solve's vector layout is deterministic."""
    extras = set()
    for rl in lists:
        for name in rl:
            if name not in AXIS_INDEX:
                extras.add(name)
    return sorted(extras)
