"""Pod predicates (reference: pkg/utils/pod/scheduling.go)."""

from __future__ import annotations

from karpenter_tpu.api.objects import Pod


def failed_to_schedule(pod: Pod) -> bool:
    return any(
        c.type == "PodScheduled" and c.reason == "Unschedulable" for c in pod.status.conditions
    )


def is_scheduled(pod: Pod) -> bool:
    return pod.spec.node_name != ""


def is_preempting(pod: Pod) -> bool:
    return pod.status.nominated_node_name != ""


def is_terminal(pod: Pod) -> bool:
    return pod.status.phase in ("Failed", "Succeeded")


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_owned_by_daemonset(pod: Pod) -> bool:
    return any(
        o.api_version == "apps/v1" and o.kind == "DaemonSet" for o in pod.metadata.owner_references
    )


def is_owned_by_node(pod: Pod) -> bool:
    """Static pods are owned by their node."""
    return any(o.api_version == "v1" and o.kind == "Node" for o in pod.metadata.owner_references)


def is_provisionable(pod: Pod) -> bool:
    """Unscheduled, not preempting, marked unschedulable, and not a
    daemonset/static pod (reference: selection/controller.go:117-123; the
    provisioning worker re-checks it between enqueue and solve,
    provisioner.go:121-134)."""
    return (
        not is_scheduled(pod)
        and not is_preempting(pod)
        and failed_to_schedule(pod)
        and not is_owned_by_daemonset(pod)
        and not is_owned_by_node(pod)
    )


def has_required_pod_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return aff is not None and aff.pod_affinity is not None and bool(aff.pod_affinity.required)


def has_required_pod_anti_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return (
        aff is not None and aff.pod_anti_affinity is not None and bool(aff.pod_anti_affinity.required)
    )
