"""Pod predicates (reference: pkg/utils/pod/scheduling.go)."""

from __future__ import annotations

from karpenter_tpu.api.objects import Pod


def failed_to_schedule(pod: Pod) -> bool:
    return any(
        c.type == "PodScheduled" and c.reason == "Unschedulable" for c in pod.status.conditions
    )


def is_scheduled(pod: Pod) -> bool:
    return pod.spec.node_name != ""


def is_preempting(pod: Pod) -> bool:
    return pod.status.nominated_node_name != ""


def is_terminal(pod: Pod) -> bool:
    return pod.status.phase in ("Failed", "Succeeded")


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_owned_by_daemonset(pod: Pod) -> bool:
    return any(
        o.api_version == "apps/v1" and o.kind == "DaemonSet" for o in pod.metadata.owner_references
    )


def is_owned_by_node(pod: Pod) -> bool:
    """Static pods are owned by their node."""
    return any(o.api_version == "v1" and o.kind == "Node" for o in pod.metadata.owner_references)


def is_provisionable(pod: Pod) -> bool:
    """Unscheduled, not preempting, marked unschedulable, and not a
    daemonset/static pod (reference: selection/controller.go:117-123; the
    provisioning worker re-checks it between enqueue and solve,
    provisioner.go:121-134)."""
    return (
        not is_scheduled(pod)
        and not is_preempting(pod)
        and failed_to_schedule(pod)
        and not is_owned_by_daemonset(pod)
        and not is_owned_by_node(pod)
    )


WILDCARD_HOST_IP = "0.0.0.0"


def host_ports(pod: Pod):
    """The (hostIP, hostPort, protocol) triples the pod claims on its node.
    Conflicting claims cannot co-locate (the reference left this unenforced —
    suite_test.go:1758 is skipped 'enable after scheduler is aware of
    hostport usage'; this framework enforces it).

    Memoized on the pod (containers are never mutated by scheduling) — this
    runs for every pod of every solve."""
    containers = pod.spec.containers
    cached = getattr(pod, "_host_ports_memo", None)
    if cached is not None and cached[0] is containers:
        return set(cached[1])
    out = set()
    for container in containers:
        for port in container.ports:
            if port.host_port:
                out.add((port.host_ip or WILDCARD_HOST_IP, port.host_port, port.protocol or "TCP"))
    try:
        pod._host_ports_memo = (containers, frozenset(out))
    except AttributeError:
        pass
    return out


def host_ports_conflict(a, b) -> bool:
    """Kubelet semantics: same (port, protocol) conflicts when either side
    binds the wildcard IP or the IPs are equal."""
    for ip_a, port_a, proto_a in a:
        for ip_b, port_b, proto_b in b:
            if port_a != port_b or proto_a != proto_b:
                continue
            if ip_a == WILDCARD_HOST_IP or ip_b == WILDCARD_HOST_IP or ip_a == ip_b:
                return True
    return False


def has_required_pod_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return aff is not None and aff.pod_affinity is not None and bool(aff.pod_affinity.required)


def has_required_pod_anti_affinity(pod: Pod) -> bool:
    aff = pod.spec.affinity
    return (
        aff is not None and aff.pod_anti_affinity is not None and bool(aff.pod_anti_affinity.required)
    )


# Priority classing for overload decisions (docs/overload.md): without a
# PriorityClass store to resolve real values, the class NAME maps to a
# coarse ordinal — enough to decide what the batcher sheds first. System
# classes outrank everything; an unnamed class is the default tier; names
# starting "low"/"best-effort" opt workloads into shed-first.
_PRIORITY_BY_CLASS = {
    "system-node-critical": 100,
    "system-cluster-critical": 90,
}


def priority_of(pod: Pod) -> int:
    """Coarse priority ordinal for shed ordering (higher = keep longer)."""
    name = pod.spec.priority_class_name or ""
    if name in _PRIORITY_BY_CLASS:
        return _PRIORITY_BY_CLASS[name]
    if name.startswith("high"):
        return 10
    if name.startswith(("low", "best-effort")):
        return -10
    return 0
