"""Rate-limited, deduplicating work queues.

The controller-runtime/client-go workqueue analog (reference: the reconciler
plumbing in ``pkg/controllers/manager.go`` and the rate limiters in
``termination/controller.go:104-113`` and ``utils/parallel/workqueue.go``):

- ``RateLimitingQueue``: dedups keys while queued, supports delayed adds, and
  applies per-item exponential backoff on ``add_rate_limited``.
- ``TokenBucket``: QPS/burst limiter (client-side flow control, e.g. the kube
  client's 200 QPS/300 burst or CreateFleet's 2 QPS/100 burst).
"""

from __future__ import annotations

import heapq
import threading
from collections import deque
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple


class TokenBucket:
    """QPS/burst token bucket; ``take`` blocks until a token is available."""

    def __init__(self, qps: float, burst: int, clock: Optional[Callable[[], float]] = None):
        self.qps = qps
        self.burst = burst
        self.clock = clock or time.monotonic
        self._tokens = float(burst)  # guarded-by: self._lock
        self._last = self.clock()  # guarded-by: self._lock
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = self.clock()
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
        self._last = now

    def try_take(self) -> bool:
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1:
                self._tokens -= 1
                return True
            return False

    def wait_time(self) -> float:
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1:
                return 0.0
            return (1 - self._tokens) / self.qps

    def take(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else self.clock() + timeout
        while True:
            if self.try_take():
                return True
            wait = self.wait_time()
            if deadline is not None:
                remaining = deadline - self.clock()
                if remaining <= 0:
                    return False
                wait = min(wait, remaining)
            time.sleep(max(wait, 0.001))


class ExponentialBackoff:
    """Per-item exponential failure backoff (client-go's
    ItemExponentialFailureRateLimiter analog)."""

    def __init__(self, base: float = 0.005, cap: float = 1000.0):
        self.base = base
        self.cap = cap
        self._failures: Dict[Any, int] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()

    def when(self, item) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base * (2**n), self.cap)

    def forget(self, item) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def retries(self, item) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class ShutDown(Exception):
    pass


class RateLimitingQueue:
    """Dedup queue with delayed adds and exponential retry backoff.

    Semantics match client-go: an item present in the queue is not added
    again; an item being processed and re-added is requeued after processing
    finishes (``done`` re-adds it).
    """

    def __init__(self, backoff: Optional[ExponentialBackoff] = None):
        self.backoff = backoff or ExponentialBackoff()
        self._lock = threading.Condition()
        self._queue: deque = deque()  # guarded-by: self._lock
        self._queued: Set[Any] = set()  # guarded-by: self._lock
        self._processing: Set[Any] = set()  # guarded-by: self._lock
        self._dirty: Set[Any] = set()  # re-added while processing; guarded-by: self._lock
        self._delayed: List[Tuple[float, int, Any]] = []  # (ready_at, seq, item) heap; guarded-by: self._lock
        self._seq = 0  # guarded-by: self._lock
        self._shutdown = False  # guarded-by: self._lock

    def add(self, item) -> None:
        with self._lock:
            if self._shutdown or item in self._queued:
                return
            if item in self._processing:
                self._dirty.add(item)
                return
            self._queued.add(item)
            self._queue.append(item)
            self._lock.notify()

    def add_after(self, item, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._lock:
            self._seq += 1
            heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, item))
            self._lock.notify()

    def add_rate_limited(self, item) -> None:
        self.add_after(item, self.backoff.when(item))

    def forget(self, item) -> None:
        self.backoff.forget(item)

    def _pump_delayed_locked(self) -> Optional[float]:
        """Move ready delayed items into the queue; returns seconds until the
        next delayed item (None if no delayed items)."""
        now = time.monotonic()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            if item not in self._queued and item not in self._processing:
                self._queued.add(item)
                self._queue.append(item)
            elif item in self._processing:
                self._dirty.add(item)
        if self._delayed:
            return max(self._delayed[0][0] - now, 0.001)
        return None

    def get(self, timeout: Optional[float] = None):
        """Block for the next item; raises ShutDown when stopped and drained."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                next_delay = self._pump_delayed_locked()
                if self._queue:
                    item = self._queue.popleft()
                    self._queued.discard(item)
                    self._processing.add(item)
                    return item
                if self._shutdown:
                    raise ShutDown()
                wait = next_delay
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._lock.wait(wait)

    def done(self, item) -> None:
        with self._lock:
            self._processing.discard(item)
            if item in self._dirty:
                self._dirty.discard(item)
                if item not in self._queued:
                    self._queued.add(item)
                    self._queue.append(item)
                    self._lock.notify()

    def shut_down(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()

    def is_shut_down(self) -> bool:
        with self._lock:
            return self._shutdown

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)
