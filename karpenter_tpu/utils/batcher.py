"""Windowed batcher (reference: pkg/controllers/provisioning/batcher.go).

Separates a stream of ``add(item)`` calls into windowed slices: the window
starts on the first item, closes after 1s idle or 10s max or 2,000 items.
Callers block on a gate that flushes when their batch has been processed.

Overload posture (docs/overload.md): the queue is BOUNDED. Past
``max_depth`` the batcher decides what to drop instead of growing without
limit — a full-queue add sheds the oldest entry of the lowest priority
class present (``karpenter_batcher_shed_total{reason="queue_full"}`` + the
``on_shed`` hook, which provisioning turns into a Warning event). The
brownout ladder additionally drives two knobs: ``set_pressure`` scales the
admission window down so saturated rounds stay small and frequent, and
``shed_low_priority`` drains queued below-floor work outright
(``reason="brownout"``).
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from typing import Callable, Deque, List, Optional, Tuple

MAX_BATCH_DURATION = 10.0
BATCH_IDLE_DURATION = 1.0
MAX_ITEMS_PER_BATCH = 2000
# queue bound: 5x the largest batch — deep enough that a burst spanning a
# few windows never sheds, shallow enough that a sustained overload sheds
# instead of hoarding hours of stale work (the queue IS the latency)
MAX_QUEUE_DEPTH = 10_000

# bounded-wait slice for the first-item park: stop() notifies the
# condition, the timeout only bounds a missed wakeup
_PARK_SLICE_S = 0.5


class Batcher:
    def __init__(
        self,
        max_duration: float = MAX_BATCH_DURATION,
        idle_duration: float = BATCH_IDLE_DURATION,
        max_items: int = MAX_ITEMS_PER_BATCH,
        max_depth: int = MAX_QUEUE_DEPTH,
        priority_fn: Optional[Callable[[object], int]] = None,
        on_shed: Optional[Callable[[object, str], None]] = None,
    ):
        self.max_duration = max_duration
        self.idle_duration = idle_duration
        self.max_items = max_items
        self.max_depth = max(int(max_depth), 1)
        # item -> priority class (higher = more important); the default
        # treats everything equally, so queue_full sheds pure-oldest
        self._priority = priority_fn or (lambda item: 0)
        # fire-and-forget shed notification (item, reason) — runs OFF the
        # queue lock; a raising hook loses its event, never the batch
        self._on_shed = on_shed
        self._cv = threading.Condition()
        # (priority, item) pairs — the class is computed ONCE at enqueue
        # (pod priority is immutable while queued), so a full-queue shed
        # never re-runs priority_fn over the whole queue under the lock
        self._items: Deque = deque()  # guarded-by: self._cv
        self._pri_counts: Counter = Counter()  # guarded-by: self._cv
        self._pressure = 1.0  # guarded-by: self._cv
        self.max_depth_seen = 0  # guarded-by: self._cv
        self.shed_total = 0  # guarded-by: self._cv
        self._gate = threading.Event()  # guarded-by: self._gate_lock
        self._gate_lock = threading.Lock()
        self._stopped = False  # guarded-by: self._gate_lock

    # -- admission -----------------------------------------------------------

    def add(self, item) -> threading.Event:
        """Enqueue an item; returns the gate event the caller may wait on —
        it is set when the batch containing the item has been processed
        (reference: batcher.go:61-69). After stop() the returned gate is
        pre-set: no flush will ever run again, and a caller handed the
        live gate would park on it for its full wait timeout.

        A full queue sheds rather than grows: the oldest entry of the
        lowest priority class present is dropped (the incoming item itself
        when it is strictly the least important) — under overload the
        queue keeps the newest, most important work."""
        shed = None
        with self._gate_lock:
            if self._stopped:
                done = threading.Event()
                done.set()
                return done
        pri = self._safe_priority(item)
        with self._cv:
            enqueue = True
            if len(self._items) >= self.max_depth:
                shed, enqueue = self._pick_shed_locked(pri, item)
                self.shed_total += 1
            if enqueue:
                self._items.append((pri, item))
                self._pri_counts[pri] += 1
            self.max_depth_seen = max(self.max_depth_seen, len(self._items))
            self._cv.notify()
        if shed is not None:
            self._notify_shed(shed, "queue_full")
        with self._gate_lock:
            if self._stopped:
                done = threading.Event()
                done.set()
                return done
            return self._gate
    # NOTE on the shed gate: the displaced item's caller still holds the
    # live gate; provision_once flushes it every round, so nobody parks
    # forever on shed work — the on_shed hook is where pending-state
    # cleanup and the Warning event happen.

    def _safe_priority(self, item) -> int:
        try:
            return int(self._priority(item))
        except Exception:
            return 0

    def _pick_shed_locked(self, incoming_pri: int, incoming) -> Tuple[object, bool]:
        """Full queue: choose the victim. Returns (victim, enqueue_incoming).
        The victim is the OLDEST entry among the lowest priority class in
        (queue + incoming); ties between a queued item and the incoming one
        shed the queued item (it is older). The class census makes the
        lowest-class lookup O(#classes); the scan for its oldest member
        stops at the first hit — under a homogeneous overload (the common
        storm) that is the queue head."""
        lowest_queued = min(self._pri_counts) if self._pri_counts else None
        if lowest_queued is None or incoming_pri < lowest_queued:
            # the incoming item is strictly the least important thing here
            return incoming, False
        for i, (pri, queued) in enumerate(self._items):
            if pri == lowest_queued:
                del self._items[i]
                self._decr_pri_locked(pri)
                return queued, True
        # unreachable: the census said the class has members
        return incoming, False

    def _decr_pri_locked(self, pri: int) -> None:
        self._pri_counts[pri] -= 1
        if self._pri_counts[pri] <= 0:
            del self._pri_counts[pri]

    def _notify_shed(self, item, reason: str) -> None:
        from karpenter_tpu import metrics

        try:
            metrics.BATCHER_SHED.labels(reason=reason).inc()
        except Exception:
            pass  # trimmed registries (sidecar test rigs)
        if self._on_shed is not None:
            try:
                self._on_shed(item, reason)
            except Exception:
                pass  # a raising hook must never fail the add

    # -- brownout knobs ------------------------------------------------------

    def set_pressure(self, scale: float) -> None:
        """Scale the admission window: ``scale`` < 1 shrinks the idle/max
        durations and the per-batch item cap, so an overloaded system runs
        small frequent rounds instead of giant stale ones. 1.0 restores
        the configured window (the brownout controller re-applies the
        current level every tick, so new batchers converge within one)."""
        with self._cv:
            self._pressure = min(max(float(scale), 0.01), 1.0)

    def pressure(self) -> float:
        with self._cv:
            return self._pressure

    def shed_low_priority(self, floor: int) -> int:
        """Drain queued items whose priority class is below ``floor``
        (oldest first, by construction of the queue). The brownout
        ladder's shed rung; returns how many were dropped."""
        with self._cv:
            keep: Deque = deque()
            shed: List = []
            for pri, item in self._items:
                if pri < floor:
                    shed.append(item)
                    self._decr_pri_locked(pri)
                else:
                    keep.append((pri, item))
            self._items = keep
            self.shed_total += len(shed)
        for item in shed:
            self._notify_shed(item, "brownout")
        return len(shed)

    def _popleft_locked(self):
        pri, item = self._items.popleft()
        self._decr_pri_locked(pri)
        return item

    def depth(self) -> int:
        with self._cv:
            return len(self._items)

    # -- lifecycle -----------------------------------------------------------

    def flush(self) -> None:
        """Release all waiters and open a new gate
        (reference: batcher.go:72-77)."""
        with self._gate_lock:
            old = self._gate
            self._gate = threading.Event()
        old.set()

    def stop(self) -> None:
        # under the gate lock, paired with add()'s check: once _stopped is
        # visible, add() hands out pre-set gates, and the flush() below
        # releases everyone already parked on the live gate — no waiter is
        # ever left on a gate that no flush will set again
        with self._gate_lock:
            self._stopped = True
        with self._cv:
            self._cv.notify_all()  # wake the wait() parked on the queue
        self.flush()

    def wait(self) -> Tuple[List, float]:
        """Block for the first item, then collect until idle/max-duration/
        max-items; returns (items, window) (reference: batcher.go:80-103).
        All parks are bounded (stop() notifies; the slice only covers a
        missed wakeup), and the window dimensions are scaled by the
        current brownout pressure."""
        items: List = []
        with self._cv:
            while not self._items:
                if self._stopped:
                    return [], 0.0
                self._cv.wait(_PARK_SLICE_S)
            if self._stopped:
                return [], 0.0
            scale = self._pressure
            items.append(self._popleft_locked())
            start = time.monotonic()
            idle = max(self.idle_duration * scale, 0.001)
            deadline = start + max(self.max_duration * scale, 0.001)
            cap = max(int(self.max_items * scale), 1)
            idle_deadline = time.monotonic() + idle
            while len(items) < cap and not self._stopped:
                if self._items:
                    items.append(self._popleft_locked())
                    idle_deadline = time.monotonic() + idle
                    continue
                timeout = min(idle_deadline, deadline) - time.monotonic()
                if timeout <= 0:
                    break
                self._cv.wait(timeout)
            return items, time.monotonic() - start
