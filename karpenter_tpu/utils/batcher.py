"""Windowed batcher (reference: pkg/controllers/provisioning/batcher.go).

Separates a stream of ``add(item)`` calls into windowed slices: the window
starts on the first item, closes after 1s idle or 10s max or 2,000 items.
Callers block on a gate that flushes when their batch has been processed.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional, Tuple

MAX_BATCH_DURATION = 10.0
BATCH_IDLE_DURATION = 1.0
MAX_ITEMS_PER_BATCH = 2000


class Batcher:
    def __init__(
        self,
        max_duration: float = MAX_BATCH_DURATION,
        idle_duration: float = BATCH_IDLE_DURATION,
        max_items: int = MAX_ITEMS_PER_BATCH,
    ):
        self.max_duration = max_duration
        self.idle_duration = idle_duration
        self.max_items = max_items
        self._queue: "queue.Queue" = queue.Queue()
        self._gate = threading.Event()  # guarded-by: self._gate_lock
        self._gate_lock = threading.Lock()
        self._stopped = False  # guarded-by: self._gate_lock

    def add(self, item) -> threading.Event:
        """Enqueue an item; returns the gate event the caller may wait on —
        it is set when the batch containing the item has been processed
        (reference: batcher.go:61-69). After stop() the returned gate is
        pre-set: no flush will ever run again, and a caller handed the
        live gate would park on it for its full wait timeout."""
        self._queue.put(item)
        with self._gate_lock:
            if self._stopped:
                done = threading.Event()
                done.set()
                return done
            return self._gate

    def flush(self) -> None:
        """Release all waiters and open a new gate
        (reference: batcher.go:72-77)."""
        with self._gate_lock:
            old = self._gate
            self._gate = threading.Event()
        old.set()

    def stop(self) -> None:
        # under the gate lock, paired with add()'s check: once _stopped is
        # visible, add() hands out pre-set gates, and the flush() below
        # releases everyone already parked on the live gate — no waiter is
        # ever left on a gate that no flush will set again
        with self._gate_lock:
            self._stopped = True
        self._queue.put(None)  # wake the waiter
        self.flush()

    def wait(self) -> Tuple[List, float]:
        """Block for the first item, then collect until idle/max-duration/
        max-items; returns (items, window) (reference: batcher.go:80-103)."""
        items: List = []
        first = self._queue.get()
        if first is None or self._stopped:
            return [], 0.0
        items.append(first)
        start = time.monotonic()
        deadline = start + self.max_duration
        while len(items) < self.max_items:
            now = time.monotonic()
            timeout = min(self.idle_duration, deadline - now)
            if timeout <= 0:
                break
            try:
                item = self._queue.get(timeout=timeout)
            except queue.Empty:
                break
            if item is None or self._stopped:
                break
            items.append(item)
        return items, time.monotonic() - start
