"""Controller-process GC policy.

A 10k-pod solve allocates hundreds of thousands of short-lived objects, all
freed by refcounting (the solve structures are acyclic) — yet every
allocation burst trips the cyclic collector, whose gen-2 passes scan the
whole warm heap (JAX, the catalog, the signature tables) for 100-200ms.
Those pauses land squarely in the solve-latency tail: the p90/p99 of the
latency benchmark showed 200ms host spikes that disappear entirely under
this policy.

``freeze_after_warmup`` is the Instagram/CPython-documented recipe: collect
once, ``gc.freeze()`` the warm heap into the permanent generation so later
collections never scan it, and raise the gen-0 threshold so collections are
rare. Cycles created afterwards are still collected — just less often and
against a small young heap.

Call it once, AFTER the warm heap actually exists — i.e. after the first
solve has compiled (the benchmark freezes after its warmup solve; the
runtime freezes when the first provisioning worker reports warmed).
``restore`` undoes the policy (tests that boot a runtime in-process must
not leak a frozen heap into the rest of the session).
"""

from __future__ import annotations

import gc
import threading

_lock = threading.Lock()
_frozen = False  # guarded-by: _lock
_saved_thresholds = None  # guarded-by: _lock


def freeze_after_warmup(gen0_threshold: int = 50000, unless=None) -> None:
    """``unless`` is an optional threading.Event checked INSIDE the lock:
    a canceller that sets the event and then calls ``restore`` can never
    lose to a freeze landing between its two steps (the check-then-freeze
    race the runtime's stop path must not have)."""
    global _frozen, _saved_thresholds
    with _lock:
        if _frozen or (unless is not None and unless.is_set()):
            return
        _saved_thresholds = gc.get_threshold()
        gc.collect()
        gc.freeze()
        gc.set_threshold(gen0_threshold, 20, 20)
        _frozen = True


def restore() -> None:
    """Unfreeze the permanent generation and restore the default
    thresholds (idempotent)."""
    global _frozen, _saved_thresholds
    with _lock:
        if not _frozen:
            return
        gc.unfreeze()
        if _saved_thresholds is not None:
            gc.set_threshold(*_saved_thresholds)
        _frozen = False
