"""Leader election via a lease — the active/passive single-writer hook
(reference: cmd/controller/main.go:84-85 ``karpenter-leader-election``).

The in-memory deployment has one process, so the default lease is in-process;
multi-process deployments back it with a shared file (one machine) or swap in
a real coordination.k8s.io/Lease client. The contract is small: acquire
(non-blocking), renew on a heartbeat, release on shutdown; holders that stop
renewing lose the lease after the duration elapses.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import logging
import os
import threading
import time
import uuid
from typing import Callable, Optional

logger = logging.getLogger("karpenter.lease")

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_INTERVAL = 5.0


class FileLease:
    """Advisory lease in a shared file: {holder, expiry}. Atomic via
    write-to-temp + rename; stale leases are taken over after expiry."""

    def __init__(
        self,
        path: str,
        identity: Optional[str] = None,
        duration: float = DEFAULT_LEASE_DURATION,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.path = path
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.duration = duration
        self.clock = clock or time.time

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def _write(self, record: dict) -> None:
        tmp = f"{self.path}.{self.identity}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, self.path)

    @contextlib.contextmanager
    def _locked(self):
        """flock-serialized critical section: acquire/renew are
        read-modify-write, and two racers interleaving around the atomic
        rename could BOTH conclude they hold the lease (split brain)."""
        lock_path = f"{self.path}.flock"
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def try_acquire(self) -> bool:
        with self._locked():
            now = self.clock()
            current = self._read()
            if current and current["holder"] != self.identity and current["expiry"] > now:
                return False
            self._write({"holder": self.identity, "expiry": now + self.duration})
            return True

    def renew(self) -> bool:
        with self._locked():
            now = self.clock()
            current = self._read()
            if (
                not current
                or current["holder"] != self.identity
                or current["expiry"] <= now  # expired: takeover may have won
            ):
                return False
            self._write({"holder": self.identity, "expiry": now + self.duration})
            return True

    def release(self) -> None:
        with self._locked():
            current = self._read()
            if current and current["holder"] == self.identity:
                try:
                    os.remove(self.path)
                except FileNotFoundError:
                    pass

    def holder(self) -> Optional[str]:
        current = self._read()
        if current and current["expiry"] > self.clock():
            return current["holder"]
        return None


class LeaderElector:
    """Blocks followers until leadership is acquired, then renews on a
    heartbeat; ``is_leader`` flips false if renewal fails (lost lease) and
    the ``on_lost`` callback fires — a second active leader must never keep
    mutating cloud state (the reference exits the process on lost lease)."""

    def __init__(
        self,
        lease: FileLease,
        renew_interval: float = DEFAULT_RENEW_INTERVAL,
        on_lost: Optional[Callable[[], None]] = None,
    ):
        self.lease = lease
        self.renew_interval = renew_interval
        self.on_lost = on_lost
        self._leader = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True, name="leader-elector")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self._leader.is_set():
                    if not self.lease.renew():
                        self._leader.clear()
                        if self.on_lost is not None:
                            self.on_lost()
                elif self.lease.try_acquire():
                    self._leader.set()
            except Exception:
                # a lease backend that raises must not kill the elector
                # thread: a dead elector with is_leader stuck True is the
                # split-brain case election exists to prevent
                logger.exception("lease operation failed")
                if self._leader.is_set():
                    self._leader.clear()
                    if self.on_lost is not None:
                        self.on_lost()
            self._stop.wait(self.renew_interval)

    def wait_for_leadership(self, timeout: Optional[float] = None) -> bool:
        return self._leader.wait(timeout)

    @property
    def is_leader(self) -> bool:
        return self._leader.is_set()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        if self._leader.is_set():
            self.lease.release()
            self._leader.clear()
