"""Leader election via a lease — the active/passive single-writer hook
(reference: cmd/controller/main.go:84-85 ``karpenter-leader-election``) —
and the keyed lease SET that generalizes it into the fleet's sharding
primitive (docs/fleet.md).

The in-memory deployment has one process, so the default lease is in-process;
multi-process deployments back it with a shared file (one machine) or swap in
a real coordination.k8s.io/Lease client. The contract is small: acquire
(non-blocking), renew on a heartbeat, release on shutdown; holders that stop
renewing lose the lease after the duration elapses.

:class:`FileLeaseSet` extends the same flock-serialized RMW discipline to a
MAP of per-key leases plus a live-member registry in one shared file — each
controller replica heartbeats its membership and holds the leases for the
provisioner shards it owns; a replica that stops renewing loses every shard
within one lease duration and a survivor takes them over
(fleet/ownership.py drives the claim/renew/release cycle).
"""

from __future__ import annotations

import contextlib
import fcntl
import glob
import json
import logging
import os
import threading
import time
import uuid
from typing import Callable, Dict, Iterable, Optional, Set

logger = logging.getLogger("karpenter.lease")

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_INTERVAL = 5.0

# a crashed writer can leave its write-to-temp file behind forever; sweep
# anything older than this many lease durations during acquire rounds
STALE_TMP_DURATIONS = 4.0


class FileLease:
    """Advisory lease in a shared file: {holder, expiry}. Atomic via
    write-to-temp + rename; stale leases are taken over after expiry."""

    def __init__(
        self,
        path: str,
        identity: Optional[str] = None,
        duration: float = DEFAULT_LEASE_DURATION,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.path = path
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.duration = duration
        self.clock = clock or time.time

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def _write(self, record: dict) -> None:
        tmp = f"{self.path}.{self.identity}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, self.path)

    def _sweep_stale_tmp(self) -> None:
        """Remove ``*.tmp`` files left by writers that crashed between the
        temp write and the rename. Caller holds the flock; only files old
        enough that no live writer can still be mid-RMW are removed."""
        horizon = time.time() - self.duration * STALE_TMP_DURATIONS
        for tmp in glob.glob(f"{glob.escape(self.path)}.*.tmp"):
            try:
                if os.path.getmtime(tmp) < horizon:
                    os.remove(tmp)
            except OSError:
                pass  # a racer renamed or removed it first

    @contextlib.contextmanager
    def _locked(self):
        """flock-serialized critical section: acquire/renew are
        read-modify-write, and two racers interleaving around the atomic
        rename could BOTH conclude they hold the lease (split brain)."""
        lock_path = f"{self.path}.flock"
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def try_acquire(self) -> bool:
        with self._locked():
            self._sweep_stale_tmp()
            now = self.clock()
            current = self._read()
            if current and current["holder"] != self.identity and current["expiry"] > now:
                return False
            self._write({"holder": self.identity, "expiry": now + self.duration})
            return True

    def renew(self) -> bool:
        with self._locked():
            now = self.clock()
            current = self._read()
            if (
                not current
                or current["holder"] != self.identity
                or current["expiry"] <= now  # expired: takeover may have won
            ):
                return False
            self._write({"holder": self.identity, "expiry": now + self.duration})
            return True

    def release(self) -> None:
        with self._locked():
            current = self._read()
            if current and current["holder"] == self.identity:
                try:
                    os.remove(self.path)
                except FileNotFoundError:
                    pass

    def holder(self) -> Optional[str]:
        # under the flock like every other accessor: the writer's RMW is
        # temp-write + rename, and an observer reading between a racer's
        # acquire check and its rename could report a holder the very next
        # rename overwrites — a torn view two observers would disagree on
        with self._locked():
            current = self._read()
        if current and current["expiry"] > self.clock():
            return current["holder"]
        return None


class FileLeaseSet:
    """Keyed advisory leases + a live-member registry in one shared file —
    the fleet sharding primitive. One JSON record::

        {"members": {identity: expiry},
         "shards":  {key: {"holder": identity, "expiry": t}}}

    All operations are flock-serialized read-modify-writes (the same
    split-brain argument as :class:`FileLease._locked`); batch operations
    (``renew_many``) amortize the flock over a replica's whole shard set so
    a 100-shard heartbeat is one critical section, not 100."""

    def __init__(
        self,
        path: str,
        identity: Optional[str] = None,
        duration: float = DEFAULT_LEASE_DURATION,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.path = path
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.duration = duration
        self.clock = clock or time.time

    # -- record plumbing (same discipline as FileLease) ---------------------
    def _read(self) -> dict:
        try:
            with open(self.path) as f:
                record = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            record = {}
        record.setdefault("members", {})
        record.setdefault("shards", {})
        return record

    def _write(self, record: dict) -> None:
        tmp = f"{self.path}.{self.identity}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, self.path)

    _locked = FileLease._locked
    _sweep_stale_tmp = FileLease._sweep_stale_tmp

    @staticmethod
    def _live(entry: Optional[dict], now: float) -> bool:
        return bool(entry) and entry["expiry"] > now

    # -- membership ---------------------------------------------------------
    def heartbeat(self) -> Set[str]:
        """Register/renew this replica's membership; prune expired members.
        Returns the LIVE member identities — the peer set the shard
        manager's rendezvous placement hashes over."""
        with self._locked():
            self._sweep_stale_tmp()
            now = self.clock()
            record = self._read()
            members = {
                m: exp for m, exp in record["members"].items() if exp > now
            }
            members[self.identity] = now + self.duration
            record["members"] = members
            self._write(record)
            return set(members)

    def members(self) -> Set[str]:
        with self._locked():
            record = self._read()
        now = self.clock()
        return {m for m, exp in record["members"].items() if exp > now}

    def resign(self) -> None:
        """Drop this replica from the member registry (clean shutdown)."""
        with self._locked():
            record = self._read()
            if record["members"].pop(self.identity, None) is not None:
                self._write(record)

    # -- per-key leases -----------------------------------------------------
    def try_acquire(self, key: str) -> bool:
        with self._locked():
            now = self.clock()
            record = self._read()
            current = record["shards"].get(key)
            if (
                self._live(current, now)
                and current["holder"] != self.identity
            ):
                return False
            record["shards"][key] = {
                "holder": self.identity, "expiry": now + self.duration,
            }
            self._write(record)
            return True

    def renew_many(self, keys: Iterable[str]) -> Set[str]:
        """Renew every still-held key in ONE critical section; returns the
        keys successfully renewed. A key someone else took over (this
        replica's hold expired) is simply absent from the result — the
        caller treats it as lost."""
        keys = list(keys)
        if not keys:
            return set()
        with self._locked():
            now = self.clock()
            record = self._read()
            renewed: Set[str] = set()
            for key in keys:
                current = record["shards"].get(key)
                if (
                    not current
                    or current["holder"] != self.identity
                    or current["expiry"] <= now  # expired: takeover may have won
                ):
                    continue
                record["shards"][key] = {
                    "holder": self.identity, "expiry": now + self.duration,
                }
                renewed.add(key)
            if renewed:
                self._write(record)
            return renewed

    def release(self, key: str) -> None:
        with self._locked():
            record = self._read()
            current = record["shards"].get(key)
            if current and current["holder"] == self.identity:
                del record["shards"][key]
                self._write(record)

    def release_all(self) -> None:
        with self._locked():
            record = self._read()
            mine = [
                k for k, v in record["shards"].items()
                if v["holder"] == self.identity
            ]
            for k in mine:
                del record["shards"][k]
            if mine:
                self._write(record)

    def holder(self, key: str) -> Optional[str]:
        with self._locked():
            record = self._read()
        current = record["shards"].get(key)
        if self._live(current, self.clock()):
            return current["holder"]
        return None

    def snapshot(self, keys: Optional[Iterable[str]] = None) -> Dict[str, str]:
        """Live key → holder map (expired holds omitted). ``keys`` is a
        hint for backends that cannot enumerate (KubeLeaseSet); the file
        record holds every key, so it is ignored here."""
        with self._locked():
            record = self._read()
        now = self.clock()
        return {
            k: v["holder"]
            for k, v in record["shards"].items()
            if self._live(v, now)
        }


class LeaderElector:
    """Blocks followers until leadership is acquired, then renews on a
    heartbeat; ``is_leader`` flips false if renewal fails (lost lease) and
    the ``on_lost`` callback fires — a second active leader must never keep
    mutating cloud state (the reference exits the process on lost lease)."""

    def __init__(
        self,
        lease: FileLease,
        renew_interval: float = DEFAULT_RENEW_INTERVAL,
        on_lost: Optional[Callable[[], None]] = None,
    ):
        self.lease = lease
        self.renew_interval = renew_interval
        self.on_lost = on_lost
        self._leader = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # at-most-once-per-epoch guard for on_lost: the elector thread's
        # failed-renew branch, its raising-backend branch, and stop() can
        # all observe the same lost leadership — only ONE may fire the
        # callback per acquisition epoch (a double on_lost double-stops
        # the manager / double-exits the process in real deployments)
        self._epoch_lock = threading.Lock()
        self._epoch = 0  # guarded-by: self._epoch_lock
        self._lost_epoch = 0  # epochs whose loss was handled; guarded-by: self._epoch_lock

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True, name="leader-elector")
        self._thread.start()

    def _acquired(self) -> None:
        with self._epoch_lock:
            self._epoch += 1
            self._leader.set()

    def _fire_lost(self, notify: bool = True) -> None:
        """Flip the leader flag and fire ``on_lost`` at most once per
        leadership epoch. ``notify=False`` (clean release via ``stop``)
        consumes the epoch WITHOUT the callback, so a racing elector-thread
        branch cannot fire it after the release."""
        with self._epoch_lock:
            if not self._leader.is_set():
                return
            self._leader.clear()
            if self._lost_epoch >= self._epoch:
                return
            self._lost_epoch = self._epoch
        if notify and self.on_lost is not None:
            self.on_lost()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self._leader.is_set():
                    if not self.lease.renew():
                        self._fire_lost()
                elif self.lease.try_acquire():
                    self._acquired()
            except Exception:
                # a lease backend that raises must not kill the elector
                # thread: a dead elector with is_leader stuck True is the
                # split-brain case election exists to prevent
                logger.exception("lease operation failed")
                self._fire_lost()
            self._stop.wait(self.renew_interval)

    def wait_for_leadership(self, timeout: Optional[float] = None) -> bool:
        return self._leader.wait(timeout)

    @property
    def is_leader(self) -> bool:
        return self._leader.is_set()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        if self._leader.is_set():
            self.lease.release()
            # consume the epoch silently: a raising backend whose elector
            # thread outlived the join timeout must not fire on_lost for a
            # leadership we just released on purpose
            self._fire_lost(notify=False)
