"""Complement-set algebra over string values.

A ``ValueSet`` is either a finite set of strings or the complement of one,
which gives a finite representation of the infinite sets produced by the
``NotIn`` / ``Exists`` node-selector operators.

Semantics follow the reference implementation
(``pkg/utils/sets/sets.go:31-157``): intersection covers all four polarity
cases, ``len()`` of a complement set counts down from a large sentinel, and
``op_type()`` maps a set back to the node-selector operator that would have
produced it.

The tensor encoding of these sets (bitmasks over an interned vocabulary with
an explicit "other" bucket standing in for the unenumerated universe) lives in
``karpenter_tpu.solver.encode``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import FrozenSet, Iterable

# Operators (mirror v1.NodeSelectorOperator).
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"

# Stand-in for the cardinality of the (infinite) universe; complement sets
# report len = INFINITE - n so that "empty" checks stay uniform
# (reference: sets.go:152-157 uses math.MaxInt64).
INFINITE = 1 << 62


@dataclass(frozen=True)
class ValueSet:
    """A finite string set or the complement of one."""

    values: FrozenSet[str] = field(default_factory=frozenset)
    complement: bool = False

    # -- constructors ------------------------------------------------------
    @staticmethod
    def of(*values: str) -> "ValueSet":
        return ValueSet(frozenset(values), False)

    @staticmethod
    def complement_of(*values: str) -> "ValueSet":
        return ValueSet(frozenset(values), True)

    @staticmethod
    def universe() -> "ValueSet":
        return ValueSet(frozenset(), True)

    @staticmethod
    def empty() -> "ValueSet":
        return ValueSet(frozenset(), False)

    # -- queries -----------------------------------------------------------
    def is_complement(self) -> bool:
        return self.complement

    def __len__(self) -> int:
        # NB: python's __len__ rejects values > sys.maxsize on some paths;
        # use .cardinality for arithmetic.
        return self.cardinality

    @property
    def cardinality(self) -> int:
        if self.complement:
            return INFINITE - len(self.values)
        return len(self.values)

    @property
    def is_empty(self) -> bool:
        return not self.complement and not self.values

    def op_type(self) -> str:
        """Map the set back to the node-selector operator that produces it
        (reference: sets.go:81-96)."""
        if self.complement:
            return OP_EXISTS if not self.values else OP_NOT_IN
        return OP_IN if self.values else OP_DOES_NOT_EXIST

    def has(self, value: str) -> bool:
        if self.complement:
            return value not in self.values
        return value in self.values

    def has_any(self, values: Iterable[str]) -> bool:
        """True if any of the supplied values are in the *underlying* finite
        set (reference HasAny ignores polarity — sets.go:120-123)."""
        return any(v in self.values for v in values)

    def contains_any(self, values: Iterable[str]) -> bool:
        """True if any supplied value is a member, honoring polarity."""
        return any(self.has(v) for v in values)

    # -- algebra -----------------------------------------------------------
    def intersection(self, other: "ValueSet") -> "ValueSet":
        """All four polarity cases (reference: sets.go:133-151)."""
        if self.complement:
            if other.complement:
                return ValueSet(self.values | other.values, True)
            return ValueSet(other.values - self.values, False)
        if other.complement:
            return ValueSet(self.values - other.values, False)
        return ValueSet(self.values & other.values, False)

    def finite_values(self) -> FrozenSet[str]:
        if self.complement:
            raise ValueError("infinite set")
        return self.values

    def complement_values(self) -> FrozenSet[str]:
        if not self.complement:
            raise ValueError("not a complement set")
        return self.values

    def __str__(self) -> str:
        vals = sorted(self.values)
        return f"{vals}'" if self.complement else f"{vals}"


def set_for_operator(operator: str, values: Iterable[str] = ()) -> ValueSet:
    """Build the ValueSet for a node-selector requirement
    (reference: requirements.go:96-105)."""
    values = tuple(values)
    if operator == OP_IN:
        return ValueSet.of(*values)
    if operator == OP_NOT_IN:
        return ValueSet.complement_of(*values)
    if operator == OP_EXISTS:
        return ValueSet.universe()
    if operator == OP_DOES_NOT_EXIST:
        return ValueSet.empty()
    raise ValueError(f"unsupported operator {operator}")
