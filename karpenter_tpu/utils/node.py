"""Node predicates (reference: pkg/utils/node/predicates.go)."""

from __future__ import annotations

from karpenter_tpu.api.objects import Node


def is_ready(node: Node) -> bool:
    return any(c.type == "Ready" and c.status == "True" for c in node.status.conditions)
