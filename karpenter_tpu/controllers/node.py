"""Node lifecycle: initialization, expiration, emptiness, finalizer.

Mirrors ``pkg/controllers/node``: watches karpenter-labeled nodes (plus mapped
events from provisioner changes and pod assignments), runs four
sub-reconcilers, persists a single update, and requeues at the soonest of the
sub-reconcilers' requested times (controller.go:42-116, ``result.Min``).
"""

from __future__ import annotations

import copy
import logging
from datetime import datetime, timezone
from typing import List, Optional

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, Taint
from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import node as nodeutil
from karpenter_tpu.utils import pod as podutil

logger = logging.getLogger("karpenter.node")

INITIALIZATION_TIMEOUT = 15 * 60.0  # reference: initialization.go:32


def _rfc3339(ts: float) -> str:
    return datetime.fromtimestamp(ts, timezone.utc).isoformat()


def _parse_rfc3339(s: str) -> float:
    return datetime.fromisoformat(s).timestamp()


def result_min(*results: Optional[float]) -> Optional[float]:
    """Merge reconcile results, taking the soonest requeue
    (reference: utils/result/result.go)."""
    times = [r for r in results if r is not None]
    return min(times) if times else None


class Initialization:
    """Remove the not-ready startup taint when the node goes Ready; delete
    nodes that never initialize within the timeout
    (reference: initialization.go:32-66)."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self, provisioner: Provisioner, node: Node) -> Optional[float]:
        if not any(t.key == lbl.NOT_READY_TAINT_KEY for t in node.spec.taints):
            return None
        if not nodeutil.is_ready(node):
            age = self.cluster.clock() - node.metadata.creation_timestamp
            if age < INITIALIZATION_TIMEOUT:
                return INITIALIZATION_TIMEOUT - age
            logger.info("Triggering termination for node %s that failed to become ready",
                        node.metadata.name)
            self.cluster.delete("nodes", node.metadata.name, namespace="")
            return None
        node.spec.taints = [t for t in node.spec.taints if t.key != lbl.NOT_READY_TAINT_KEY]
        # node-ready closes the provisioning lifecycle: a zero-work span,
        # parented (via the annotation provisioning stamped at launch) into
        # the launch trace — time-from-creation is the attribute that
        # matters, the ready transition itself is instantaneous
        from karpenter_tpu import obs

        ctx = obs.from_traceparent(
            node.metadata.annotations.get(obs.TRACE_ANNOTATION)
        )
        if ctx is not None:
            with obs.tracer().span(
                "node.ready",
                parent=ctx,
                attrs={
                    "node": node.metadata.name,
                    "since_creation_s": round(
                        self.cluster.clock() - node.metadata.creation_timestamp, 3
                    ),
                },
            ):
                pass
        return None


class Expiration:
    """Delete nodes older than ``ttl_seconds_until_expired``
    (reference: expiration.go:33-54)."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self, provisioner: Provisioner, node: Node) -> Optional[float]:
        ttl = provisioner.spec.ttl_seconds_until_expired
        if ttl is None:
            return None
        expiration_time = node.metadata.creation_timestamp + ttl
        now = self.cluster.clock()
        if now > expiration_time:
            logger.info("Triggering termination for expired node %s after %ss",
                        node.metadata.name, ttl)
            self.cluster.delete("nodes", node.metadata.name, namespace="")
            return None
        return expiration_time - now


class Emptiness:
    """Annotate empty nodes with an emptiness timestamp; delete them once the
    TTL elapses; clear the annotation if pods land again
    (reference: emptiness.go:36-100). Empty = every pod is terminal or
    daemonset/static."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self, provisioner: Provisioner, node: Node) -> Optional[float]:
        ttl = provisioner.spec.ttl_seconds_after_empty
        if ttl is None:
            return None
        if not nodeutil.is_ready(node):
            return None
        empty = self.is_empty(node)
        stamp = node.metadata.annotations.get(lbl.EMPTINESS_TIMESTAMP_ANNOTATION)
        if not empty:
            if stamp is not None:
                del node.metadata.annotations[lbl.EMPTINESS_TIMESTAMP_ANNOTATION]
                logger.info("Removed emptiness TTL from node %s", node.metadata.name)
            return None
        now = self.cluster.clock()
        if stamp is None:
            node.metadata.annotations[lbl.EMPTINESS_TIMESTAMP_ANNOTATION] = _rfc3339(now)
            logger.info("Added TTL to empty node %s", node.metadata.name)
            return float(ttl)
        emptiness_time = _parse_rfc3339(stamp)
        if now > emptiness_time + ttl:
            logger.info("Triggering termination after %ss for empty node %s",
                        ttl, node.metadata.name)
            self.cluster.delete("nodes", node.metadata.name, namespace="")
            return None
        return emptiness_time + ttl - now

    def is_empty(self, node: Node) -> bool:
        for p in self.cluster.pods_on_node(node.metadata.name):
            if podutil.is_terminal(p):
                continue
            if not podutil.is_owned_by_daemonset(p) and not podutil.is_owned_by_node(p):
                return False
        return True


class Finalizer:
    """Ensure self-registered nodes carry the termination finalizer — covers
    instances that launch when the node-object create failed
    (reference: finalizer.go:31-42)."""

    def reconcile(self, provisioner: Provisioner, node: Node) -> Optional[float]:
        if node.metadata.deletion_timestamp is not None:
            return None
        if lbl.TERMINATION_FINALIZER not in node.metadata.finalizers:
            node.metadata.finalizers.append(lbl.TERMINATION_FINALIZER)
        return None


class CloudLiveness:
    """Detect instances terminated out from under their Node objects.

    Asks the provider's ``instance_gone`` probe — which debounces describe
    flakes behind an N-consecutive-miss tracker (resilience.MissTracker),
    so one chaotic describe response can never orphan a healthy node —
    and hands a confirmed-gone node to the termination path. Providers
    without a describe surface answer ``NotImplemented`` and opt the whole
    sub-reconciler out (no requeue pressure); a probe that merely failed
    this time answers None and keeps its cadence."""

    PROBE_INTERVAL = 30.0

    def __init__(self, cluster: Cluster, cloud_provider):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self._last_probe: dict = {}
        self._last_sweep: Optional[float] = None

    def _sweep(self, now: float) -> None:
        """Nodes terminated by OTHER controllers (consolidation, expiration,
        interruption) never hit this sub-reconciler's own cleanup paths;
        sweep their probe stamps so a churning spot fleet can't grow the
        table for the process lifetime. Time-gated: on fleets larger than
        the threshold the table legitimately stays big, and a full scan per
        reconcile would be O(N²) per round."""
        if len(self._last_probe) <= 256:
            return
        if self._last_sweep is not None and now - self._last_sweep < self.PROBE_INTERVAL:
            return
        self._last_sweep = now
        live = {n.metadata.name for n in self.cluster.nodes()}
        for name in list(self._last_probe):
            if name not in live:
                del self._last_probe[name]

    def reconcile(self, provisioner: Provisioner, node: Node) -> Optional[float]:
        if self.cloud_provider is None or node.metadata.deletion_timestamp is not None:
            return None
        now = self.cluster.clock()
        self._sweep(now)
        last = self._last_probe.get(node.metadata.name)
        if last is not None and now - last < self.PROBE_INTERVAL:
            return self.PROBE_INTERVAL - (now - last)
        self._last_probe[node.metadata.name] = now
        try:
            gone = self.cloud_provider.instance_gone(node)
        except Exception:
            logger.debug("liveness probe failed for %s", node.metadata.name, exc_info=True)
            return self.PROBE_INTERVAL
        if gone is NotImplemented:  # vendor has no liveness surface at all
            self._last_probe.pop(node.metadata.name, None)
            return None
        if gone is None:
            # the probe itself failed this time — KEEP the cadence: one
            # flaky describe must not permanently halt liveness monitoring
            return self.PROBE_INTERVAL
        if gone:
            logger.info(
                "Triggering termination for node %s: backing instance confirmed gone",
                node.metadata.name,
            )
            self._last_probe.pop(node.metadata.name, None)
            self.cluster.delete("nodes", node.metadata.name, namespace="")
            return None
        return self.PROBE_INTERVAL


class NodeController:
    """reference: node/controller.go:42-150."""

    def __init__(self, cluster: Cluster, cloud_provider=None):
        self.cluster = cluster
        self.initialization = Initialization(cluster)
        self.expiration = Expiration(cluster)
        self.emptiness = Emptiness(cluster)
        self.finalizer = Finalizer()
        self.liveness = CloudLiveness(cluster, cloud_provider)

    def reconcile(self, name: str) -> Optional[float]:
        live = self.cluster.try_get("nodes", name, namespace="")
        if live is None or live.metadata.deletion_timestamp is not None:
            return None
        provisioner_name = live.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL)
        if provisioner_name is None:
            return None
        provisioner = self.cluster.try_get("provisioners", provisioner_name, namespace="")
        if provisioner is None:
            return None
        # sub-reconcilers run over a DEEP COPY (reference:
        # node/controller.go:62-116): mutating the shared informer-cache
        # object before a write that can fail would leave the cache
        # diverged from the server with nothing re-driving the patch
        node = copy.deepcopy(live)
        before = _snapshot(live)
        results: List[Optional[float]] = []
        for sub in (self.initialization, self.expiration, self.emptiness,
                    self.finalizer, self.liveness):
            results.append(sub.reconcile(provisioner, node))
            # a sub-reconciler may delete the node (finalizer-bearing nodes
            # stay in the store but start terminating); stop touching it then
            if (
                node.metadata.deletion_timestamp is not None
                or self.cluster.try_get("nodes", name, namespace="") is None
            ):
                return None
        after = _snapshot(node)
        if after != before:
            # ONE merge patch with exactly the changed fields (reference:
            # node/controller.go:106-115) — a full-object PUT from the
            # informer cache races other writers' resourceVersions
            from karpenter_tpu.kube.serde import taint_to_wire

            patch: dict = {}
            if after[0] != before[0]:
                # arrays replace wholesale under RFC 7386
                patch.setdefault("spec", {})["taints"] = [
                    taint_to_wire(t) for t in node.spec.taints
                ]
            if after[1] != before[1]:
                # maps merge per key: send only added/changed keys, plus
                # nulls for removals — re-asserting unchanged keys would
                # clobber concurrent writers with cached values
                old = dict(before[1])
                annotations = {
                    k: v for k, v in node.metadata.annotations.items()
                    if old.get(k) != v
                }
                for key in old:
                    if key not in node.metadata.annotations:
                        annotations[key] = None  # merge-patch delete
                patch.setdefault("metadata", {})["annotations"] = annotations
            if after[2] != before[2]:
                patch.setdefault("metadata", {})["finalizers"] = list(
                    node.metadata.finalizers
                )
            self.cluster.merge_patch("nodes", name, patch, namespace="")
        return result_min(*results)

    def register(self, manager) -> None:
        """Watch nodes directly, provisioners mapped to their nodes, and pods
        mapped to their node (reference: controller.go:118-150)."""

        def on_node(event: str, node) -> None:
            if node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL):
                manager.enqueue("node", node.metadata.name)

        def on_provisioner(event: str, provisioner) -> None:
            for node in self.cluster.nodes():
                if node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) == provisioner.metadata.name:
                    manager.enqueue("node", node.metadata.name)

        def on_pod(event: str, pod) -> None:
            if pod.spec.node_name:
                manager.enqueue("node", pod.spec.node_name)

        self.cluster.watch("nodes", on_node)
        self.cluster.watch("provisioners", on_provisioner)
        self.cluster.watch("pods", on_pod)


def _snapshot(node: Node):
    return (
        tuple((t.key, t.value, t.effect) for t in node.spec.taints),
        tuple(sorted(node.metadata.annotations.items())),
        tuple(node.metadata.finalizers),
    )
