"""PVC selected-node annotation.

Mirrors ``pkg/controllers/persistentvolumeclaim``: once a pod is scheduled,
write the ``volume.kubernetes.io/selected-node`` annotation onto its PVCs so
the volume provisioner creates the volume in the right zone before kubelet
asks for it (controller.go:37-122).
"""

from __future__ import annotations

from typing import List

from karpenter_tpu.api.objects import PersistentVolumeClaim, Pod
from karpenter_tpu.kube.client import Cluster

SELECTED_NODE_ANNOTATION = "volume.kubernetes.io/selected-node"


class PVCController:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self, name: str, namespace: str = "default") -> None:
        pod = self.cluster.try_get("pods", name, namespace)
        if pod is None or not pod.spec.node_name:
            return
        for pvc in self.pvcs_for_pod(pod):
            if pvc.metadata.annotations.get(SELECTED_NODE_ANNOTATION) == pod.spec.node_name:
                continue
            pvc.metadata.annotations[SELECTED_NODE_ANNOTATION] = pod.spec.node_name
            self.cluster.update("pvcs", pvc)

    def pvcs_for_pod(self, pod: Pod) -> List[PersistentVolumeClaim]:
        """reference: controller.go:111-122."""
        out: List[PersistentVolumeClaim] = []
        for volume in pod.spec.volumes:
            if not volume.persistent_volume_claim:
                continue
            pvc = self.cluster.try_get(
                "pvcs", volume.persistent_volume_claim, pod.metadata.namespace
            )
            if pvc is not None:
                out.append(pvc)
        return out

    def register(self, manager) -> None:
        def on_pod(event: str, pod) -> None:
            manager.enqueue("pvc", (pod.metadata.name, pod.metadata.namespace))

        self.cluster.watch("pods", on_pod)
