"""Termination: finalizer-driven node teardown.

Mirrors ``pkg/controllers/termination``: a deleted Node bearing the
``karpenter.sh/termination`` finalizer is cordoned, drained (respecting
do-not-evict, static pods, stuck-terminating pods, and PDBs via the eviction
queue's 429-retry), then the cloud instance is deleted and the finalizer
removed (terminate.go:43-141, eviction.go:33-107, controller.go:63-95).
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, Pod, Taint
from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils.workqueue import ExponentialBackoff, RateLimitingQueue, ShutDown

logger = logging.getLogger("karpenter.termination")

# reference: eviction.go:34-36
EVICTION_QUEUE_BASE_DELAY = 0.1
EVICTION_QUEUE_MAX_DELAY = 10.0

CRITICAL_PRIORITY_CLASSES = ("system-cluster-critical", "system-node-critical")

UNSCHEDULABLE_TAINT = Taint(key="node.kubernetes.io/unschedulable", effect="NoSchedule")


class EvictionQueue:
    """Async rate-limited evictor: PDB-blocked evictions (the 429 analog)
    retry with exponential backoff (reference: eviction.go:33-107)."""

    def __init__(self, cluster: Cluster, start: bool = True):
        self.cluster = cluster
        self.queue = RateLimitingQueue(
            ExponentialBackoff(base=EVICTION_QUEUE_BASE_DELAY, cap=EVICTION_QUEUE_MAX_DELAY)
        )
        # membership set spanning queued + delayed-for-retry keys: repeated
        # drain rounds must not bypass a parked key's backoff
        # (reference: eviction.go:56-63 pairs the workqueue with a set.Set)
        self._in_flight: set = set()
        self._in_flight_mu = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(target=self.run, daemon=True, name="eviction")
            self._thread.start()

    def add(self, pods: List[Pod]) -> None:
        for pod in pods:
            key = (pod.metadata.namespace, pod.metadata.name)
            with self._in_flight_mu:
                if key in self._in_flight:
                    continue
                self._in_flight.add(key)
            self.queue.add(key)

    def run(self) -> None:
        while True:
            try:
                key = self.queue.get()
            except ShutDown:
                return
            self.process_one(key)

    def process_one(self, key: Tuple[str, str]) -> bool:
        """Evict + queue bookkeeping for one dequeued key; returns whether
        the eviction succeeded. A blocked eviction requeues on the SERVER's
        ``Retry-After`` hint when the apiserver sent one (the PDB knows when
        it might admit the eviction better than a blind backoff does), and
        on the exponential backoff otherwise."""
        ok, hint = self.evict_once(key)
        if ok:
            self.queue.forget(key)
            with self._in_flight_mu:
                self._in_flight.discard(key)
            self.queue.done(key)
            return True
        self.queue.done(key)
        if hint is not None and hint > 0:
            self.queue.add_after(key, hint)
        else:
            self.queue.add_rate_limited(key)
        return False

    def evict_once(self, key: Tuple[str, str]) -> Tuple[bool, Optional[float]]:
        namespace, name = key
        pod = self.cluster.try_get("pods", name, namespace)
        if pod is None:  # 404 → nothing to evict
            return True, None
        ok, hint = self.cluster.evict_with_hint(pod)
        if not ok:
            logger.debug(
                "eviction of %s/%s blocked by PDB (429%s)", namespace, name,
                f", Retry-After {hint:.2f}s" if hint is not None else "",
            )
        return ok, hint

    def stop(self) -> None:
        self.queue.shut_down()
        if self._thread:
            self._thread.join(timeout=2)


def is_stuck_terminating(pod: Pod, now: float) -> bool:
    """Kubelet-partition guard: the pod is past its graceful-deletion window
    (reference: terminate.go:144-149)."""
    if pod.metadata.deletion_timestamp is None:
        return False
    return now > pod.metadata.deletion_timestamp + pod.spec.termination_grace_period_seconds


class Terminator:
    """reference: terminate.go:35-141."""

    def __init__(self, cluster: Cluster, cloud_provider: CloudProvider, eviction_queue: EvictionQueue):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.eviction_queue = eviction_queue

    def cordon(self, node: Node) -> None:
        if node.spec.unschedulable:
            return
        # precise merge-patch (the reference's single-patch idiom): a
        # full-object PUT from the informer cache races other writers'
        # resourceVersions, and mutating the cached object BEFORE a write
        # that might fail would make the early-return above lie forever
        self.cluster.merge_patch(
            "nodes", node.metadata.name, {"spec": {"unschedulable": True}},
            namespace=node.metadata.namespace,
        )
        logger.info("Cordoned node %s", node.metadata.name)

    def drain(self, node: Node, force: bool = False) -> bool:
        """Evict pods; True when the node is fully drained. ``force`` is
        the interruption subsystem's deadline hook: once the cloud's grace
        period is spent the capacity disappears regardless, so do-not-evict
        stops blocking and every pod is enqueued for eviction."""
        pods = self.get_pods(node)
        if not force:
            for pod in pods:
                if pod.metadata.annotations.get(lbl.DO_NOT_EVICT_ANNOTATION) == "true":
                    logger.debug(
                        "Unable to drain node %s: pod %s has do-not-evict",
                        node.metadata.name, pod.key,
                    )
                    return False
        self.evict(pods)
        return len(pods) == 0

    def terminate(self, node: Node) -> None:
        self.cloud_provider.delete(node)
        self.cluster.remove_finalizer("nodes", node, lbl.TERMINATION_FINALIZER)
        from karpenter_tpu.kube.events import recorder_for

        recorder_for(self.cluster).event(
            "Node", node.metadata.name, "Terminated",
            "cordoned, drained and deleted the backing instance",
        )
        logger.info("Deleted node %s", node.metadata.name)

    def get_pods(self, node: Node) -> List[Pod]:
        """Evictable pods: exclude pods tolerating the unschedulable taint
        (they would reschedule right back), stuck-terminating pods, and
        static pods (reference: terminate.go:98-120)."""
        now = self.cluster.clock()
        out = []
        for p in self.cluster.pods_on_node(node.metadata.name):
            if any(t.tolerates(UNSCHEDULABLE_TAINT) for t in p.spec.tolerations):
                continue
            if is_stuck_terminating(p, now):
                continue
            if podutil.is_owned_by_node(p):
                continue
            out.append(p)
        return out

    def evict(self, pods: List[Pod]) -> None:
        """Critical pods evict only after all non-critical are gone
        (reference: terminate.go:122-141)."""
        critical, non_critical = [], []
        for pod in pods:
            if pod.metadata.deletion_timestamp is not None:
                continue
            if pod.spec.priority_class_name in CRITICAL_PRIORITY_CLASSES:
                critical.append(pod)
            else:
                non_critical.append(pod)
        self.eviction_queue.add(non_critical if non_critical else critical)


class TerminationController:
    """reference: termination/controller.go:50-113."""

    DRAIN_REQUEUE = 1.0

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        start_queue: bool = True,
        fenced=None,
    ):
        self.cluster = cluster
        self.eviction_queue = EvictionQueue(cluster, start=start_queue)
        self.terminator = Terminator(cluster, cloud_provider, self.eviction_queue)
        # partition-tolerance fence (docs/partition.md): finalizer-driven
        # teardown acts on the INFORMER view, which is stale while the
        # apiserver is unreachable past lease expiry — defer the cloud
        # delete until the control plane answers. (Cloud-NOTIFIED
        # terminations — interruption's force path — are deliberately not
        # gated: the cloud itself declared that capacity dying.)
        self.fenced = fenced or (lambda: False)

    def reconcile(self, name: str) -> Optional[float]:
        node = self.cluster.try_get("nodes", name, namespace="")
        if node is None:
            return None
        if node.metadata.deletion_timestamp is None:
            return None
        if lbl.TERMINATION_FINALIZER not in node.metadata.finalizers:
            return None
        self.terminator.cordon(node)
        if not self.terminator.drain(node):
            return self.DRAIN_REQUEUE
        if self.fenced():
            from karpenter_tpu import metrics

            metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(reason="fenced").inc()
            logger.warning(
                "deferring cloud delete of %s: replica fenced (apiserver "
                "unreachable past lease expiry)", name,
            )
            return self.DRAIN_REQUEUE
        self.terminator.terminate(node)
        return None

    def register(self, manager) -> None:
        def on_node(event: str, node) -> None:
            manager.enqueue("termination", node.metadata.name)

        def on_pod(event: str, pod) -> None:
            # pod deletions progress drains; re-kick the hosting node
            if pod.spec.node_name:
                manager.enqueue("termination", pod.spec.node_name)

        self.cluster.watch("nodes", on_node)
        self.cluster.watch("pods", on_pod)

    def stop(self) -> None:
        self.eviction_queue.stop()
