"""Provisioning: per-Provisioner workers that batch, solve, launch, and bind.

Mirrors ``pkg/controllers/provisioning``: the controller reconciles
Provisioner objects — hot-swapping an in-memory worker when the spec hash
changes, layering the live catalog's requirements in at apply — and each
worker runs batch → re-verify → get catalog → solve → parallel launch,
creating the Node object itself (pre-registration with the not-ready taint)
and binding pods directly (provisioner.go:81-181).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Pod
from karpenter_tpu.api.provisioner import (
    SOLVER_FFD,
    SOLVER_TPU,
    Provisioner,
    default_provisioner,
    validate_provisioner,
)
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.cloudprovider.types import CloudProvider, NodeRequest
from karpenter_tpu.kube.client import Cluster, Conflict
from karpenter_tpu.scheduling.ffd import VirtualNode
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.batcher import Batcher

logger = logging.getLogger("karpenter.provisioning")

# Catalog refresh period — the reference requeues every 5 minutes to pick up
# catalog drift (provisioning/controller.go:82).
REQUEUE_INTERVAL = 300.0

# How often a replica re-checks a provisioner it does NOT own: ownership can
# arrive within one lease duration of the owner's death, so the recheck must
# be of the same order (docs/fleet.md).
OWNERSHIP_RECHECK_INTERVAL = 5.0

# Wall-clock allowance for one provision round (catalog → solve → launches):
# the resilience layer's retry deadlines are capped by what remains of this,
# so a flaky control plane degrades the round as a whole instead of every
# call independently stacking its own worst case (resilience/policy.py).
PROVISION_ROUND_BUDGET = 60.0


# Re-verification between enqueue and solve (reference: provisioner.go:121-134
# and selection/controller.go:117-123 share this predicate).
is_provisionable = podutil.is_provisionable


class ProvisionerWorker:
    """One worker goroutine-equivalent per Provisioner
    (reference: provisioner.go:40-77)."""

    def __init__(
        self,
        provisioner: Provisioner,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        scheduler: Optional[Scheduler] = None,
        batcher: Optional[Batcher] = None,
        solver_service_address: Optional[str] = None,
        owned: Optional[callable] = None,
        fenced: Optional[callable] = None,
        journal=None,
        pack_checksum: Optional[bool] = None,
        canary_rate: Optional[float] = None,
        solver_stream: Optional[bool] = None,
        solver_shm_dir: Optional[str] = None,
        solver_delta: Optional[bool] = None,
        unschedulable_event_rounds: int = 3,
        warm_pool: bool = False,
    ):
        self.provisioner = provisioner
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        # warm-pool claiming (controllers/warmpool.py): when on, each
        # round first-fits its batch onto standing speculative nodes
        # BEFORE the solver — a warm hit binds immediately instead of
        # paying the launch-to-ready latency
        self.warm_pool = warm_pool
        # decision observability (docs/decisions.md): every round lands in
        # the decision audit log; a pod failing this many CONSECUTIVE
        # rounds gets its PodUnschedulable Warning event
        self.unschedulable_event_rounds = unschedulable_event_rounds
        # the current round's decision id — Warning events emitted from
        # this worker's decision path annotate it (karplint
        # `event-decision-id`); "" until the first record lands
        self.last_decision_id = ""
        # write-ahead launch journal (launch/journal.py): intent recorded
        # BEFORE the cloud create, resolved only after the bind — the
        # breadcrumb crash recovery replays. None = journaling off.
        self.journal = journal
        self.scheduler = scheduler or Scheduler(
            cluster, solver_service_address=solver_service_address,
            pack_checksum=pack_checksum, canary_rate=canary_rate,
            solver_stream=solver_stream, solver_shm_dir=solver_shm_dir,
            solver_delta=solver_delta,
        )
        # bounded, priority-aware admission (docs/overload.md): a full
        # queue sheds the oldest lowest-priority pod instead of growing
        # without limit, and the brownout ladder scales the window/sheds
        # queued low-priority work through the same hooks
        self.batcher = batcher or Batcher(
            priority_fn=podutil.priority_of, on_shed=self._on_shed
        )
        # fleet split-brain guard: does this replica still hold the shard
        # lease for this provisioner? Re-checked at solve time and again
        # immediately before every cloud create — a replica that lost its
        # lease mid-round must not launch (docs/fleet.md). Single-replica
        # deployments run with the constant-True default.
        self.owned = owned or (lambda: True)
        # partition-tolerance fence (docs/partition.md): True while the
        # apiserver has been unreachable past the shard leases' expiry
        # margin — a peer with a working control plane may own this shard
        # already, so cloud creates are refused until contact resumes
        self.fenced = fenced or (lambda: False)
        self._pending_lock = threading.Lock()
        self._pending_keys: set = set()
        # keys a failed launch re-queued THIS round: provision_once's
        # cleanup must not strip their pending state while they sit in the
        # batcher, or selection's verify requeue would re-relax preferences
        # the pods never needed to give up
        self._requeued_keys: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # set once the TPU solver warmup finished (success or failure) —
        # observable so tests can assert the warmup path actually runs
        self.warmed = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if self.provisioner.spec.solver == SOLVER_TPU:
            # pre-compile the solver for this catalog's dimensions so the
            # first real batch doesn't pay the multi-second XLA compile
            threading.Thread(target=self._warmup, daemon=True).start()

    def _warmup(self) -> None:
        try:
            # one background retry: a transient first-compile failure (TPU
            # not plumbed yet, catalog call flake) must not make the first
            # real batch eat the compile storm
            for attempt in (1, 2):
                try:
                    self._warmup_once()
                    return
                except Exception:
                    metrics.SOLVER_WARMUP_FAILURES.inc()
                    if attempt == 2 or self._stop.is_set():
                        logger.exception(
                            "solver warmup failed (first batch will compile)"
                        )
                        return
                    logger.exception(
                        "solver warmup failed; retrying once in background"
                    )
                    self._stop.wait(1.0)
                    if self._stop.is_set():  # shutdown mustn't pay a compile
                        return
        finally:
            self.warmed.set()

    def _warmup_once(self) -> None:
        from karpenter_tpu.cloudprovider.metrics import reconciling_controller
        from karpenter_tpu.testing.factories import make_pod

        reconciling_controller.set("provisioning")

        instance_types = self.cloud_provider.get_instance_types(
            self.provisioner.spec.constraints.provider
        )
        # on a real accelerator, warm the FULL batch bucket (the batcher
        # caps batches at max_items, so the first event storm solves in
        # that shape bucket — warming only a tiny bucket would leave the
        # storm to pay the multi-second compile); CPU test runs keep the
        # small bucket, their scan-kernel compile at 2048 is too slow
        from karpenter_tpu.solver.pallas_kernel import pallas_available

        n_warm = self.batcher.max_items if pallas_available() else 4
        pods = [make_pod(requests={"cpu": "0.1"}) for _ in range(n_warm)]
        self.scheduler.solve(self.provisioner, instance_types, pods)
        logger.debug(
            "solver warmed for provisioner %s (%d-pod bucket)",
            self.provisioner.name, n_warm,
        )

    def stop(self) -> None:
        self._stop.set()
        self.batcher.stop()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        from karpenter_tpu.cloudprovider.metrics import reconciling_controller

        reconciling_controller.set("provisioning")
        while not self._stop.is_set():
            try:
                self.provision_once()
            except Exception:
                logger.exception("provisioning loop error")

    # -- API ---------------------------------------------------------------
    def add(self, pod: Pod) -> threading.Event:
        """Enqueue a pod; returns the gate the selection reconciler MAY block
        on (reference: provisioner.go:77-79). The pod's key is tracked as
        pending until its batch has been solved, so a non-blocking selection
        can tell "awaiting its batch" from "needs another round"."""
        with self._pending_lock:
            self._pending_keys.add(pod.key)
        return self.batcher.add(pod)

    def is_pending(self, key) -> bool:
        """Is this pod enqueued or in the batch currently being solved?"""
        with self._pending_lock:
            return key in self._pending_keys

    def _on_shed(self, pod: Pod, reason: str) -> None:
        """Batcher shed hook: clear the pod's pending state — selection's
        periodic requeue re-submits it once capacity recovers — and
        surface the drop as a Warning event so every shed is auditable."""
        key = getattr(pod, "key", None)
        if key is None:
            return
        with self._pending_lock:
            self._pending_keys.discard(key)
            self._requeued_keys.discard(key)
        from karpenter_tpu.kube.events import recorder_for

        recorder_for(self.cluster).event(
            "Provisioner", self.provisioner.name, "PodShed",
            f"pod {key} shed from the admission queue ({reason}); it "
            "re-enters selection when capacity recovers", type="Warning",
            decision_id=self.last_decision_id,
        )

    # -- the provision loop ------------------------------------------------
    def provision_once(self) -> List[VirtualNode]:
        # flush unconditionally so gate waiters never stall on a failed solve
        # (reference: provisioner.go:84 `defer p.batcher.Flush()`)
        batch_keys = ()
        try:
            pods, _window = self.batcher.wait()
            batch_keys = {p.key for p in pods}
            return self._provision_batch(pods, _window)
        finally:
            with self._pending_lock:
                # fast-requeued pods are back in the batcher: keep them
                # pending so is_pending() holds through the next round
                self._pending_keys -= set(batch_keys) - self._requeued_keys
                self._requeued_keys.clear()
            self.batcher.flush()

    def _provision_batch(self, pods: List[Pod], window: float) -> List[VirtualNode]:
        from karpenter_tpu import obs

        # the round's root span starts AFTER the batcher hands over its
        # window (the idle wait is not latency anyone is owed); the
        # admission window happened BEFORE this span existed, so it rides
        # along as an attribute — a backdated child record would put an
        # interval outside the parent and corrupt self-time attribution
        with obs.tracer().span(
            "provision.round",
            attrs={
                "provisioner": self.provisioner.name,
                "batch": len(pods),
                "admission_window_s": round(max(window, 0.0), 6),
            },
        ) as round_sp:
            # dedupe by key: watch-event storms and verify requeues can
            # enqueue the same (or a replaced) pod object twice; double
            # inclusion would double its requests in the solve. Keep the
            # LATEST object per key (a replaced watch object carries the
            # freshest spec, e.g. after preference relaxation) at the
            # FIRST occurrence's position (stable FFD input order).
            latest = {}
            key_order = []
            for p in pods:
                if not is_provisionable(p):
                    continue
                if p.key not in latest:
                    key_order.append(p.key)
                latest[p.key] = p
            pods = [latest[k] for k in key_order]
            if not pods:
                return []
            if self.fenced():
                # apiserver unreachable past lease expiry: a peer may own
                # this shard already — launching now is the split-brain the
                # fence exists to prevent (docs/partition.md)
                metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                    reason="fenced"
                ).inc()
                round_sp.set_attribute("skipped", "fenced")
                return []
            if not self.owned():
                # shard lease gone: the new owner's selection loop re-routes
                # these pods to ITS worker — solving here would race its
                # launches (pending state clears in provision_once's finally)
                metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                    reason="lost_ownership"
                ).inc()
                round_sp.set_attribute("skipped", "lost_ownership")
                return []
            if self.warm_pool:
                # warm-hit steal BEFORE the solver: pods that fit standing
                # speculative capacity bind now; only the remainder pays
                # for a solve + cold launch
                pods = self._steal_warm(pods, round_sp)
                if not pods:
                    return []
            metrics.SOLVER_BATCH_SIZE.labels(backend=self.provisioner.spec.solver).observe(len(pods))
            # one time budget for the whole round: catalog, solve, and every
            # launch's retries all draw down the same allowance
            from karpenter_tpu.resilience import Budget

            budget = Budget(PROVISION_ROUND_BUDGET)
            with budget.activate():
                instance_types = self.cloud_provider.get_instance_types(
                    self.provisioner.spec.constraints.provider
                )
                nodes = self.scheduler.solve(self.provisioner, instance_types, pods)
                self._observe_stages()
                # the decision audit record lands BEFORE any launch: even
                # a round whose launches crash leaves its decision (and
                # any per-pod elimination verdicts) replayable
                self._record_decision(pods, nodes, round_sp)
                # parallel launch per virtual node (reference: provisioner.go:113)
                with ThreadPoolExecutor(max_workers=min(8, max(len(nodes), 1))) as pool:
                    # executor threads don't inherit contextvars: each launch
                    # re-activates the SHARED round budget in its own thread
                    # and parents its span on the round explicitly
                    launched = list(
                        pool.map(lambda v: self._launch(v, budget, round_sp), nodes)
                    )
            round_sp.set_attribute("nodes", len(nodes))
            round_sp.set_attribute("launched", sum(map(bool, launched)))
            if any(launched):  # only actual creations count as a scale event
                from karpenter_tpu.kube import serde

                try:
                    # status subresource: a main-resource write would have
                    # its status silently dropped by a real apiserver
                    self.cluster.patch_status(
                        "provisioners", self.provisioner.name,
                        {"lastScaleTime": serde.wire_ts(self.cluster.clock())},
                        namespace="",
                    )
                except Exception:
                    logger.debug("lastScaleTime write failed", exc_info=True)
            return nodes

    def _record_decision(self, pods: List[Pod], nodes: List[VirtualNode], round_sp) -> None:
        """Append this round to the decision audit log (obs/decisions.py):
        considered pods, the chosen packing, per-pod elimination
        attribution for whatever stayed unplaced, route/session
        provenance, and the brownout/fence state at decision time — then
        close the Kubernetes loop (PodUnschedulable Warning events for
        pods past the consecutive-failure threshold). Best-effort: the
        audit plane must never fail a reconcile round."""
        from karpenter_tpu import obs
        from karpenter_tpu.obs import decisions as dec

        if not dec.enabled():
            return
        try:
            log = obs.decision_log()
            state = {
                "fenced": bool(self.fenced()),
                **obs.state_snapshot(only=("brownout",)),
            }
            rec = log.record_round(
                provisioner=self.provisioner.name,
                pods=pods,
                nodes=nodes,
                context=self.scheduler.last_decision_context(),
                trace_id=round_sp.trace_id,
                state=state,
            )
            if rec is not None:
                self.last_decision_id = rec["id"]
                round_sp.set_attribute("decision_id", rec["id"])
                if rec["unschedulable_count"]:
                    round_sp.set_attribute(
                        "unschedulable", rec["unschedulable_count"]
                    )
            log.emit_unschedulable_events(
                self.cluster, threshold=self.unschedulable_event_rounds
            )
        except Exception:
            logger.debug("decision record failed", exc_info=True)

    def _observe_stages(self) -> None:
        """Plumb the solve's per-stage timings onto the scrape: the <100ms
        p99 is judged on scheduling_duration_seconds, but only the stage
        histogram says WHERE a regression landed (host encode vs wire
        serialization vs the in-flight pack_fetch vs decode)."""
        prof = self.scheduler.last_stage_profile()
        for stage, seconds in prof.items():
            if stage.endswith("_s") and isinstance(seconds, float):
                metrics.SOLVER_STAGE_DURATION.labels(stage=stage[:-2]).observe(seconds)

    # -- warm-pool claiming --------------------------------------------------
    def _steal_warm(self, pods: List[Pod], round_sp) -> List[Pod]:
        """First-fit the batch onto this provisioner's standing warm-pool
        nodes (controllers/warmpool.py) and bind the hits immediately —
        the speculative capacity is already launched (often already
        ready), so a hit skips the whole solve → create → ready pipeline.
        Returns the pods the solver still owes capacity. Hit/miss counts
        are per POD: the measured warm-hit rate is
        hits / (hits + misses)."""
        name = self.provisioner.name
        warm = [
            n for n in self.cluster.nodes()
            if n.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) == name
            and lbl.WARM_POOL_ANNOTATION in n.metadata.annotations
            and n.metadata.deletion_timestamp is None
        ]
        if not warm:
            metrics.WARMPOOL_MISSES.labels(provisioner=name).inc(len(pods))
            return pods
        # name order: the wave controller and every replica agree, so a
        # retried round re-claims the same nodes first
        warm.sort(key=lambda n: n.metadata.name)
        # plan first, record second, claim last: a stolen batch is still
        # a decision — the audit record (with the warm nodes as packing)
        # must land before any bind, same as the solver path, and the ring
        # must carry EVERY arrival or a replayed window (tools/whatif.py)
        # under-counts demand by exactly the hit rate
        remaining = list(pods)
        plan = []
        for node in warm:
            if not remaining:
                break
            chosen = self._fit_on_warm(remaining, node)
            if not chosen:
                continue
            plan.append((node, chosen))
            taken = {id(p) for p in chosen}
            remaining = [p for p in remaining if id(p) not in taken]
        if not plan:
            metrics.WARMPOOL_MISSES.labels(provisioner=name).inc(len(pods))
            return pods
        decision_id = self._record_warm_claims(plan, round_sp)
        hits = 0
        claimed = 0
        lost = set()
        for node, chosen in plan:
            if not self._claim_warm(node, chosen, decision_id):
                # claim lost (node raced away): its pods fall back to the
                # solver
                lost.update(id(p) for p in chosen)
                continue
            hits += len(chosen)
            claimed += 1
        if lost:
            # restore original batch positions for the fallen-back pods
            keep = {id(p) for p in remaining} | lost
            remaining = [p for p in pods if id(p) in keep]
        if hits:
            metrics.WARMPOOL_HITS.labels(provisioner=name).inc(hits)
            round_sp.set_attribute("warm_hits", hits)
            round_sp.set_attribute("warm_nodes", claimed)
        if remaining:
            metrics.WARMPOOL_MISSES.labels(provisioner=name).inc(
                len(remaining)
            )
        return remaining

    def _fit_on_warm(self, pods: List[Pod], node) -> List[Pod]:
        """The pods (first-fit, batch order) this warm node can hold:
        node-selector entries must match the node's labels, the template
        constraints must admit the pod (cheap re-check — the batch already
        passed selection), and the accumulated requests must fit the
        node's allocatable (exact milli-unit arithmetic)."""
        chosen: List[Pod] = []
        alloc = node.status.allocatable
        for pod in pods:
            sel = pod.spec.node_selector or {}
            if any(
                node.metadata.labels.get(k) != v for k, v in sel.items()
            ):
                continue
            if self.provisioner.spec.constraints.validate_pod(pod):
                continue
            if not res.fits(res.requests_for_pods(*(chosen + [pod])), alloc):
                continue
            chosen.append(pod)
        return chosen

    def _record_warm_claims(self, plan, round_sp) -> str:
        """Append the warm-claim plan to the decision audit ring. A round
        the steal absorbs never reaches ``_record_decision``, and a ring
        missing those rounds would replay (tools/whatif.py) as if the
        demand they served never arrived. The stand-in packing entries
        carry the claimed pods so the record shows zero unschedulable."""
        from types import SimpleNamespace

        from karpenter_tpu import obs
        from karpenter_tpu.obs import decisions as dec

        if not dec.enabled():
            return ""
        try:
            rec = obs.decision_log().record_round(
                provisioner=self.provisioner.name,
                pods=[p for _, chosen in plan for p in chosen],
                nodes=[
                    SimpleNamespace(
                        instance_type_options=[], pods=list(chosen)
                    )
                    for _, chosen in plan
                ],
                trace_id=round_sp.trace_id,
                state={
                    "warm_claim": True,
                    "warm_nodes": [n.metadata.name for n, _ in plan],
                },
            )
            if rec is not None:
                self.last_decision_id = rec["id"]
                round_sp.set_attribute("decision_id", rec["id"])
                return rec["id"]
        except Exception:
            logger.debug("warm claim record failed", exc_info=True)
        return ""

    def _claim_warm(self, node, pods: List[Pod], decision_id: str = "") -> bool:
        """Claim the node (remove the warm marker — what tells the GC
        ladder this speculation landed), bind the pods, and resolve the
        speculative journal entry by the node's launch token. The claim
        patch goes FIRST: a crash after it leaves a claimed node whose
        open entry resolves as NODE_EXISTS on the next sweep, never a
        double-claim."""
        try:
            self.cluster.merge_patch(
                "nodes", node.metadata.name,
                {"metadata": {"annotations": {lbl.WARM_POOL_ANNOTATION: None}}},
                namespace="",
            )
        except Exception:
            # claim lost (node deleted/raced): the pods stay in the batch
            # and the solver provides for them normally
            logger.debug(
                "warm-pool claim failed for %s", node.metadata.name,
                exc_info=True,
            )
            return False
        self._bind(pods, node.metadata.name)
        token = node.metadata.annotations.get(lbl.LAUNCH_TOKEN_ANNOTATION, "")
        if token and self.journal is not None:
            self.journal.resolve(token)
        from karpenter_tpu.kube.events import recorder_for

        recorder_for(self.cluster).event(
            "Node", node.metadata.name, "WarmPoolHit",
            f"bound {len(pods)} pod(s) to standing warm-pool capacity for "
            f"provisioner {self.provisioner.name} (no launch paid)",
            decision_id=decision_id or self.last_decision_id,
        )
        return True

    def _launch(self, vnode: VirtualNode, budget=None, parent_span=None) -> bool:
        """Returns whether a node was actually created."""
        from contextlib import nullcontext

        from karpenter_tpu import obs
        from karpenter_tpu.cloudprovider.metrics import reconciling_controller

        # executor threads don't inherit the worker's context: the budget
        # re-activates and the launch span parents on the round explicitly
        reconciling_controller.set("provisioning")
        with budget.activate() if budget is not None else nullcontext():
            with obs.tracer().span(
                "provision.launch",
                parent=parent_span,
                attrs={"pods": len(vnode.pods)},
            ) as sp:
                created = self._launch_one(vnode)
                sp.set_attribute("created", created)
                return created

    def _launch_one(self, vnode: VirtualNode) -> bool:
        try:
            # the launch-side split-brain guard: re-checked as late as
            # possible before the cloud create. Launches are tokened (the
            # wire fleet POST dedupes), but a lost lease means another
            # replica may ALREADY be solving these pods — creating here
            # would double capacity and race its binds.
            if self.fenced():
                metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                    reason="fenced"
                ).inc()
                logger.warning(
                    "skipping launch for %s: replica fenced (apiserver "
                    "unreachable past lease expiry)",
                    self.provisioner.name,
                )
                return False
            if not self.owned():
                metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                    reason="lost_ownership"
                ).inc()
                logger.warning(
                    "skipping launch for %s: shard lease lost",
                    self.provisioner.name,
                )
                return False
            # fresh limits check against live status (reference:
            # provisioner.go:138-144 re-reads the provisioner)
            live = self.cluster.try_get("provisioners", self.provisioner.name, namespace="")
            prov = live if live is not None else self.provisioner
            if prov.spec.limits is not None:
                err = prov.spec.limits.exceeded_by(prov.status.resources)
                if err:
                    logger.info("skipping launch: %s", err)
                    return False
            from karpenter_tpu import obs

            launch_span = obs.tracer().current()
            trace = (
                obs.to_traceparent(launch_span) if launch_span is not None else ""
            )
            # the launch token IS the launch's identity: stamped on the
            # cloud instance (providers replay a committed token, so the
            # metered retry policy can cover create), journaled BEFORE the
            # cloud call (crash recovery re-describes by it), annotated on
            # the Node (the GC cross-check pairs instance and Node by it)
            import uuid as _uuid

            token = _uuid.uuid4().hex
            if launch_span is not None:
                launch_span.set_attribute("launch_token", token[:12])
            if self.journal is not None:
                self.journal.record_intent(token, self.provisioner.name, trace)
            node = self.cloud_provider.create(
                NodeRequest(
                    template=vnode.constraints,
                    instance_type_options=vnode.instance_type_options,
                    launch_token=token,
                )
            )
            # merge the constraint template into the returned node: labels,
            # taints (incl. not-ready), finalizer (reference:
            # provisioner.go:152-160 + constraints.go:69-105)
            template = vnode.constraints.to_node()
            node.metadata.labels = {**template.metadata.labels, **node.metadata.labels}
            node.metadata.labels[lbl.PROVISIONER_NAME_LABEL] = self.provisioner.name
            # stamp the launch trace onto the Node: the ready transition
            # happens minutes later in another reconcile, and this
            # annotation is how node.ready joins the launch trace
            if trace:
                node.metadata.annotations[obs.TRACE_ANNOTATION] = trace
            node.metadata.annotations.setdefault(
                lbl.LAUNCH_TOKEN_ANNOTATION, token
            )
            node.metadata.finalizers = list(
                set(node.metadata.finalizers) | set(template.metadata.finalizers)
            )
            node.spec.taints = node.spec.taints + [
                t for t in template.spec.taints if t.key not in {x.key for x in node.spec.taints}
            ]
            try:
                self.cluster.create("nodes", node)
            except Conflict:
                # node self-registered first — idempotent create
                # (reference: provisioner.go:155-164)
                pass
            if self.journal is not None:
                self.journal.mark_created(token, node.metadata.name)
            self._bind(vnode.pods, node.metadata.name)
            if self.journal is not None:
                # bind done: the launch is fully committed across all three
                # stores — the journal entry has nothing left to protect
                self.journal.resolve(token)
            from karpenter_tpu.kube.events import recorder_for

            recorder_for(self.cluster).event(
                "Node", node.metadata.name, "Launched",
                f"launched {node.metadata.labels.get(lbl.INSTANCE_TYPE, '?')} "
                f"for provisioner {self.provisioner.name}; bound {len(vnode.pods)} pod(s)",
            )
            return True
        except Exception:
            logger.exception("launching node")
            from karpenter_tpu.kube.events import recorder_for

            recorder_for(self.cluster).event(
                "Provisioner", self.provisioner.name, "LaunchFailed",
                "node launch failed; see controller logs", type="Warning",
                decision_id=self.last_decision_id,
            )
            # fast retry: the pods are still provisionable — re-enter the
            # batcher for the NEXT round (paced by the batch idle window)
            # instead of stalling a full selection requeue period per
            # transient launch failure; provision_once's key dedupe absorbs
            # any concurrent selection re-submit of the same pods
            for pod in vnode.pods:
                if is_provisionable(pod):
                    self.add(pod)
                    with self._pending_lock:
                        self._requeued_keys.add(pod.key)
            return False

    def _bind(self, pods: List[Pod], node_name: str) -> None:
        from karpenter_tpu import obs

        start = time.perf_counter()
        ok = True
        with obs.tracer().span(
            "provision.bind", attrs={"node": node_name, "pods": len(pods)}
        ) as sp:
            for pod in pods:
                try:
                    # re-check against the LIVE pod: a rebalance can hand
                    # the shard to another replica between this replica's
                    # solve and its bind, and that replica may have bound
                    # the pod already — binds are re-checked, never
                    # duplicated (docs/fleet.md). A pod the cluster does
                    # not know (test harnesses inject those) binds as-is.
                    live = self.cluster.try_get(
                        "pods", pod.metadata.name, namespace=pod.metadata.namespace
                    )
                    if live is not None and live.spec.node_name:
                        if live.spec.node_name != node_name:
                            metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                                reason="already_bound"
                            ).inc()
                        continue
                    self.cluster.bind(live if live is not None else pod, node_name)
                except Exception:
                    ok = False
                    logger.exception("binding pod %s", pod.key)
            sp.set_attribute("ok", ok)
        metrics.BIND_DURATION.labels(result="success" if ok else "error").observe(
            time.perf_counter() - start
        )


def spec_hash(provisioner: Provisioner) -> int:
    """Change detection for worker hot-swap
    (reference: controller.go:119 hashstructure of spec)."""
    c = provisioner.spec.constraints
    return hash(
        (
            tuple(sorted(c.labels.items())),
            tuple((t.key, t.value, t.effect) for t in c.taints),
            tuple(
                (r.key, r.operator, tuple(r.values)) for r in c.requirements.requirements
            ),
            str(c.provider),
            provisioner.spec.ttl_seconds_after_empty,
            provisioner.spec.ttl_seconds_until_expired,
            provisioner.spec.solver,
            tuple(sorted((provisioner.spec.limits.resources if provisioner.spec.limits else {}).items())),
        )
    )


class ProvisioningController:
    """Reconciles Provisioner objects into running workers
    (reference: provisioning/controller.go:43-154)."""

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        start_workers: bool = True,
        default_solver: str = SOLVER_FFD,
        solver_service_address: Optional[str] = None,
        ownership=None,
        journal=None,
        pack_checksum: Optional[bool] = None,
        canary_rate: Optional[float] = None,
        solver_stream: Optional[bool] = None,
        solver_shm_dir: Optional[str] = None,
        solver_delta: Optional[bool] = None,
        unschedulable_event_rounds: int = 3,
        warm_pool: bool = False,
    ):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.start_workers = start_workers  # False: tests drive provision_once inline
        # warm-pool claiming: workers steal onto standing speculative
        # nodes before solving (controllers/warmpool.py launches them)
        self.warm_pool = warm_pool
        # decision observability: consecutive failed rounds before a pod's
        # PodUnschedulable Warning event (docs/decisions.md)
        self.unschedulable_event_rounds = unschedulable_event_rounds
        self.default_solver = default_solver
        self.solver_service_address = solver_service_address
        # pack-integrity knobs (docs/integrity.md), threaded to every
        # worker's scheduler; None = the KARPENTER_PACK_CHECKSUM /
        # KARPENTER_CANARY_RATE env twins
        self.pack_checksum = pack_checksum
        self.canary_rate = canary_rate
        # streaming solver transport + zero-copy shm arena (None = the
        # KARPENTER_SOLVER_STREAM / KARPENTER_SOLVER_SHM_DIR env twins)
        self.solver_stream = solver_stream
        self.solver_shm_dir = solver_shm_dir
        # resident delta encoding (None = the KARPENTER_SOLVER_DELTA twin)
        self.solver_delta = solver_delta
        self.journal = journal  # write-ahead launch journal, shared by workers
        # fleet.ShardManager (or None = this replica owns everything):
        # reconcile only runs workers for owned shards, and each worker's
        # launch path re-checks through the same manager
        self.ownership = ownership
        self.workers: Dict[str, ProvisionerWorker] = {}  # guarded-by: self._lock
        self._hashes: Dict[str, int] = {}  # guarded-by: self._lock
        # provisioners with a live gauge series — a failed Apply never
        # creates a worker, so stop()/teardown can't rely on self.workers
        # to know which series to drop. Mutated from per-provisioner
        # reconcile threads and iterated by stop(): same lock as the
        # worker table.
        self._gauged: set = set()  # guarded-by: self._lock
        self._lock = threading.Lock()

    def reconcile(self, name: str) -> Optional[float]:
        provisioner = self.cluster.try_get("provisioners", name, namespace="")
        if provisioner is None or provisioner.metadata.deletion_timestamp is not None:
            self._teardown(name)
            return None
        if self.ownership is not None and not self.ownership.owns(name):
            # another replica's shard: never run a worker for it here (the
            # split-brain P0 — two workers would double-launch its pods).
            # Re-check on a lease-scale cadence so a rebalance lands fast.
            self._teardown(name)
            return OWNERSHIP_RECHECK_INTERVAL
        # Active condition lifecycle (reference: provisioner_status.go:38-41,
        # the knative living ``Active`` set): every Apply outcome lands in
        # status.conditions, and the status write happens only on change so
        # steady-state requeues don't churn the apiserver.
        try:
            self.apply(provisioner)
        except Exception as e:
            reason = (
                "ValidationFailed" if isinstance(e, ValueError) else "ApplyFailed"
            )
            self._set_active(provisioner, "False", reason, str(e))
            raise
        self._set_active(provisioner, "True")
        # requeue to pick up instance-type catalog drift
        # (reference: provisioning/controller.go:82, 5 minutes)
        return REQUEUE_INTERVAL

    def _set_active(
        self, provisioner: Provisioner, value: str, reason: str = "", message: str = ""
    ) -> None:
        """Persist the Active condition through the status subresource.
        The live (cached) object is never mutated here: on a failed write
        the cache still holds the old condition, so the next reconcile's
        comparison re-detects the drift and retries. lastTransitionTime
        moves only when the status value flips (knative semantics)."""
        from karpenter_tpu.api.provisioner import ACTIVE, Condition
        from karpenter_tpu.kube import serde

        metrics.PROVISIONER_ACTIVE.labels(provisioner=provisioner.name).set(
            1 if value == "True" else 0
        )
        # reconcile threads race stop()'s iteration over this set
        with self._lock:
            self._gauged.add(provisioner.name)
        cond = provisioner.status.condition(ACTIVE)
        if cond is not None and (cond.status, cond.reason, cond.message) == (
            value, reason, message,
        ):
            return
        ltt = (
            self.cluster.clock()
            if cond is None or cond.status != value
            else cond.last_transition_time
        )
        wire = serde.prov_condition_to_wire(
            Condition(
                type=ACTIVE, status=value, reason=reason, message=message,
                last_transition_time=ltt,
            )
        )
        # arrays replace wholesale under RFC 7386: the patch must carry the
        # FULL conditions list, not just Active, or conditions owned by
        # other writers get erased. Read-modify-write against the freshest
        # cache copy (a raced write loses benignly — the next reconcile's
        # comparison re-detects the drift and retries).
        from karpenter_tpu.kube.patch import upsert_condition

        live = self.cluster.try_get("provisioners", provisioner.name, namespace="")
        base = (live or provisioner).status.conditions
        wire_conditions = upsert_condition(
            [serde.prov_condition_to_wire(c) for c in base], wire
        )
        try:
            self.cluster.patch_status(
                "provisioners", provisioner.name,
                {"conditions": wire_conditions}, namespace="",
            )
        except Exception:
            # a lost condition write surfaces again on the next reconcile;
            # it must never mask the Apply outcome itself
            logger.debug("provisioner Active condition write failed", exc_info=True)

    def apply(self, provisioner: Provisioner) -> None:
        """Validate, default, layer live catalog requirements, and (re)start
        the worker when the spec changed (reference: controller.go:93-116).
        Defaulting re-runs here so the control loop is safe without the
        webhook (reference: provisioning/controller.go:94-95)."""
        default_provisioner(provisioner, self.default_solver)
        self.cloud_provider.default(provisioner.spec.constraints)
        errs = validate_provisioner(provisioner)
        errs += self.cloud_provider.validate(provisioner.spec.constraints)
        if errs:
            raise ValueError(f"invalid provisioner {provisioner.name}: {errs}")
        h = spec_hash(provisioner)
        enriched = self._with_catalog(provisioner)
        # check + swap is one critical section so concurrent applies cannot
        # both pass the hash check and leak a started worker
        with self._lock:
            if self._hashes.get(provisioner.name) == h:
                # still refresh catalog requirements (requeue path)
                self.workers[provisioner.name].provisioner = enriched
                return
            old = self.workers.pop(provisioner.name, None)
            name = provisioner.name
            worker = ProvisionerWorker(
                enriched, self.cluster, self.cloud_provider,
                solver_service_address=self.solver_service_address,
                owned=(
                    (lambda: self.ownership.owns(name))
                    if self.ownership is not None else None
                ),
                fenced=(
                    self.ownership.fenced
                    if self.ownership is not None
                    and hasattr(self.ownership, "fenced") else None
                ),
                journal=self.journal,
                pack_checksum=self.pack_checksum,
                canary_rate=self.canary_rate,
                solver_stream=self.solver_stream,
                solver_shm_dir=self.solver_shm_dir,
                solver_delta=self.solver_delta,
                unschedulable_event_rounds=self.unschedulable_event_rounds,
                warm_pool=self.warm_pool,
            )
            self.workers[provisioner.name] = worker
            self._hashes[provisioner.name] = h
            if self.start_workers:
                worker.start()
        if old:
            old.stop()

    def _with_catalog(self, provisioner: Provisioner) -> Provisioner:
        instance_types = self.cloud_provider.get_instance_types(
            provisioner.spec.constraints.provider
        )
        c = provisioner.spec.constraints.clone()
        c.requirements = c.requirements.merge(catalog_requirements(instance_types))
        out = Provisioner(metadata=provisioner.metadata, spec=provisioner.spec, status=provisioner.status)
        out.spec = type(provisioner.spec)(
            constraints=c,
            ttl_seconds_after_empty=provisioner.spec.ttl_seconds_after_empty,
            ttl_seconds_until_expired=provisioner.spec.ttl_seconds_until_expired,
            limits=provisioner.spec.limits,
            solver=provisioner.spec.solver,
        )
        return out

    def _teardown(self, name: str) -> None:
        with self._lock:
            worker = self.workers.pop(name, None)
            self._hashes.pop(name, None)
            self._gauged.discard(name)
        if worker:
            worker.stop()
        # drop the gauge series: a deleted provisioner must not linger on
        # the scrape as managed-and-failing. Several prometheus_client
        # releases raise KeyError from remove() for a never-gauged label
        # set (e.g. a reconcile of a name whose Apply never ran), and that
        # must not escape reconcile().
        try:
            metrics.PROVISIONER_ACTIVE.remove(name)
        except KeyError:
            pass

    def release_shard(self, name: str) -> None:
        """``ShardManager.on_lost`` hook: stop this provisioner's worker
        SYNCHRONOUSLY — by the time the lease duration elapses and a
        survivor claims the shard, this replica must no longer be solving,
        launching, or binding for it."""
        with self._lock:
            worker = self.workers.pop(name, None)
            self._hashes.pop(name, None)
        if worker:
            worker.stop()

    def list_workers(self) -> List[ProvisionerWorker]:
        """Active workers sorted by provisioner name — selection priority
        order (reference: controller.go:136-145)."""
        with self._lock:
            return [self.workers[k] for k in sorted(self.workers)]

    def submit(self, pod: Pod) -> Optional[ProvisionerWorker]:
        """Inject a pod straight into the first admitting worker's batcher,
        bypassing the selection controller — the interruption subsystem's
        proactive-replacement hook: pods released from a disrupted node
        enter the provisioning pipeline BEFORE the node drains, so
        replacement capacity is launching while the old node still runs.
        Returns the worker, or None when no provisioner admits the pod
        (the caller leaves it pending for selection to retry)."""
        # volume topology must ride along even though selection is bypassed:
        # a replacement pod with a zone-bound PV packed into another zone
        # would bind where its volume cannot attach — and selection cannot
        # repair it later (is_pending short-circuits its reconcile)
        from karpenter_tpu.controllers.selection import VolumeTopology

        VolumeTopology(self.cluster).inject(pod)
        for worker in self.list_workers():
            if not worker.provisioner.spec.constraints.validate_pod(pod):
                worker.add(pod)
                return worker
        return None

    def stop(self) -> None:
        # snapshot under the lock: reconcile threads may still be mutating
        # both tables while shutdown walks them
        with self._lock:
            names = set(self.workers)
            # provisioners whose Apply only ever failed have a gauge series
            # but no worker — clear those too
            names |= self._gauged
        for name in names:
            self._teardown(name)
