"""Speculative warm-pool provisioning: launch ahead of forecast demand.

The arrival forecaster (karpenter_tpu/forecast/) predicts, per
provisioner, how many pods will arrive within the launch-to-ready
horizon. This controller turns the prediction's upper band into standing
capacity: every wave it compares predicted node demand against the
provisioner's current warm pool and launches the deficit *speculatively*
— through the same constraint-template path the provisioning worker
uses, under the same fence/ownership/limit guards, journaled with the
``speculative`` marker so crash recovery and the TTL reaper own every
outcome:

- a speculative launch writes its Node with the ``karpenter.sh/warm-pool``
  annotation and leaves its journal entry OPEN — the entry is the TTL
  breadcrumb, not an orphan;
- demand claims the node BEFORE the solver: the provisioning worker's
  warm-hit steal binds pods to a warm node, removes the annotation, and
  resolves the journal token;
- no demand within ``--warm-pool-ttl`` → the GC replay ladder
  (launch/recovery.py) reclaims the instance (``SPECULATION_EXPIRED``);
- a crash anywhere in between → the ordinary adopt/confirm ladder, with
  adopted speculative orphans re-entering the pool.

Brownout rung 1 pauses speculation (``set_paused`` — re-asserted every
brownout tick, checked again between launches so a rung change freezes a
wave mid-flight); fenced replicas never speculative-create. Waves land in
the decision audit ring, so ``tools/whatif.py`` can re-simulate pool
policy against recorded demand.
"""

from __future__ import annotations

import logging
import math
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node
from karpenter_tpu.cloudprovider.types import NodeRequest
from karpenter_tpu.kube.client import Cluster, Conflict
from karpenter_tpu.launch import recovery

logger = logging.getLogger("karpenter.warmpool")

# Wave cadence: fast enough that a flash crowd's forecast turns into
# standing capacity within one launch-to-ready horizon, slow enough that
# the node scan + forecast reads stay negligible.
WARM_POOL_INTERVAL = 10.0

# Per-provisioner standing-pool ceiling: the upper band is a prediction,
# and an unbounded predictor must never be able to buy unbounded capacity.
DEFAULT_MAX_WARM_NODES = 10

WARM_POOL_KEY = "__warmpool__"  # never a valid node name (not DNS-1123)


class WarmPoolController:
    """The standing speculation wave (same self-rescheduling-reconcile
    idiom as the GC sweep). ``provisioning`` is the
    ``ProvisioningController`` — the workers it runs carry the enriched
    constraint templates, the fence, and the ownership checks every
    speculative create re-uses."""

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider,
        provisioning,
        journal=None,
        ownership=None,
        interval: float = WARM_POOL_INTERVAL,
        warm_pool_ttl: float = recovery.DEFAULT_WARM_POOL_TTL,
        max_nodes: int = DEFAULT_MAX_WARM_NODES,
    ):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.provisioning = provisioning
        self.journal = journal
        self.ownership = ownership  # fleet.ShardManager, or None = own all
        self.interval = interval
        self.warm_pool_ttl = warm_pool_ttl
        self.max_nodes = max_nodes
        # brownout rung 1 (resilience/brownout.py): True stops NEW
        # speculation — checked at wave start AND between launches so a
        # rung arriving mid-wave freezes the remainder; existing warm
        # nodes stay claimable and age out through the TTL
        self._paused = False  # guarded-by: self._mu
        self._mu = threading.Lock()
        # bench/test observability beside the prometheus counters
        self.speculative_launches = 0
        self.waves = 0

    # -- brownout surface ----------------------------------------------------
    def set_paused(self, paused: bool) -> None:
        with self._mu:
            changed = self._paused != bool(paused)
            self._paused = bool(paused)
        metrics.WARMPOOL_PAUSED.set(1 if paused else 0)
        if changed:
            logger.warning(
                "warm-pool speculation %s",
                "paused (brownout)" if paused else "resumed",
            )

    def paused(self) -> bool:
        with self._mu:
            return self._paused

    # -- reconcile -----------------------------------------------------------
    def reconcile(self, key: str) -> Optional[float]:
        if key != WARM_POOL_KEY:
            return None
        from karpenter_tpu import obs
        from karpenter_tpu.cloudprovider.metrics import reconciling_controller

        reconciling_controller.set("warmpool")
        try:
            with obs.tracer().span("warmpool.wave") as sp:
                self._wave(sp)
        except Exception:
            # one raised wave defers speculation a tick; demand still
            # provisions normally through the worker path
            logger.exception("warm-pool wave failed")
        self.waves += 1
        return self.interval

    def _wave(self, span) -> None:
        from karpenter_tpu import obs

        eng = obs.forecaster()
        if eng is None:
            span.set_attribute("skipped", "no_forecaster")
            return
        if self.paused():
            span.set_attribute("skipped", "paused")
            return
        if self.ownership is not None and getattr(
            self.ownership, "fenced", lambda: False
        )():
            # apiserver unreachable past lease expiry: a peer may own
            # these shards already — speculating now is the split-brain
            # double-launch the fence exists to prevent
            metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(reason="fenced").inc()
            span.set_attribute("skipped", "fenced")
            return
        launched_total = 0
        for worker in self.provisioning.list_workers():
            name = worker.provisioner.name
            if self.ownership is not None and not self.ownership.owns(name):
                continue
            forecast = eng.predict(name)
            want = self._nodes_wanted(forecast, eng)
            standing = len(self._warm_nodes(name))
            metrics.WARMPOOL_SIZE.labels(provisioner=name).set(standing)
            deficit = min(want, self.max_nodes) - standing
            if deficit <= 0:
                continue
            launched_total += self._launch_wave(
                worker, deficit, forecast, standing, span
            )
        span.set_attribute("launched", launched_total)

    @staticmethod
    def _nodes_wanted(forecast: dict, eng) -> int:
        """Predicted pod arrivals (upper band) over the launch-to-ready
        horizon, converted to nodes through the observed pods-per-node
        packing density."""
        pods = float(forecast.get("predicted_pods_upper", 0.0))
        if pods <= 0:
            return 0
        return int(math.ceil(pods / max(eng.pods_per_node(), 1.0)))

    def _warm_nodes(self, provisioner: str) -> List[Node]:
        """This provisioner's standing (unclaimed, not terminating) warm
        nodes, name-sorted so the steal and the wave agree on order."""
        out = [
            n for n in self.cluster.nodes()
            if n.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) == provisioner
            and lbl.WARM_POOL_ANNOTATION in n.metadata.annotations
            and n.metadata.deletion_timestamp is None
        ]
        out.sort(key=lambda n: n.metadata.name)
        return out

    def _launch_wave(
        self, worker, deficit: int, forecast: dict, standing: int, span
    ) -> int:
        """Launch ``deficit`` speculative nodes for one provisioner and
        record the wave as a decision. The audit record lands BEFORE the
        launches (same discipline as the provisioning round): even a wave
        whose creates crash leaves its decision replayable."""
        name = worker.provisioner.name
        decision_id = self._record_wave(
            name, deficit, forecast, standing, span
        )
        def one(_i: int) -> bool:
            # brownout rung landed mid-wave: tasks not yet started freeze
            # here — the remainder of the wave never reaches the cloud
            if self.paused():
                span.set_attribute("froze", "paused")
                return False
            return self._launch_speculative(worker, decision_id, span)

        if deficit == 1:
            launched = 1 if one(0) else 0
        else:
            # concurrent creates, same shape as the worker's launch fan-out:
            # a deficit of N must not pay N serial launch latencies — the
            # whole point is standing capacity BEFORE the demand lands
            with ThreadPoolExecutor(max_workers=min(8, deficit)) as pool:
                launched = sum(bool(ok) for ok in pool.map(one, range(deficit)))
        if launched:
            from karpenter_tpu.kube.events import recorder_for

            recorder_for(self.cluster).event(
                "Provisioner", name, "SpeculativeLaunch",
                f"launched {launched} warm-pool node(s) ahead of demand "
                f"(forecast {forecast.get('predicted_pods_upper', 0.0):.1f} "
                f"pods over {forecast.get('horizon_s', 0.0):.0f}s, "
                f"{standing} standing)",
                decision_id=decision_id,
            )
        span.set_attribute(f"launched.{name}", launched)
        return launched

    def _record_wave(
        self, provisioner: str, deficit: int, forecast: dict, standing: int,
        span,
    ) -> str:
        """Warm-pool waves ride the same decision ring as provisioning
        rounds (docs/decisions.md): zero pods considered, the speculative
        intent in ``state`` — what tools/whatif.py re-simulates."""
        from karpenter_tpu import obs

        try:
            rec = obs.decision_log().record_round(
                provisioner=provisioner,
                pods=[],
                nodes=[],
                trace_id=span.trace_id,
                state={
                    "warm_pool_wave": True,
                    "deficit": deficit,
                    "standing": standing,
                    "forecast": {
                        k: v for k, v in forecast.items()
                        if isinstance(v, (int, float, str))
                    },
                },
            )
            return rec["id"] if rec is not None else ""
        except Exception:
            logger.debug("warm-pool wave record failed", exc_info=True)
            return ""

    def _launch_speculative(self, worker, decision_id: str, parent_span) -> bool:
        """One speculative create through the provisioning template path:
        same guards, same journal, same token discipline — differing only
        in the ``speculative`` journal marker, the warm annotation, and
        the entry deliberately staying OPEN (no pods to bind; resolution
        belongs to the claim or the TTL reaper)."""
        from karpenter_tpu import obs

        name = worker.provisioner.name
        try:
            # late split-brain guards, re-checked per create like the
            # worker's _launch_one — a wave outlives a rebalance
            if worker.fenced():
                metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                    reason="fenced"
                ).inc()
                logger.warning(
                    "skipping speculative launch for %s: replica fenced", name
                )
                return False
            if not worker.owned():
                metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                    reason="lost_ownership"
                ).inc()
                logger.warning(
                    "skipping speculative launch for %s: shard lease lost",
                    name,
                )
                return False
            # fresh limits check against live status: speculation must
            # never spend capacity the provisioner's limits reserve for
            # real demand
            live = self.cluster.try_get("provisioners", name, namespace="")
            prov = live if live is not None else worker.provisioner
            if prov.spec.limits is not None:
                err = prov.spec.limits.exceeded_by(prov.status.resources)
                if err:
                    logger.info("skipping speculative launch: %s", err)
                    return False
            constraints = worker.provisioner.spec.constraints
            options = self.cloud_provider.get_instance_types(
                constraints.provider
            )
            with obs.tracer().span(
                "warmpool.launch",
                parent=parent_span,
                attrs={"provisioner": name, "decision_id": decision_id},
            ) as sp:
                trace = obs.to_traceparent(sp)
                token = uuid.uuid4().hex
                sp.set_attribute("launch_token", token[:12])
                if self.journal is not None:
                    self.journal.record_intent(
                        token, name, trace, speculative=True
                    )
                node = self.cloud_provider.create(
                    NodeRequest(
                        template=constraints,
                        instance_type_options=options,
                        launch_token=token,
                    )
                )
                template = constraints.to_node()
                node.metadata.labels = {
                    **template.metadata.labels, **node.metadata.labels,
                }
                node.metadata.labels[lbl.PROVISIONER_NAME_LABEL] = name
                node.metadata.annotations[lbl.WARM_POOL_ANNOTATION] = "true"
                if trace:
                    node.metadata.annotations[obs.TRACE_ANNOTATION] = trace
                node.metadata.annotations.setdefault(
                    lbl.LAUNCH_TOKEN_ANNOTATION, token
                )
                node.metadata.finalizers = list(
                    set(node.metadata.finalizers)
                    | set(template.metadata.finalizers)
                )
                node.spec.taints = node.spec.taints + [
                    t for t in template.spec.taints
                    if t.key not in {x.key for x in node.spec.taints}
                ]
                try:
                    self.cluster.create("nodes", node)
                except Conflict:
                    pass  # node self-registered first — idempotent create
                if self.journal is not None:
                    # entry stays OPEN past mark_created: a speculative
                    # launch has no bind to resolve it — the claim or the
                    # TTL reaper does
                    self.journal.mark_created(token, node.metadata.name)
            self.speculative_launches += 1
            metrics.WARMPOOL_SPECULATIVE_LAUNCHES.labels(
                provisioner=name
            ).inc()
            return True
        except Exception:
            # the journal entry (if written) stays: recovery confirms
            # NEVER_LAUNCHED or adopts, exactly like a crashed real launch
            logger.exception("speculative launch for %s", name)
            return False

    def register(self, manager) -> None:
        manager.enqueue("warmpool", WARM_POOL_KEY)
