"""Garbage collection: the crash-consistency sweep over live instances.

The launch path is three writes against three stores (cloud create → Node
object → binds) and the write-ahead journal (launch/journal.py) brackets
them; this controller is the read side that makes the bracket mean
something. Each sweep, on the shards this replica owns (PR-6
``ShardManager`` routing — two replicas must never adopt or reap the same
instance):

1. **Journal replay** — every unresolved entry old enough to have lost
   its process runs the adopt/confirm ladder (launch/recovery.py):
   re-describe the token against ``CloudProvider.list_instances()``,
   adopt the instance no Node tracks (write the Node, rejoin the launch
   trace), or confirm it never launched and drop the entry.
2. **Leak sweep** — live instances with no Node AND no journal entry
   (token-less out-of-band launches, pre-token builds, a journal lost
   with its host) older than the grace period are terminated through the
   PR-1 orchestrator's reaper: capacity nobody can account for must die,
   not bill forever. The grace period is what protects instances still
   mid-registration — including a multi-host TPU slice's pending
   siblings, which stay token-less until their claiming creates land.

Reference Karpenter ships the same loop as instance tagging + node
garbage collection; this one adds the journal so interrupted launches
are *adopted* instead of re-paid.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.types import LiveInstance
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.launch import recovery
from karpenter_tpu.launch.journal import LaunchJournal

logger = logging.getLogger("karpenter.gc")

# Sweep cadence: one GC period is the adoption-latency bar the chaos
# crash-storm holds recovery to, so it must stay well under the emptiness
# TTL that would reap an adopted-then-idle node.
GC_INTERVAL = 30.0

# How old an untracked, unjournaled instance must be before it is declared
# a leak: registration (create → Node write → ready) takes seconds, and a
# multi-host slice's pending siblings wait token-less for their claiming
# creates — reaping those would kill a healthy launch in flight.
LEAK_GRACE_PERIOD = 120.0

GC_POLL_KEY = "__gc__"  # never a valid node name (not DNS-1123)


class GarbageCollectionController:
    """The standing sweep (same self-rescheduling-reconcile idiom as the
    interruption poll). ``journal`` may be None — the leak sweep still
    runs; adoption needs the journal's breadcrumbs."""

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider,
        journal: Optional[LaunchJournal] = None,
        termination=None,
        ownership=None,
        gc_interval: float = GC_INTERVAL,
        grace_period: float = LEAK_GRACE_PERIOD,
        replay_after: Optional[float] = None,
        warm_pool_ttl: float = recovery.DEFAULT_WARM_POOL_TTL,
    ):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.journal = journal
        self.termination = termination  # TerminationController (terminator)
        self.ownership = ownership  # fleet.ShardManager, or None = own all
        self.gc_interval = gc_interval
        self.grace_period = grace_period
        # unclaimed speculative (warm-pool) launches older than this are
        # reclaimed by the replay ladder even though their instance is live
        self.warm_pool_ttl = warm_pool_ttl
        # entries younger than this may still have a live launching
        # process. The floor is recovery.DEFAULT_REPLAY_AFTER, sized past
        # the WORST-case intent-to-commit window (fleet-limiter stall +
        # metered retry deadline): resolving an entry NEVER_LAUNCHED while
        # its create is still in flight would destroy the very breadcrumb
        # a subsequent crash needs — the orphan would then age into the
        # leak sweep instead of being adopted. A sweep cadence slower than
        # the floor raises the age-in with it.
        self.replay_after = (
            replay_after if replay_after is not None
            else max(gc_interval, recovery.DEFAULT_REPLAY_AFTER)
        )
        # bench/test observability beside the prometheus counters
        self.adopted = 0
        self.leaks_terminated = 0
        self.replays = 0
        self.sweeps = 0
        self.speculation_reclaimed = 0
        self.consolidation_waves_replayed = 0

    # -- shard routing -----------------------------------------------------
    def _owns(self, shard: str) -> bool:
        from karpenter_tpu.fleet import DEFAULT_SHARD

        if self.ownership is None:
            return True
        if shard and self.cluster.try_get(
            "provisioners", shard, namespace=""
        ) is not None:
            return self.ownership.owns(shard)
        # unattributed work (no provisioner, or a deleted one) belongs to
        # the default shard — same routing as interruption notices
        return self.ownership.owns(DEFAULT_SHARD)

    def _shard_for_instance(
        self,
        live: LiveInstance,
        entries_by_token: Dict[str, "recovery.LaunchRecord"],
    ) -> str:
        """A leaked instance has no Node to read the provisioner label
        from — only its journal entry (if any, from this sweep's snapshot)
        attributes it."""
        if live.launch_token:
            entry = entries_by_token.get(live.launch_token)
            if entry is not None:
                return entry.provisioner
        return ""

    # -- reconcile ---------------------------------------------------------
    def reconcile(self, key: str) -> Optional[float]:
        if key != GC_POLL_KEY:
            return None
        from karpenter_tpu import obs
        from karpenter_tpu.cloudprovider.metrics import reconciling_controller

        reconciling_controller.set("garbage_collection")
        try:
            with obs.tracer().span("gc.sweep") as sp:
                self._sweep(sp)
        except Exception:
            # one raised sweep (a flaked list, a raced write) defers a GC
            # round; the next tick re-checks everything from scratch
            logger.exception("garbage-collection sweep failed")
        self.sweeps += 1
        return self.gc_interval

    def _sweep(self, span) -> None:
        if self.ownership is not None and getattr(
            self.ownership, "fenced", lambda: False
        )():
            # apiserver unreachable past lease expiry (docs/partition.md):
            # this replica can neither trust its Node view nor its shard
            # claims — adopting or terminating now could reap a peer's
            # healthy in-flight launch. Skip the whole sweep until the
            # control plane answers again.
            metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(reason="fenced").inc()
            span.set_attribute("skipped", "fenced")
            return
        instances = self.cloud_provider.list_instances()
        if instances is NotImplemented or instances is None:
            # this vendor has no inventory surface: recovery can still
            # resolve never-launched entries? No — without a list there is
            # no way to tell "never launched" from "invisible", so the
            # provider opts out of the sweep entirely (journal entries
            # keep accumulating as the operator's signal)
            span.set_attribute("skipped", "no_list_surface")
            return
        by_token: Dict[str, LiveInstance] = {
            inst.launch_token: inst
            for inst in instances
            if inst.launch_token
        }
        span.set_attribute("instances", len(instances))
        # ONE journal snapshot and ONE node index per sweep: the per-
        # instance journal.get (a flock'd file parse or an apiserver GET)
        # and per-instance full-node scans made the sweep O(n×m) with I/O.
        # The pre-replay snapshot is also the CORRECT shield for the leak
        # sweep: an entry the replay ladder resolves this sweep (adopt /
        # confirm) keeps protecting its instance until next sweep re-reads.
        entries = (
            list(self.journal.unresolved()) if self.journal is not None else []
        )
        index = recovery.NodeIndex(self.cluster)
        self._replay_journal(by_token, entries, index)
        self._sweep_leaks(
            instances, {e.token: e for e in entries}, index,
        )

    def _replay_journal(
        self,
        by_token: Dict[str, LiveInstance],
        entries,
        index: "recovery.NodeIndex",
    ) -> None:
        if self.journal is None:
            return
        from karpenter_tpu import obs

        now = self.cluster.clock()
        for entry in entries:
            if not self._owns(entry.provisioner):
                continue
            # the replay span rejoins the original launch trace: the
            # journal stored the launch span's traceparent at intent time
            parent = obs.from_traceparent(entry.trace)
            with obs.tracer().span(
                "gc.replay",
                parent=parent,
                attrs={
                    "token": entry.token[:12],
                    "provisioner": entry.provisioner,
                    "state": entry.state,
                },
            ) as sp:
                outcome = recovery.replay_entry(
                    self.journal, self.cluster, self.cloud_provider,
                    entry, by_token, now, replay_after=self.replay_after,
                    index=index, warm_pool_ttl=self.warm_pool_ttl,
                    reap=self._reap,
                )
                sp.set_attribute("outcome", outcome)
            if outcome == recovery.PENDING:
                continue
            self.replays += 1
            metrics.LAUNCH_JOURNAL_REPLAYS.labels(outcome=outcome).inc()
            if outcome == recovery.CONSOLIDATION_REPLAYED:
                self.consolidation_waves_replayed += 1
                from karpenter_tpu.kube.events import recorder_for

                recorder_for(self.cluster).event(
                    "Provisioner", entry.provisioner,
                    "ConsolidationWaveReplayed",
                    f"replayed crashed consolidation wave "
                    f"{entry.token[:20]} (decision "
                    f"{entry.decision_id or 'unknown'}): surviving victims "
                    "un-cordoned, journal entry resolved",
                    type="Warning",
                )
            if outcome == recovery.SPECULATION_EXPIRED:
                self.speculation_reclaimed += 1
                metrics.WARMPOOL_EXPIRED.inc()
                from karpenter_tpu.kube.events import recorder_for

                recorder_for(self.cluster).event(
                    "Node", by_token[entry.token].id, "SpeculationExpired",
                    f"reclaimed speculative warm-pool capacity for "
                    f"provisioner {entry.provisioner}: no demand landed "
                    f"within the {self.warm_pool_ttl:.0f}s TTL",
                    type="Warning",
                )
            if outcome == recovery.ADOPTED:
                self.adopted += 1
                metrics.LAUNCH_ORPHANS_ADOPTED.inc()
                from karpenter_tpu.kube.events import recorder_for

                recorder_for(self.cluster).event(
                    "Node", by_token[entry.token].id, "Adopted",
                    f"adopted orphan instance for provisioner "
                    f"{entry.provisioner}: its launching process died "
                    "before the Node object was written",
                    type="Warning",
                )

    def _sweep_leaks(
        self,
        instances: List[LiveInstance],
        entries_by_token: Dict[str, "recovery.LaunchRecord"],
        index: "recovery.NodeIndex",
    ) -> None:
        from karpenter_tpu import obs

        now = self.cluster.clock()
        for live in instances:
            if index.find(live) is not None:
                continue
            if live.launch_token and live.launch_token in entries_by_token:
                continue  # journaled: the replay ladder owns its fate
            age = now - live.created_at
            if age < self.grace_period:
                continue  # mid-registration or a pending multi-host sibling
            if not self._owns(
                self._shard_for_instance(live, entries_by_token)
            ):
                continue
            with obs.tracer().span(
                "gc.terminate_leak",
                attrs={"instance": live.id, "age_s": round(age, 3)},
            ):
                try:
                    self._reap(live)
                except Exception:
                    # the instance outlives one failed reap; next sweep
                    # re-finds it (delete is idempotent + retried)
                    logger.exception("terminating leaked instance %s", live.id)
                    continue
            self.leaks_terminated += 1
            metrics.LAUNCH_INSTANCES_LEAKED.inc()

    def _reap(self, live: LiveInstance) -> None:
        """Terminate an instance no Node tracks and no journal explains,
        through the PR-1 terminator (cloud delete + event) so the reap
        shares the orchestrator's teardown machinery and audit trail."""
        node = recovery.node_for_instance(self.cluster, self.cloud_provider, live)
        # the fabricated node is ephemeral — never written to the cluster;
        # it exists to drive the terminator's provider delete + event
        node.metadata.finalizers = []
        logger.warning(
            "terminating leaked instance %s (age %.0fs, token %r): no Node "
            "tracks it and no journal entry explains it",
            live.id, self.cluster.clock() - live.created_at,
            live.launch_token[:12] if live.launch_token else "",
        )
        if self.termination is not None:
            self.termination.terminator.terminate(node)
        else:
            self.cloud_provider.delete(node)

    def register(self, manager) -> None:
        manager.enqueue("garbage_collection", GC_POLL_KEY)
