"""Interruption: poll the cloud's disruption stream, drive the response.

The reference snapshot has no interruption controller (it shipped later as
the SQS/EventBridge consumer in ``pkg/controllers/interruption``); this is
that subsystem built on this framework's own event source — every cloud
provider implements ``poll_disruptions()`` (karpenter_tpu/interruption).

Two key spaces share one workqueue:

- ``POLL_KEY`` — the standing poll: drain the provider's notice queue,
  dispatch each notice to the orchestrator, requeue after
  ``poll_interval`` (the self-rescheduling-reconcile idiom the catalog
  refresh also uses).
- a node name — that node's grace-period deadline: requeue until the node
  is gone (drain completed) or the deadline passes (force-terminate).

Replacement lead time is observed from the pod watch: the orchestrator
records when each pod was injected for replacement; the watch sees the
re-bind (nodeName set again) and the difference is the histogram sample —
how long the workload waited for replacement capacity.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Dict, Optional

from karpenter_tpu import metrics
from karpenter_tpu.interruption.orchestrator import Orchestrator
from karpenter_tpu.interruption.types import DisruptionNotice
from karpenter_tpu.kube.client import Cluster

logger = logging.getLogger("karpenter.interruption")

# Notice latency budget: EC2/GCE give 30-120s warnings, so a 2s poll keeps
# the response well inside the grace period without hammering the API.
POLL_INTERVAL = 2.0

# Deadline watch granularity: how often a tracked node is re-checked while
# its grace period runs down (the drain usually finishes long before).
DEADLINE_REQUEUE = 1.0

POLL_KEY = "__poll__"  # never a valid node name (not DNS-1123)


class InterruptionController:
    def __init__(
        self,
        cluster: Cluster,
        cloud_provider,
        provisioning=None,
        termination=None,
        poll_interval: float = POLL_INTERVAL,
        ownership=None,
    ):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.poll_interval = poll_interval
        # fleet.ShardManager (or None = this replica handles everything):
        # a notice for a node whose provisioner shard another replica owns
        # is requeued to the provider stream — two replicas must never
        # orchestrate (taint/drain/force-terminate) the same node
        self.ownership = ownership
        self.foreign_notices = 0  # requeued to the owner; test observability
        self.orchestrator = Orchestrator(
            cluster, cloud_provider, provisioning, termination
        )
        self._mu = threading.Lock()
        # node name -> grace deadline (cluster-clock seconds)
        self._deadlines: Dict[str, float] = {}
        # pod key -> notice time, awaiting the replacement re-bind
        self._awaiting: Dict[str, float] = {}
        self._manager = None
        # bench/test observability (the prometheus histogram is the
        # production scrape); bounded so a long-lived process on a
        # spot-heavy fleet doesn't grow it without limit
        self.lead_times: "deque[float]" = deque(maxlen=10000)
        # watches attach at construction, not register(): inline test
        # harnesses drive reconcile() without a manager and still need the
        # re-bind observation to fire
        self.cluster.watch("pods", self._on_pod)
        self.cluster.watch("nodes", self._on_node)

    # -- observability -----------------------------------------------------
    @property
    def evicted_unready(self) -> int:
        return self.orchestrator.evicted_unready

    @property
    def notices_handled(self) -> int:
        return self.orchestrator.notices_handled

    # -- reconcile ---------------------------------------------------------
    def reconcile(self, key: str) -> Optional[float]:
        if key == POLL_KEY:
            return self._poll()
        return self._enforce_deadline(key)

    def _poll(self) -> float:
        # budget the poll round so the wire client's retries cannot stall
        # the notice stream past its own cadence (resilience/policy.py);
        # an open poll breaker yields an empty drain, not an exception
        from karpenter_tpu.resilience import Budget

        with Budget(max(self.poll_interval * 2.0, 1.0)).activate():
            for notice in self.cloud_provider.poll_disruptions():
                try:
                    self.handle_notice(notice)
                except Exception:
                    # one malformed/raced notice must not stall the stream
                    logger.exception("handling disruption notice %r", notice)
        return self.poll_interval

    def _shard_for(self, node_name: str) -> str:
        """The shard key that owns this node's lifecycle: its provisioner
        label, or the fleet's default shard for unattributed nodes. A label
        naming a DELETED provisioner also maps to the default shard — that
        key leaves every replica's shard universe, so routing to it would
        requeue the notice forever with no owner ever appearing."""
        from karpenter_tpu.api import labels as lbl
        from karpenter_tpu.fleet import DEFAULT_SHARD

        node = self.cluster.try_get("nodes", node_name, namespace="")
        if node is None:
            return DEFAULT_SHARD
        shard = node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL)
        if not shard:
            return DEFAULT_SHARD
        if self.cluster.try_get("provisioners", shard, namespace="") is None:
            return DEFAULT_SHARD
        return shard

    def _routed_away(self, notice: DisruptionNotice) -> bool:
        """True when another replica owns this notice's shard AND the
        provider accepted the requeue — the owner's next poll picks it up.
        Both HTTP wires re-offer via POST …/events/requeue, so foreign
        notices now requeue across processes too; a provider with no
        requeue surface at all answers False and the notice is handled
        locally: availability beats strict sharding, and the orchestrator's
        node-scoped actions stay exactly-once because only THIS replica
        drained the notice."""
        if self.ownership is None:
            return False
        if self.ownership.owns(self._shard_for(notice.node_name)):
            return False
        if not self.cloud_provider.requeue_disruption(notice):
            return False
        self.foreign_notices += 1
        metrics.FLEET_FOREIGN_NOTICES.inc()
        return True

    def handle_notice(self, notice: DisruptionNotice) -> None:
        if self._routed_away(notice):
            return
        metrics.INTERRUPTION_NOTICES.labels(
            kind=notice.kind, provider=self.cloud_provider.name()
        ).inc()
        # feed the consolidation risk model: every notice raises the EWMA
        # for this node's (capacity_type, zone), so the re-pack's
        # disruption-cost dimension retires reclaim-prone capacity first
        node = self.cluster.try_get("nodes", notice.node_name, namespace="")
        if node is not None:
            from karpenter_tpu.api import labels as lbl
            from karpenter_tpu.controllers.disruption import risk_tracker

            risk_tracker().observe(
                node.metadata.labels.get(lbl.CAPACITY_TYPE, ""),
                node.metadata.labels.get(lbl.TOPOLOGY_ZONE, ""),
            )
        notice_time = self.cluster.clock()

        def on_release(pod) -> None:
            # registered BEFORE the pod enters the batcher: a re-bind can
            # land microseconds after submit, and the lead-time observation
            # must already be armed
            with self._mu:
                self._awaiting[pod.key] = notice_time

        response = self.orchestrator.handle(notice, on_release=on_release)
        if response is None:
            return
        with self._mu:
            self._deadlines[response.node_name] = response.deadline
        if self._manager is not None:
            self._manager.enqueue("interruption", response.node_name)

    def _enforce_deadline(self, name: str) -> Optional[float]:
        with self._mu:
            deadline = self._deadlines.get(name)
        if deadline is None:
            return None
        node = self.cluster.try_get("nodes", name, namespace="")
        if node is None:
            # drained and terminated inside the grace period — the clean exit
            with self._mu:
                self._deadlines.pop(name, None)
            metrics.INTERRUPTION_DRAINS_COMPLETED.inc()
            return None
        now = self.cluster.clock()
        if now < deadline:
            return min(DEADLINE_REQUEUE, deadline - now)
        self.orchestrator.force_terminate(node)
        with self._mu:
            self._deadlines.pop(name, None)
        metrics.INTERRUPTION_DRAINS_COMPLETED.inc()
        return None

    # -- watches -----------------------------------------------------------
    def _on_pod(self, event: str, pod) -> None:
        # dirty-read fast path: this fires on EVERY pod event in the
        # cluster, and outside an active interruption the awaiting table is
        # empty — skip the lock then (pods being registered have their
        # nodeName cleared first, so nothing observable is missed)
        if not self._awaiting:
            return
        if event == "DELETED":
            with self._mu:
                self._awaiting.pop(pod.key, None)
            return
        if not pod.spec.node_name:
            return
        with self._mu:
            t0 = self._awaiting.pop(pod.key, None)
        if t0 is None:
            return
        lead = max(self.cluster.clock() - t0, 0.0)
        metrics.INTERRUPTION_REPLACEMENT_LEAD_TIME.observe(lead)
        self.lead_times.append(lead)

    def _on_node(self, event: str, node) -> None:
        if event != "DELETED" or self._manager is None:
            return
        with self._mu:
            tracked = node.metadata.name in self._deadlines
        if tracked:
            # close out the deadline record promptly instead of waiting for
            # the next DEADLINE_REQUEUE tick
            self._manager.enqueue("interruption", node.metadata.name)

    def register(self, manager) -> None:
        self._manager = manager
        manager.enqueue("interruption", POLL_KEY)
