"""Selection: route provisionable pods to a Provisioner worker.

Mirrors ``pkg/controllers/selection``: filter provisionable pods, validate
supportability, relax preferences on retry (5-min TTL cache), inject volume
topology from PVCs, pick the first Provisioner whose ``validate_pod`` passes,
and enqueue into its batcher (controller.go:61-115).

Divergence from the reference: required pod affinity/anti-affinity is rejected
there (controller.go:145-150); this framework schedules it (BASELINE config 3)
via topology pre-assignment (scheduling/topology.py), so the routing
controller accepts it by default, validating only that the affinity topology
keys are supported. Pass ``allow_pod_affinity=False`` for reference-parity
rejection.
"""

from __future__ import annotations

import copy
import logging
from typing import List, Optional

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import (
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    Toleration,
)
from karpenter_tpu.api.requirements import SUPPORTED_NODE_SELECTOR_OPS
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils.pod import is_provisionable
from karpenter_tpu.utils.ttlcache import TTLCache

logger = logging.getLogger("karpenter.selection")

PREFERENCE_TTL = 300.0  # reference: preferences.go:32 ExpirationTTL
REQUEUE_AFTER = 5.0  # reference: controller.go:83 verify-scheduled requeue

SUPPORTED_TOPOLOGY_KEYS = {lbl.HOSTNAME, lbl.TOPOLOGY_ZONE}


class Preferences:
    """Iterative constraint relaxation keyed by pod UID
    (reference: preferences.go:36-163).

    Each failed scheduling round removes, in order: one preferred podAffinity
    term, one preferred podAntiAffinity term, the heaviest preferred
    nodeAffinity term, one required nodeAffinity OR-term (only when more than
    one remains), then adds a toleration for PreferNoSchedule taints.
    """

    def __init__(self, clock=None):
        self.cache = TTLCache(PREFERENCE_TTL, clock=clock)

    def relax(self, pod: Pod) -> None:
        cached = self.cache.get(pod.metadata.uid)
        if cached is None:
            # first sighting: remember the original affinity/tolerations
            self.cache.set(
                pod.metadata.uid,
                (copy.deepcopy(pod.spec.affinity), copy.deepcopy(pod.spec.tolerations)),
            )
            return
        affinity, tolerations = cached
        # hand out copies: downstream injection (volume topology) mutates the
        # pod's affinity, and an aliased cache entry would accumulate those
        # injected requirements across retries
        pod.spec.affinity = copy.deepcopy(affinity)
        pod.spec.tolerations = copy.deepcopy(tolerations)
        if self._relax(pod):
            self.cache.set(
                pod.metadata.uid,
                (copy.deepcopy(pod.spec.affinity), copy.deepcopy(pod.spec.tolerations)),
            )

    def _relax(self, pod: Pod) -> bool:
        for fn in (
            self._remove_preferred_pod_affinity_term,
            self._remove_preferred_pod_anti_affinity_term,
            self._remove_preferred_node_affinity_term,
            self._remove_required_node_affinity_term,
            self._tolerate_prefer_no_schedule_taints,
        ):
            reason = fn(pod)
            if reason is not None:
                logger.debug("Relaxing soft constraints for pod %s: %s", pod.key, reason)
                return True
        return False

    def _remove_preferred_pod_affinity_term(self, pod: Pod) -> Optional[str]:
        aff = pod.spec.affinity
        if aff is None or aff.pod_affinity is None or not aff.pod_affinity.preferred:
            return None
        terms = sorted(aff.pod_affinity.preferred, key=lambda t: -t.weight)
        aff.pod_affinity.preferred = terms[1:]
        return "removed preferred pod affinity term"

    def _remove_preferred_pod_anti_affinity_term(self, pod: Pod) -> Optional[str]:
        aff = pod.spec.affinity
        if aff is None or aff.pod_anti_affinity is None or not aff.pod_anti_affinity.preferred:
            return None
        terms = sorted(aff.pod_anti_affinity.preferred, key=lambda t: -t.weight)
        aff.pod_anti_affinity.preferred = terms[1:]
        return "removed preferred pod anti-affinity term"

    def _remove_preferred_node_affinity_term(self, pod: Pod) -> Optional[str]:
        aff = pod.spec.affinity
        if aff is None or aff.node_affinity is None or not aff.node_affinity.preferred:
            return None
        terms = sorted(aff.node_affinity.preferred, key=lambda t: -t.weight)
        aff.node_affinity.preferred = terms[1:]
        return "removed heaviest preferred node affinity term"

    def _remove_required_node_affinity_term(self, pod: Pod) -> Optional[str]:
        aff = pod.spec.affinity
        if aff is None or aff.node_affinity is None or len(aff.node_affinity.required) <= 1:
            # unlike preferred terms, the last required OR-term cannot go
            return None
        aff.node_affinity.required = aff.node_affinity.required[1:]
        return "removed required node affinity OR-term"

    def _tolerate_prefer_no_schedule_taints(self, pod: Pod) -> Optional[str]:
        for t in pod.spec.tolerations:
            if t.operator == "Exists" and t.effect == "PreferNoSchedule" and not t.key:
                return None
        pod.spec.tolerations = pod.spec.tolerations + [
            Toleration(operator="Exists", effect="PreferNoSchedule")
        ]
        return "added toleration for PreferNoSchedule taints"


class VolumeTopology:
    """Translate pod PVCs into node-affinity requirements
    (reference: volumetopology.go:36-125): bound PV → the PV's required
    nodeAffinity terms; unbound PVC → StorageClass allowedTopologies."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def inject(self, pod: Pod) -> None:
        requirements = self._get_requirements(pod)
        if not requirements:
            return
        from karpenter_tpu.api.objects import Affinity, NodeAffinity

        if pod.spec.affinity is None:
            pod.spec.affinity = Affinity()
        if pod.spec.affinity.node_affinity is None:
            pod.spec.affinity.node_affinity = NodeAffinity()
        na = pod.spec.affinity.node_affinity
        if not na.required:
            na.required = [NodeSelectorTerm()]
        # appended to every required OR-term so the volume constraint holds
        # whichever branch the scheduler picks (reference appends to the terms
        # of the first required selector, volumetopology.go:52-60)
        for term in na.required:
            term.match_expressions = term.match_expressions + requirements

    def _get_requirements(self, pod: Pod) -> List[NodeSelectorRequirement]:
        requirements: List[NodeSelectorRequirement] = []
        for volume in pod.spec.volumes:
            if not volume.persistent_volume_claim:
                continue
            pvc = self.cluster.try_get(
                "pvcs", volume.persistent_volume_claim, pod.metadata.namespace
            )
            if pvc is None:
                continue
            if pvc.volume_name:
                requirements.extend(self._pv_requirements(pvc.volume_name))
            elif pvc.storage_class_name:
                requirements.extend(self._storage_class_requirements(pvc.storage_class_name))
        return requirements

    def _storage_class_requirements(self, name: str) -> List[NodeSelectorRequirement]:
        sc = self.cluster.try_get("storageclasses", name, namespace="")
        if sc is None:
            return []
        out: List[NodeSelectorRequirement] = []
        for term in sc.allowed_topologies:
            out.extend(term.match_expressions)
        return out

    def _pv_requirements(self, name: str) -> List[NodeSelectorRequirement]:
        pv = self.cluster.try_get("pvs", name, namespace="")
        if pv is None:
            return []
        out: List[NodeSelectorRequirement] = []
        for term in pv.node_affinity_required:
            out.extend(term.match_expressions)
        return out


def validate(pod: Pod, allow_pod_affinity: bool = False) -> List[str]:
    """Supportability gate (reference: controller.go:125-176)."""
    errs: List[str] = []
    for constraint in pod.spec.topology_spread_constraints:
        if constraint.topology_key not in SUPPORTED_TOPOLOGY_KEYS:
            errs.append(
                f"unsupported topology key {constraint.topology_key} not in {sorted(SUPPORTED_TOPOLOGY_KEYS)}"
            )
    aff = pod.spec.affinity
    if aff is not None:
        if allow_pod_affinity:
            # this framework schedules required pod (anti-)affinity; only the
            # topology key needs to be one the solver can reason about
            for term in _pod_affinity_terms(pod):
                if term.topology_key not in SUPPORTED_TOPOLOGY_KEYS:
                    errs.append(
                        f"unsupported pod affinity topology key {term.topology_key}"
                    )
        else:
            if podutil.has_required_pod_affinity(pod):
                errs.append("pod affinity 'required' is not supported")
            if podutil.has_required_pod_anti_affinity(pod):
                errs.append("pod anti-affinity 'required' is not supported")
        if aff.node_affinity is not None:
            for pref in aff.node_affinity.preferred:
                errs.extend(_validate_term_ops(pref.preference))
            for term in aff.node_affinity.required:
                errs.extend(_validate_term_ops(term))
    return errs


def _pod_affinity_terms(pod: Pod):
    aff = pod.spec.affinity
    terms = []
    if aff.pod_affinity is not None:
        terms.extend(aff.pod_affinity.required)
    if aff.pod_anti_affinity is not None:
        terms.extend(aff.pod_anti_affinity.required)
    return terms


def _validate_term_ops(term: NodeSelectorTerm) -> List[str]:
    return [
        f"node selector term has unsupported operator {r.operator}"
        for r in term.match_expressions
        if r.operator not in SUPPORTED_NODE_SELECTOR_OPS
    ]


class SelectionController:
    """Routes pods to provisioner workers (reference: controller.go:43-115).

    ``reconcile`` returns the requeue-after seconds (None = done), matching
    the reference's Result{RequeueAfter: 5s} verify-loop contract.
    """

    def __init__(
        self,
        cluster: Cluster,
        provisioning_controller,
        allow_pod_affinity: bool = True,
        clock=None,
        wait: bool = True,
    ):
        self.cluster = cluster
        self.provisioners = provisioning_controller
        self.preferences = Preferences(clock=clock)
        self.volume_topology = VolumeTopology(cluster)
        self.allow_pod_affinity = allow_pod_affinity
        # wait=True blocks each reconcile on the batch gate, the reference's
        # goroutine idiom (controller.go:86-115: 10k goroutines are free).
        # wait=False is the thread-pool idiom the controller process runs
        # with: enqueue and return — a pod sitting in an unresolved batch is
        # recognized via worker.is_pending and NOT re-relaxed/re-enqueued,
        # and the 5s verify requeue provides the completion check. Without
        # this, a 32-thread pool caps batch formation at ~32 pods/solve
        # under a 10k-pod event storm.
        self.wait = wait

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        pod = self.cluster.try_get("pods", name, namespace)
        if pod is None:
            return None
        if not is_provisionable(pod):
            return None
        # prewarm the pod's solve statics HERE, in the wide reconcile pool,
        # so the worker's solve (one thread, latency-critical) finds every
        # canonical core/request vector memoized instead of building 10k of
        # them inside its latency budget
        from karpenter_tpu.scheduling.statics import statics

        statics(pod)
        errs = validate(pod, self.allow_pod_affinity)
        if errs:
            logger.error("Ignoring pod %s, %s", pod.key, "; ".join(errs))
            return None
        self.select_provisioner(pod)
        return REQUEUE_AFTER

    def select_provisioner(self, pod: Pod) -> bool:
        """Relax → inject volume topology → first matching provisioner →
        enqueue + block on the batch gate (reference: controller.go:86-115).
        Raises ``NoProvisionerMatched`` when every provisioner rejects the pod
        so the manager retries with backoff — each retry relaxes another
        preference (the reference returns an error for the same reason,
        controller.go:107-108)."""
        workers = self.provisioners.list_workers()
        # already enqueued and awaiting its batch: this reconcile is the
        # verify requeue firing early — don't relax another preference or
        # double-enqueue, just keep the requeue clock running
        if any(
            w.is_pending(pod.key) for w in workers if hasattr(w, "is_pending")
        ):
            return True
        if self._defer_to_foreign_owner(pod):
            # fleet mode (docs/fleet.md): the FIRST provisioner (in the
            # same sorted-name priority order single-replica selection
            # uses) that admits this pod belongs to another replica's
            # shard — that replica's selection loop serves it. Requeue
            # quietly; proceeding here would double-provision pods two
            # shards both admit, and raising would RELAX a preference per
            # retry on a pod this replica must not touch (pods are shared
            # objects).
            return False
        self.preferences.relax(pod)
        self.volume_topology.inject(pod)
        if not workers:
            return False
        errs = []
        for worker in workers:
            perrs = worker.provisioner.spec.constraints.validate_pod(pod)
            if perrs:
                errs.append(f"tried provisioner/{worker.provisioner.name}: {'; '.join(perrs)}")
            else:
                gate = worker.add(pod)
                if self.wait:
                    gate.wait(timeout=30)
                return True
        # the decision plane's admission feed (docs/decisions.md): an
        # every-provisioner rejection is a decision too — classify the
        # dimension (taint intolerance vs requirement mismatch), extend
        # the pod's consecutive-failure streak, and close the loop with
        # the PodUnschedulable Warning event once the streak crosses the
        # threshold. Best-effort: audit trouble never changes routing.
        self._note_admission_failure(pod, errs)
        raise NoProvisionerMatched(
            f"pod {pod.key} matched 0/{len(workers)} provisioners: {'; '.join(errs)}"
        )

    def _note_admission_failure(self, pod: Pod, errs: List[str]) -> None:
        from karpenter_tpu import obs
        from karpenter_tpu.obs import decisions as dec

        if not dec.enabled():
            return
        try:
            log = obs.decision_log()
            log.note_admission_failure(pod, errs)
            # per-pod emission: this feed runs once per rejected pod, so
            # only THIS pod's streak is checked (a whole-table sweep here
            # would be O(rejected x failing) event writes per pass)
            log.maybe_emit_for(
                self.cluster, pod.key,
                threshold=getattr(
                    self.provisioners, "unschedulable_event_rounds", 3
                ),
            )
        except Exception:
            logger.debug("admission-failure audit failed", exc_info=True)

    def _defer_to_foreign_owner(self, pod: Pod) -> bool:
        """True when the FIRST cluster-wide provisioner (sorted by name —
        the same priority order ``list_workers`` serves single-replica
        selection in) that admits this pod belongs to another replica's
        shard. Exactly ONE replica answers False per pod, so overlapping
        provisioners split across shards never double-provision it. The
        ownership check short-circuits first: non-fleet deployments pay
        nothing here. The admission check runs against the raw spec — more
        permissive than the owner's catalog-enriched view; on the rare
        divergence the owner's own retry/relax loop still serves the pod."""
        ownership = getattr(self.provisioners, "ownership", None)
        if ownership is None:
            return False
        for prov in sorted(
            self.cluster.provisioners(), key=lambda p: p.metadata.name
        ):
            if prov.metadata.deletion_timestamp is not None:
                continue
            if prov.spec.constraints.validate_pod(pod):
                continue  # does not admit; next priority
            return not ownership.owns(prov.metadata.name)
        return False


class NoProvisionerMatched(Exception):
    """Every active provisioner rejected the pod this round."""
