"""The disruption-budget layer under voluntary consolidation (docs/consolidation.md).

Consolidation is the one controller that *chooses* to take capacity away,
so its blast radius needs an availability contract the involuntary paths
(interruption, expiry) never did: a provisioner-level
``maxUnavailable``-style budget — a count (``"3"``) or a percent
(``"20%"``) of the provisioner's nodes — enforced per wave AND across
concurrently-settling waves. Three pieces live here:

- :func:`parse_budget` / :func:`resolve_budget` — the budget grammar and
  its arithmetic. Percent budgets resolve with roundUp semantics against
  the CURRENT node count (the same ``intstr`` rule as PDB
  ``maxUnavailable`` — ``kube.client.resolve_pdb_threshold``), so a 10%
  budget on a 5-node cluster still allows one node. ``"0"`` (or ``"0%"``)
  is the explicit off switch: voluntary disruption disabled entirely.

- :class:`BudgetLedger` — the cross-wave account. A wave RESERVES its
  victims before touching them and RELEASES them only when the wave
  settles; two waves of the same provisioner in flight at once (shards
  rebalancing mid-wave, concurrent reconciles) draw from ONE account, so
  their union can never exceed the budget. The ledger is deliberately
  shareable: replicas in one process (tests, the bench storm) inject a
  common instance.

- :class:`InterruptionRiskTracker` — the ``poll_disruptions`` feedback
  loop. Every disruption notice bumps an EWMA per (capacity_type, zone);
  consolidation folds the risk into each node's disruption cost so the
  re-pack retires the capacity the cloud was going to take anyway first.

Plus :func:`pdb_frozen_pod_keys`, the plan-time victim screen: a pod whose
PDB currently allows ZERO disruptions freezes its node out of candidacy
*before* a wave starts — discovering the freeze at drain time strands a
cordoned node mid-wave with its replacement already paid for.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Set, Tuple

from karpenter_tpu.kube.client import resolve_pdb_threshold

# Baseline interruption risk by capacity type, used before (and under) any
# live poll_disruptions signal: spot capacity is reclaimable by contract.
DEFAULT_RISK = {"spot": 0.15, "preemptible": 0.15, "on-demand": 0.02}
RISK_FALLBACK = 0.05
# EWMA smoothing for observed notices; one notice moves the needle, a
# quiet week decays it back toward the capacity-type baseline.
RISK_ALPHA = 0.3


def parse_budget(spec: Optional[str]) -> Optional[str]:
    """Validate and normalize one budget spec. Returns the normalized
    string (``"3"`` / ``"20%"``) or None for unset. Raises ValueError on
    anything else — a typo'd budget must fail admission, not silently
    disable the safety layer."""
    if spec is None:
        return None
    s = str(spec).strip()
    if not s:
        return None
    body = s[:-1] if s.endswith("%") else s
    try:
        value = int(body)
    except ValueError:
        raise ValueError(
            f"disruption budget must be a count or percent (got {spec!r})"
        )
    if value < 0:
        raise ValueError(f"disruption budget must be non-negative (got {spec!r})")
    if s.endswith("%") and value > 100:
        raise ValueError(f"disruption budget percent over 100 (got {spec!r})")
    return f"{value}%" if s.endswith("%") else str(value)


def resolve_budget(spec: Optional[str], total_nodes: int) -> Optional[int]:
    """How many of ``total_nodes`` may be disrupted concurrently. None =
    no budget configured (the caller falls back to its wave size). ``"0"``
    resolves to 0 — disruption disabled. Percent budgets use the PDB
    roundUp rule, with one exception: a NON-ZERO percent on a non-empty
    cluster never rounds below 1 (a budget meant to pace disruption must
    not quietly become the off switch on small clusters)."""
    if spec is None:
        return None
    allowed = resolve_pdb_threshold(spec, total_nodes)
    if allowed is None:
        return None
    if str(spec).strip().endswith("%"):
        pct = int(str(spec).strip()[:-1])
        if pct > 0 and total_nodes > 0:
            allowed = max(allowed, 1)
    return max(int(allowed), 0)


class BudgetLedger:
    """In-flight disrupted nodes per provisioner, across waves.

    ``reserve`` admits the longest prefix of ``names`` that keeps the
    provisioner's total in-flight count within ``allowed`` (prefix, not
    subset: callers pass victims cheapest-disruption-first, and the
    admitted set must honor that order). ``release`` returns capacity to
    the account when a wave settles — including partially, for victims
    that settle out-of-band."""

    def __init__(self):
        self._mu = threading.Lock()
        self._in_flight: Dict[str, Set[str]] = {}  # guarded-by: self._mu

    def reserve(
        self, provisioner: str, names: List[str], allowed: int
    ) -> List[str]:
        with self._mu:
            held = self._in_flight.setdefault(provisioner, set())
            room = max(allowed - len(held), 0)
            admitted = [n for n in names if n not in held][:room]
            held.update(admitted)
            return admitted

    def release(self, provisioner: str, names: Iterable[str]) -> None:
        with self._mu:
            held = self._in_flight.get(provisioner)
            if held is None:
                return
            held.difference_update(names)
            if not held:
                self._in_flight.pop(provisioner, None)

    def in_flight(self, provisioner: str) -> int:
        with self._mu:
            return len(self._in_flight.get(provisioner, ()))


class InterruptionRiskTracker:
    """EWMA of interruption pressure per (capacity_type, zone), fed by the
    interruption controller's notice stream. ``risk`` answers in [0, 1]:
    the probability-flavored score consolidation folds into disruption
    cost — capacity the cloud keeps reclaiming is cheap to retire
    voluntarily (it was leaving anyway)."""

    def __init__(self, alpha: float = RISK_ALPHA):
        self.alpha = alpha
        self._mu = threading.Lock()
        self._ewma: Dict[Tuple[str, str], float] = {}  # guarded-by: self._mu

    def observe(self, capacity_type: str, zone: str, signal: float = 1.0) -> None:
        key = (capacity_type or "", zone or "")
        with self._mu:
            cur = self._ewma.get(key, 0.0)
            self._ewma[key] = cur + self.alpha * (min(max(signal, 0.0), 1.0) - cur)

    def decay(self) -> None:
        """One quiet interval: every series relaxes toward 0."""
        with self._mu:
            for key in list(self._ewma):
                self._ewma[key] *= 1.0 - self.alpha
                if self._ewma[key] < 1e-4:
                    del self._ewma[key]

    def risk(self, capacity_type: str, zone: str) -> float:
        base = DEFAULT_RISK.get(capacity_type or "", RISK_FALLBACK)
        key = (capacity_type or "", zone or "")
        with self._mu:
            observed = self._ewma.get(key, 0.0)
        return min(max(base, observed), 1.0)


_default_risk_lock = threading.Lock()
_default_risk: Optional[InterruptionRiskTracker] = None


def risk_tracker() -> InterruptionRiskTracker:
    """The process-default tracker: the interruption controller feeds it,
    consolidation reads it — no wiring needed between the two."""
    global _default_risk
    with _default_risk_lock:
        if _default_risk is None:
            _default_risk = InterruptionRiskTracker()
        return _default_risk


def pdb_frozen_pod_keys(cluster) -> Set[str]:
    """Pod keys whose PodDisruptionBudget currently allows ZERO voluntary
    disruptions — the plan-time victim screen. Mirrors the apiserver's
    Evict math (``kube.client.Cluster.evict``): a pod is frozen when any
    matching PDB would refuse one more eviction right now. One pass over
    the PDBs, not per-candidate-node evict probes."""
    frozen: Set[str] = set()
    try:
        pdbs = cluster.list("pdbs")
    except Exception:
        return frozen
    if not pdbs:
        return frozen
    pods_by_ns: Dict[str, list] = {}
    for p in cluster.pods():
        pods_by_ns.setdefault(p.metadata.namespace, []).append(p)
    for pdb in pdbs:
        if pdb.selector is None:
            continue
        matching = [
            p
            for p in pods_by_ns.get(pdb.metadata.namespace, [])
            if pdb.selector.matches(p.metadata.labels)
        ]
        if not matching:
            continue
        healthy = [
            p for p in matching if p.metadata.deletion_timestamp is None
        ]
        min_avail = resolve_pdb_threshold(pdb.min_available, len(matching))
        max_unavail = resolve_pdb_threshold(pdb.max_unavailable, len(matching))
        allows_one = True
        if min_avail is not None and len(healthy) - 1 < min_avail:
            allows_one = False
        if max_unavail is not None and (
            len(matching) - (len(healthy) - 1)
        ) > max_unavail:
            allows_one = False
        if not allows_one:
            frozen.update(p.key for p in matching)
    return frozen
