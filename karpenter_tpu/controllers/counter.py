"""Counter: provisioner resource accounting.

Mirrors ``pkg/controllers/counter``: maintains
``provisioner.status.resources`` — the summed capacity of the provisioner's
nodes — which is the input to ``Limits.exceeded_by`` checked before every
launch (controller.go:51-87).
"""

from __future__ import annotations

from typing import Dict

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import resources as res


class CounterController:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self, name: str) -> None:
        provisioner = self.cluster.try_get("provisioners", name, namespace="")
        if provisioner is None:
            return
        counts = self.resource_counts_for(name)
        if counts != provisioner.status.resources:
            from karpenter_tpu.kube import serde

            # status subresource write (deploy/crd.yaml subresources.status).
            # RFC 7386 merges key-wise, so a key that vanished from the
            # counts (its last node deleted) must be cleared with an
            # explicit null or it would linger and feed Limits forever
            patch = {k: None for k in provisioner.status.resources if k not in counts}
            patch.update(serde.quantities(counts))
            self.cluster.patch_status(
                "provisioners", name, {"resources": patch}, namespace=""
            )

    def resource_counts_for(self, provisioner_name: str) -> Dict[str, float]:
        """Sum node capacity over this provisioner's nodes
        (reference: controller.go:72-87)."""
        total: Dict[str, float] = {}
        for node in self.cluster.nodes():
            if node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) != provisioner_name:
                continue
            total = res.merge(total, node.status.capacity)
        return total

    def register(self, manager) -> None:
        """Watch nodes, mapping each to its owning provisioner
        (reference: controller.go:90-112)."""

        def on_node(event: str, node) -> None:
            name = node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL)
            if name:
                manager.enqueue("counter", name)

        def on_provisioner(event: str, provisioner) -> None:
            manager.enqueue("counter", provisioner.metadata.name)

        self.cluster.watch("nodes", on_node)
        self.cluster.watch("provisioners", on_provisioner)
