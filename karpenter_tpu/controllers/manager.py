"""Controller manager: the watch→queue→reconcile runtime.

Mirrors ``pkg/controllers/manager.go`` + controller-runtime: each registered
controller gets a rate-limited dedup workqueue and N worker threads; watches
feed the queues via ``enqueue``; reconcilers return an optional
requeue-after (seconds) and raise to trigger exponential-backoff retry.
Healthz/readyz are simple liveness flags (reference: manager.go:48-61).

Leader election (reference: cmd/controller/main.go:84-85) degenerates to a
process-local lock here: the in-memory cluster has exactly one writer
process; a multi-process deployment backs ``Cluster`` with a real apiserver
and brings its own lease, so the manager exposes the same hook.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_tpu.kube.client import Cluster, Conflict
from karpenter_tpu.utils.workqueue import RateLimitingQueue, ShutDown

logger = logging.getLogger("karpenter.manager")

# Reference concurrency defaults: selection 10,000; everything else 10
# (selection/controller.go:183, provisioning/controller.go:152). Thread-based
# workers cap lower; the queues dedup so throughput is equivalent.
DEFAULT_CONCURRENCY = 10


class _Registration:
    def __init__(self, name: str, reconcile: Callable, concurrency: int):
        self.name = name
        self.reconcile = reconcile
        self.concurrency = concurrency
        self.queue = RateLimitingQueue()
        self.threads: List[threading.Thread] = []
        self.conflicts: Dict = {}  # key -> consecutive Conflict count


class Manager:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self._controllers: Dict[str, _Registration] = {}
        self._started = False
        self._healthy = threading.Event()

    # -- registration ------------------------------------------------------
    def register(
        self,
        name: str,
        reconcile: Callable[..., Optional[float]],
        concurrency: int = DEFAULT_CONCURRENCY,
    ) -> None:
        """Register a reconciler. ``reconcile(key)`` may return seconds to
        requeue after, or raise to retry with backoff."""
        if name in self._controllers:
            raise ValueError(f"controller {name} already registered")
        self._controllers[name] = _Registration(name, reconcile, concurrency)

    def enqueue(self, controller: str, key) -> None:
        reg = self._controllers.get(controller)
        if reg is not None:
            reg.queue.add(key)

    def enqueue_after(self, controller: str, key, delay: float) -> None:
        reg = self._controllers.get(controller)
        if reg is not None:
            reg.queue.add_after(key, delay)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for reg in self._controllers.values():
            # a stopped manager's queues are shut down permanently; restart
            # gets fresh queues so workers don't exit on arrival
            if reg.queue.is_shut_down():
                reg.queue = RateLimitingQueue(backoff=reg.queue.backoff)
            reg.threads = [t for t in reg.threads if t.is_alive()]
            for i in range(reg.concurrency):
                # workers pin the queue instance they were started with: a
                # stop()/start() swap must not let an old worker touch the
                # replacement queue (it would break the dedup invariant)
                t = threading.Thread(
                    target=self._worker, args=(reg, reg.queue), daemon=True,
                    name=f"{reg.name}-{i}",
                )
                reg.threads.append(t)
                t.start()
        self._healthy.set()

    def stop(self) -> None:
        self._healthy.clear()
        for reg in self._controllers.values():
            reg.queue.shut_down()
        for reg in self._controllers.values():
            for t in reg.threads:
                t.join(timeout=2)
        self._started = False

    def healthz(self) -> bool:
        return self._healthy.is_set()

    readyz = healthz

    # -- worker loop -------------------------------------------------------
    CONFLICT_REQUEUE = 0.2  # optimistic-concurrency retry, not backoff
    CONFLICT_RETRY_CAP = 5  # then it's a real problem: back off + log

    def _worker(self, reg: _Registration, queue) -> None:
        while True:
            try:
                key = queue.get()
            except ShutDown:
                return
            try:
                requeue_after = self._call(reg, key)
            except Conflict:
                # a stale-resourceVersion write is the normal outcome of
                # optimistic concurrency against an apiserver: requeue
                # promptly (the next reconcile reads the fresher cache).
                # Bounded — a key that conflicts every time (broken watch,
                # fighting writers) must surface and back off, not hot-loop.
                count = reg.conflicts.get(key, 0) + 1
                reg.conflicts[key] = count
                queue.done(key)
                if count >= self.CONFLICT_RETRY_CAP:
                    logger.warning(
                        "%s: reconcile %r conflicted %d times; backing off",
                        reg.name, key, count,
                    )
                    queue.add_rate_limited(key)
                else:
                    logger.debug("%s: reconcile %r conflicted; requeueing", reg.name, key)
                    queue.add_after(key, self.CONFLICT_REQUEUE)
                continue
            except Exception:
                logger.exception("%s: reconcile %r failed", reg.name, key)
                queue.done(key)
                queue.add_rate_limited(key)
                continue
            reg.conflicts.pop(key, None)
            queue.forget(key)
            queue.done(key)
            if requeue_after is not None:
                queue.add_after(key, requeue_after)

    @staticmethod
    def _call(reg: _Registration, key) -> Optional[float]:
        from karpenter_tpu.cloudprovider.metrics import reconciling_controller

        token = reconciling_controller.set(reg.name)
        try:
            if isinstance(key, tuple):
                return reg.reconcile(*key)
            return reg.reconcile(key)
        finally:
            reconciling_controller.reset(token)

    # -- synchronous drive (test harness) ----------------------------------
    def reconcile_now(self, controller: str, key) -> Optional[float]:
        """Run one reconcile inline — the ExpectReconcileSucceeded analog
        (reference: pkg/test/expectations/expectations.go:199-203)."""
        reg = self._controllers[controller]
        return self._call(reg, key)
