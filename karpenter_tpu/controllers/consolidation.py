"""Consolidation: cost-optimal re-pack of the live cluster.

New capability beyond the reference snapshot (its deprovisioning is only
emptiness/expiry TTLs — node/emptiness.go, node/expiration.go); required by
BASELINE.json config 5 ("Consolidation re-pack of 1k live nodes"). The tensor
formulation makes this natural: feed the *entire* cluster's pods through the
same batched solver used for pending pods and compare the proposed packing's
price against what is currently running.

Plan: collect the provisioner's consolidatable nodes (ready, not deleting,
no do-not-evict pods, no PDB-frozen pods) and their reschedulable pods,
re-solve in one batch on the normal solver routes (the proposal inherits
bit-exact route parity from the scheduler), then reduce the proposal to a
MINIMAL-MOVE wave (solver/repack.py): nodes already holding their proposed
packing are kept untouched, the rest retire cheapest-disruption-first —
price discounted by the ``poll_disruptions``-fed interruption risk, plus a
per-pod move charge.

Execute has two migration modes:

- ``bind``: launch replacements and rebind pods directly — valid only where
  the store permits rebinding (the in-memory cluster; a real apiserver
  rejects Binding a pod that already has a nodeName);
- ``evict`` (auto-selected for ``ApiCluster``): retire the victims — with
  an orchestrator wired, each runs the PR-1 taint→replace→drain sequence
  (replacement pods injected BEFORE any eviction); without one, the legacy
  delete→termination-drain path. Workload recreations flow through the
  NORMAL provisioning path, whose solver launches the same cost-optimal
  capacity the plan priced.

The robustness envelope around an evict wave (docs/consolidation.md):

- the disruption budget (controllers/disruption.py) — provisioner-level
  ``maxUnavailable``-style count/percent, enforced per wave AND across
  concurrently-settling waves through a shared ledger;
- the journal (launch/journal.py, ``consolidation`` marker): the wave's
  victims are journaled BEFORE the first cordon, so a mid-wave crash is
  replayed by the recovery ladder — survivors un-cordoned, entry resolved;
- the decision id (obs/decisions.py): every wave records an audit entry
  and stamps its id on the journal entry and every wave/move event;
- brownout rung 1 pauses new waves; a fenced or non-owning replica never
  executes one.
"""

from __future__ import annotations

import copy
import logging
import threading
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.cloudprovider.types import CloudProvider, InstanceType, NodeRequest
from karpenter_tpu.controllers.disruption import (
    BudgetLedger,
    pdb_frozen_pod_keys,
    resolve_budget,
    risk_tracker,
)
from karpenter_tpu.controllers.provisioning import REQUEUE_INTERVAL
from karpenter_tpu.kube.client import Cluster, Conflict
from karpenter_tpu.scheduling.ffd import VirtualNode
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.solver.repack import minimal_move_match, order_retirement
from karpenter_tpu.utils import node as nodeutil
from karpenter_tpu.utils import pod as podutil

logger = logging.getLogger("karpenter.consolidation")

# Savings below this fraction of current cost are not worth the churn.
MIN_SAVINGS_FRACTION = 0.05

# Evict-mode retirement pacing (VERDICT r2 weak #5 / ADVICE r2): at most
# this many nodes are handed to the termination controller per reconcile
# wave, and the next wave waits until the prior wave's nodes are gone AND
# the recreated pods have re-seated. The reference consolidates one command
# at a time and paces evictions through a rate-limited queue
# (termination/eviction.go:45-56); an unpaced 1k-node plan is a
# cluster-wide availability dip with only per-pod PDB retries as a brake.
EVICT_WAVE_SIZE = 5
WAVE_CHECK_INTERVAL = 10.0
# safety valve: a wave that has not settled after this long (e.g. an
# unrelated permanently-unschedulable pod appeared, or a replacement
# launch failed terminally) stops blocking further consolidation — and is
# FINISHED cleanly (survivors un-cordoned, journal resolved, budget
# released), because bounded disruption must not become unbounded deadlock
WAVE_SETTLE_TIMEOUT = 300.0


@dataclass
class ConsolidationPlan:
    provisioner: Provisioner
    nodes: List[Node] = field(default_factory=list)  # candidates, old world
    pods: List[Pod] = field(default_factory=list)  # reschedulable pods
    proposed: List[VirtualNode] = field(default_factory=list)  # new world
    current_price: float = 0.0
    proposed_price: float = 0.0
    # the minimal-move reduction (solver/repack.py): candidates whose
    # proposed packing is what they already run stay untouched; only the
    # rest retire (cheapest-disruption-first) / launch
    keep: List[Node] = field(default_factory=list)
    retire: List[Node] = field(default_factory=list)
    launch: List[VirtualNode] = field(default_factory=list)
    moves: List[Pod] = field(default_factory=list)
    node_pods: Dict[str, List[Pod]] = field(default_factory=dict)

    @property
    def savings(self) -> float:
        return self.current_price - self.proposed_price

    @property
    def worthwhile(self) -> bool:
        if not self.nodes or self.current_price <= 0:
            return False
        # every reschedulable pod must have a seat in the new world
        placed = sum(len(v.pods) for v in self.proposed)
        if placed < len(self.pods):
            return False
        if not self.retire:
            # minimal-move says the cluster already IS the proposal
            return False
        return self.savings / self.current_price >= MIN_SAVINGS_FRACTION


class ConsolidationController:
    """Batched re-pack + deprovision. Registered per provisioner; requeues on
    the provisioning cadence."""

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        enabled: bool = True,
        solver_service_address: Optional[str] = None,
        migration: Optional[str] = None,  # "bind" | "evict" | None = auto
        wave_size: int = EVICT_WAVE_SIZE,
        ownership=None,
        orchestrator=None,  # interruption.Orchestrator (taint→replace→drain)
        journal=None,  # launch.journal.LaunchJournal (wave crash safety)
        decisions=None,  # obs.decisions.DecisionLog override (tests)
        ledger: Optional[BudgetLedger] = None,
        risk=None,  # disruption.InterruptionRiskTracker override (tests)
        default_budget: Optional[str] = None,
    ):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.enabled = enabled
        self.solver_service_address = solver_service_address
        # fleet.ShardManager (or None): consolidation disrupts a
        # provisioner's nodes, so only the shard owner may plan/execute a
        # wave — N un-sharded replicas would each retire wave_size nodes
        # concurrently (N× the configured disruption pacing)
        self.ownership = ownership
        # the disruption-safety envelope — each piece optional so the
        # legacy construction (tests, bind-mode callers) keeps working:
        # no orchestrator → legacy delete path, no journal → no crash
        # breadcrumb, no budget → wave-size pacing only
        self.orchestrator = orchestrator
        self.journal = journal
        self._decisions = decisions
        self.ledger = ledger if ledger is not None else BudgetLedger()
        self.risk = risk if risk is not None else risk_tracker()
        self.default_budget = default_budget
        # bench/test observability beside the prometheus counters
        self.waves_executed = 0
        self.moves_executed = 0
        self.nodes_reclaimed = 0
        self.budget_blocked = 0
        self.cost_delta_usd = 0.0
        from karpenter_tpu.kube.apiserver import ApiCluster

        if migration is None:
            # a real apiserver rejects rebinding a running pod
            migration = "evict" if isinstance(cluster, ApiCluster) else "bind"
        if migration not in ("bind", "evict"):
            raise ValueError(f"migration must be bind|evict, got {migration}")
        self.wave_size = max(1, wave_size)
        # in-flight evict wave PER PROVISIONER (reconciles of different
        # provisioners run concurrently): name -> (node names, pod keys
        # already pending when the wave launched, settle deadline, journal
        # token, decision id)
        self._wave_lock = threading.Lock()
        self._pending_waves: Dict[str, tuple] = {}
        # brownout ladder rung 1 (resilience/brownout.py): consolidation is
        # VOLUNTARY disruption — evicting pods creates the exact pending
        # work an overloaded provisioner is already drowning in, so it is
        # the first wave the ladder pauses. In-flight waves still settle;
        # only NEW plans are deferred.
        self._paused = False  # guarded-by: self._wave_lock
        if migration == "bind" and isinstance(cluster, ApiCluster):
            # would fail mid-execute on the first rebind (409), leaking the
            # already-launched replacements next to the old capacity
            raise ValueError(
                "bind migration cannot work against a real apiserver "
                "(Binding an assigned pod is rejected); use evict"
            )
        self.migration = migration

    def _decision_log(self):
        if self._decisions is not None:
            return self._decisions
        from karpenter_tpu import obs

        return obs.decision_log()

    # -- planning ----------------------------------------------------------
    def plan(self, provisioner: Provisioner) -> ConsolidationPlan:
        catalog = self.cloud_provider.get_instance_types(
            provisioner.spec.constraints.provider
        )
        price_by_type: Dict[str, float] = {it.name: it.effective_price() for it in catalog}
        nodes, pods, node_pods = self._candidates(provisioner)
        plan = ConsolidationPlan(
            provisioner=provisioner, nodes=nodes, pods=pods, node_pods=node_pods
        )
        if not nodes:
            return plan
        plan.current_price = sum(
            price_by_type.get(n.metadata.labels.get(lbl.INSTANCE_TYPE, ""), 0.0)
            for n in nodes
        )
        # the batched re-pack: the whole cluster's pods in ONE solve. Solve on
        # clones — topology injection writes nodeSelectors — against a shadow
        # cluster with the candidates removed: the candidates' own live pods
        # must not count as existing topology/affinity occupants, or
        # anti-affinity workloads could never consolidate (their old seats
        # would block their new ones).
        clones = [copy.deepcopy(p) for p in pods]
        for clone in clones:
            clone.spec.node_name = ""
        shadow = self._shadow_cluster(nodes, pods)
        scheduler = Scheduler(shadow, solver_service_address=self.solver_service_address)
        plan.proposed = scheduler.solve(provisioner, catalog, clones) if pods else []
        plan.proposed_price = sum(
            v.instance_type_options[0].effective_price() for v in plan.proposed
        )
        # minimal-move reduction + disruption-cost retirement order
        match = minimal_move_match(nodes, node_pods, plan.proposed)
        plan.keep = match.keep
        plan.launch = match.launch
        plan.moves = match.moves
        plan.retire = order_retirement(
            match.retire, node_pods, price_by_type, self.risk.risk
        )
        return plan

    def _shadow_cluster(self, excluded_nodes: List[Node], excluded_pods: List[Pod]) -> Cluster:
        """The world as it will look once the candidates are gone: every
        other node/pod plus the daemonsets (for overhead computation). The
        shadow is read-only for the solve, so live objects are seeded as-is —
        no O(cluster) deepcopy per planning tick."""
        shadow = Cluster(clock=self.cluster.clock)
        gone_nodes = {n.metadata.name for n in excluded_nodes}
        gone_pods = {(p.metadata.namespace, p.metadata.name) for p in excluded_pods}
        for node in self.cluster.nodes():
            if node.metadata.name not in gone_nodes:
                shadow.seed("nodes", node)
        for pod in self.cluster.pods():
            if (pod.metadata.namespace, pod.metadata.name) not in gone_pods:
                shadow.seed("pods", pod)
        for ds in self.cluster.daemonsets():
            shadow.seed("daemonsets", ds)
        return shadow

    def _candidates(
        self, provisioner: Provisioner
    ) -> Tuple[List[Node], List[Pod], Dict[str, List[Pod]]]:
        """Nodes safe to consolidate and the pods that must be re-seated."""
        nodes: List[Node] = []
        pods: List[Pod] = []
        node_pods: Dict[str, List[Pod]] = {}
        # one pass over pods instead of a per-node scan (1k nodes × 10k pods
        # would otherwise be 10M predicate evaluations)
        by_node: Dict[str, List[Pod]] = {}
        for p in self.cluster.pods():
            if p.spec.node_name:
                by_node.setdefault(p.spec.node_name, []).append(p)
        # plan-time victim screening: a pod whose PDB allows zero
        # disruptions right now freezes its node out of candidacy HERE —
        # discovering it at drain time would strand a cordoned node
        # mid-wave with its replacement already paid for
        frozen = pdb_frozen_pod_keys(self.cluster) if self.migration == "evict" else set()
        for node in self.cluster.nodes():
            if node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) != provisioner.name:
                continue
            if node.metadata.deletion_timestamp is not None:
                continue
            if not nodeutil.is_ready(node) or node.spec.unschedulable:
                continue
            its_pods = [
                p
                for p in by_node.get(node.metadata.name, [])
                if not podutil.is_terminal(p)
                and not podutil.is_owned_by_daemonset(p)
                and not podutil.is_owned_by_node(p)
            ]
            if any(
                p.metadata.annotations.get(lbl.DO_NOT_EVICT_ANNOTATION) == "true"
                for p in its_pods
            ):
                continue
            if frozen and any(p.key in frozen for p in its_pods):
                continue
            if self.migration == "evict" and any(
                not p.metadata.owner_references for p in its_pods
            ):
                # voluntary disruption must not destroy workloads: an
                # ownerless pod has no controller to recreate it after the
                # drain, so its node is not a candidate (bind mode migrates
                # the pod itself and has no such constraint)
                continue
            nodes.append(node)
            pods.extend(its_pods)
            node_pods[node.metadata.name] = its_pods
        return nodes, pods, node_pods

    # -- execution ---------------------------------------------------------
    def _budget_allowed(self, provisioner: Provisioner) -> Optional[int]:
        """Resolve the provisioner's disruption budget against its CURRENT
        node count (like PDB percentages resolve against matching pods).
        Provisioner spec wins over the controller-level default; None =
        no budget configured."""
        spec = getattr(provisioner.spec, "disruption_budget", None) or self.default_budget
        if spec is None or str(spec).strip() == "":
            return None
        total = sum(
            1 for n in self.cluster.nodes()
            if n.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL)
            == provisioner.name
        )
        return resolve_budget(spec, total)

    def execute(self, plan: ConsolidationPlan) -> List[Node]:
        """Retire the old world; build the new one per the migration mode
        (bind: launch + rebind here; evict: the provisioning path rebuilds
        from the recreated pending pods)."""
        launched: List[Node] = []
        prov_name = plan.provisioner.metadata.name
        if self.migration == "bind":
            for vnode in plan.launch:
                node = self.cloud_provider.create(
                    NodeRequest(
                        template=vnode.constraints,
                        instance_type_options=vnode.instance_type_options,
                    )
                )
                template = vnode.constraints.to_node()
                node.metadata.labels = {**template.metadata.labels, **node.metadata.labels}
                node.metadata.labels[lbl.PROVISIONER_NAME_LABEL] = plan.provisioner.name
                node.metadata.finalizers = list(
                    set(node.metadata.finalizers) | set(template.metadata.finalizers)
                )
                # replacement nodes are immediately schedulable:
                # consolidation binds directly, so the not-ready scheduler
                # fence is unnecessary
                node.spec.taints = [
                    t for t in template.spec.taints if t.key != lbl.NOT_READY_TAINT_KEY
                ]
                try:
                    self.cluster.create("nodes", node)
                except Conflict:
                    pass
                launched.append(node)
                for pod in vnode.pods:
                    live = self.cluster.try_get(
                        "pods", pod.metadata.name, pod.metadata.namespace
                    )
                    if live is not None:
                        self.cluster.bind(live, node.metadata.name)
        # retire the old world. In bind mode every pod was already rebound
        # above, so the drains are empty and all retired nodes go at once.
        # In evict mode the drain IS the migration (workload controllers
        # recreate, and the pending recreations drive the provisioner to
        # rebuild capacity) — so retirement is PACED (at most wave_size per
        # reconcile, cheapest disruption first) and BUDGETED (the ledger
        # admits only what the maxUnavailable-style budget allows across
        # every concurrently-settling wave).
        retire = plan.retire
        decision_id = ""
        if self.migration == "evict":
            wanted = [n.metadata.name for n in retire[: self.wave_size]]
            allowed = self._budget_allowed(plan.provisioner)
            if allowed is None:
                admitted_names = self.ledger.reserve(prov_name, wanted, 10**9)
            else:
                admitted_names = self.ledger.reserve(prov_name, wanted, allowed)
            admitted = set(admitted_names)
            retire = [n for n in retire[: self.wave_size] if n.metadata.name in admitted]
            blocked = len(wanted) - len(retire)
            log = self._decision_log()
            record = (
                log.record_consolidation(
                    prov_name,
                    victims=admitted_names,
                    keep=len(plan.keep),
                    moves=sum(
                        len(plan.node_pods.get(n, ())) for n in admitted_names
                    ),
                    savings=plan.savings,
                    context={
                        "budget": allowed,
                        "budget_blocked": blocked,
                        "candidates": len(plan.nodes),
                        "plan_retire": len(plan.retire),
                    },
                )
                if log is not None else None
            )
            decision_id = record["id"] if record else ""
            if blocked:
                metrics.CONSOLIDATION_BUDGET_BLOCKED.labels(prov_name).inc(blocked)
                self.budget_blocked += blocked
                from karpenter_tpu.kube.events import recorder_for

                recorder_for(self.cluster).event(
                    "Provisioner", prov_name, "ConsolidationBudgetBlocked",
                    f"disruption budget admitted {len(retire)} of "
                    f"{len(wanted)} wave victim(s) "
                    f"({allowed if allowed is not None else 'unbounded'} "
                    "concurrent disruptions allowed)",
                    type="Warning", decision_id=decision_id,
                )
            if not retire:
                return launched
        # baseline BEFORE the retirement: pods already pending before this
        # wave must not gate settlement, but pods displaced BY the wave
        # (evicted and recreated while the loop runs) must — snapshotting
        # after would let them slip into the baseline
        baseline = (
            {p.key for p in self.cluster.pods() if podutil.is_provisionable(p)}
            if self.migration == "evict"
            else set()
        )
        from karpenter_tpu import obs

        token = ""
        moves = 0
        with obs.tracer().span(
            "consolidation.wave",
            attrs={
                "provisioner": prov_name,
                "victims": len(retire),
                "decision_id": decision_id,
            },
        ) as wave_sp:
            if self.migration == "evict" and self.journal is not None:
                # journal the WHOLE wave before the first victim is
                # touched: the entry is what recovery replays after a
                # mid-wave crash (launch/recovery.py un-cordons survivors)
                token = f"consolidation-{uuid.uuid4().hex[:16]}"
                self.journal.record_intent(
                    token, prov_name, trace=obs.to_traceparent(wave_sp),
                    marker="consolidation",
                    victims=[n.metadata.name for n in retire],
                    decision_id=decision_id,
                )
            for old in retire:
                try:
                    if self.migration == "evict" and self.orchestrator is not None:
                        # taint→replace→drain per victim: replacement pods
                        # are injected into provisioning BEFORE any eviction
                        resp = self.orchestrator.consolidate(
                            old, decision_id=decision_id
                        )
                        if resp is not None:
                            moves += len(resp.migrated)
                            if resp.blocked:
                                # plan-time screening should make this
                                # impossible; a non-zero count is the hard
                                # bar's tripwire, not business as usual
                                metrics.CONSOLIDATION_EVICTED_UNREADY.inc(
                                    len(resp.blocked)
                                )
                    else:
                        moves += len(plan.node_pods.get(old.metadata.name, ()))
                        self.cluster.delete("nodes", old.metadata.name, namespace="")
                except Exception:
                    logger.exception("retiring node %s", old.metadata.name)
        if self.migration == "evict":
            with self._wave_lock:
                self._pending_waves[prov_name] = (
                    [n.metadata.name for n in retire],
                    baseline,
                    self.cluster.clock() + WAVE_SETTLE_TIMEOUT,
                    token,
                    decision_id,
                )
        # plan-time estimate of the wave's $-delta: the admitted victims'
        # prices leave, the launch side's share of the proposal arrives
        # with them (settled waves confirm node counts; prices are catalog
        # facts either way)
        wave_fraction = len(retire) / max(len(plan.retire), 1)
        wave_delta = -plan.savings * wave_fraction
        self.cost_delta_usd += wave_delta
        metrics.CONSOLIDATION_COST_DELTA.labels(prov_name).set(self.cost_delta_usd)
        metrics.CONSOLIDATION_WAVES.labels(prov_name).inc()
        metrics.CONSOLIDATION_MOVES.labels(prov_name).inc(moves or len(plan.moves))
        self.waves_executed += 1
        self.moves_executed += moves or len(plan.moves)
        logger.info(
            "consolidating %d of %d candidate nodes (kept %d in place) -> "
            "%d launched (%s migration), price %.3f -> %.3f (saving %.3f)",
            len(retire), len(plan.nodes), len(plan.keep), len(plan.launch),
            self.migration, plan.current_price, plan.proposed_price,
            plan.savings,
        )
        from karpenter_tpu.kube.events import recorder_for

        recorder_for(self.cluster).event(
            "Provisioner", prov_name, "Consolidated",
            f"retiring {len(retire)} of {len(plan.nodes)} candidate node(s), "
            f"{len(plan.keep)} kept in place ({self.migration} migration), "
            f"hourly price {plan.current_price:.3f} -> {plan.proposed_price:.3f}",
            decision_id=decision_id,
        )
        return launched

    def _finish_wave(
        self, provisioner_name: str, wave: tuple, timed_out: bool
    ) -> None:
        """Close out one wave — on clean settlement AND on the settle
        timeout (a victim deleted out-of-band or a terminally-failed
        replacement launch must not wedge the loop): un-cordon any victim
        still standing (its drain never finished; a cordoned survivor is
        pure capacity loss), resolve the journal entry, release the
        budget, and count what was actually reclaimed."""
        node_names, _baseline, _deadline, token, decision_id = wave
        reclaimed = 0
        for name in node_names:
            node = self.cluster.try_get("nodes", name, namespace="")
            if node is None:
                reclaimed += 1
                continue
            if node.metadata.deletion_timestamp is not None:
                continue  # drain in flight; termination finishes it
            if not node.spec.unschedulable:
                continue
            from karpenter_tpu.kube.serde import taint_to_wire

            taints_wire = [
                taint_to_wire(t) for t in node.spec.taints
                if not (
                    t.key == lbl.INTERRUPTION_TAINT_KEY
                    and t.value == "consolidation"
                )
            ]
            try:
                self.cluster.merge_patch(
                    "nodes", name,
                    {"spec": {"unschedulable": False, "taints": taints_wire}},
                    namespace="",
                )
                logger.warning(
                    "consolidation wave for %s: un-cordoned surviving "
                    "victim %s (%s)",
                    provisioner_name, name,
                    "settle timeout" if timed_out else "settled without it",
                )
            except Exception:
                logger.exception("un-cordon of wave victim %s", name)
        if self.journal is not None and token:
            try:
                self.journal.resolve(token)
            except Exception:
                logger.exception("resolving wave journal entry %s", token)
        self.ledger.release(provisioner_name, node_names)
        if reclaimed:
            metrics.CONSOLIDATION_RECLAIMED_NODES.labels(provisioner_name).inc(
                reclaimed
            )
            self.nodes_reclaimed += reclaimed

    def wave_settled(self, provisioner_name: str) -> bool:
        """Has this provisioner's in-flight evict wave fully landed? True
        when every retired node is gone (termination finished its drain)
        and no pod that appeared SINCE the wave launched is still waiting
        for capacity (pods already pending before the wave don't gate it) —
        only then may the next wave disrupt more nodes. A wave past its
        settle deadline stops gating AND is finished cleanly (survivors
        un-cordoned, journal resolved, budget released): bounded
        disruption must not become unbounded deadlock on an out-of-band
        node delete, a dead replacement launch, or an unrelated stuck
        pod."""
        with self._wave_lock:
            wave = self._pending_waves.get(provisioner_name)
        if wave is None:
            return True
        node_names, baseline, deadline = wave[0], wave[1], wave[2]
        if self.cluster.clock() >= deadline:
            logger.warning(
                "consolidation wave for %s did not settle within %.0fs; "
                "finishing it and releasing the gate",
                provisioner_name, WAVE_SETTLE_TIMEOUT,
            )
            with self._wave_lock:
                wave = self._pending_waves.pop(provisioner_name, None)
            if wave is not None:
                self._finish_wave(provisioner_name, wave, timed_out=True)
            return True
        for name in node_names:
            if self.cluster.try_get("nodes", name, namespace="") is not None:
                return False
        if any(
            podutil.is_provisionable(p) and p.key not in baseline
            for p in self.cluster.pods()
        ):
            return False
        with self._wave_lock:
            wave = self._pending_waves.pop(provisioner_name, None)
        if wave is not None:
            self._finish_wave(provisioner_name, wave, timed_out=False)
        return True

    # -- brownout ----------------------------------------------------------
    def set_paused(self, paused: bool) -> None:
        with self._wave_lock:
            self._paused = bool(paused)

    def paused(self) -> bool:
        with self._wave_lock:
            return self._paused

    # -- reconcile ---------------------------------------------------------
    def reconcile(self, name: str) -> Optional[float]:
        if not self.enabled:
            return None
        provisioner = self.cluster.try_get("provisioners", name, namespace="")
        if provisioner is None:
            return None
        if self.ownership is not None and not self.ownership.owns(name):
            # another replica's shard (docs/fleet.md): re-check on a
            # lease-scale cadence so a rebalance picks the work up here
            from karpenter_tpu.controllers.provisioning import (
                OWNERSHIP_RECHECK_INTERVAL,
            )

            return OWNERSHIP_RECHECK_INTERVAL
        if self.ownership is not None and getattr(
            self.ownership, "fenced", lambda: False
        )():
            # a fenced replica (lease expired mid-partition) must not
            # mutate the cluster — same rule as the GC sweep
            from karpenter_tpu.controllers.provisioning import (
                OWNERSHIP_RECHECK_INTERVAL,
            )

            return OWNERSHIP_RECHECK_INTERVAL
        if self.paused():
            # brownout: no new voluntary disruption while the ladder is
            # engaged — re-check on the wave cadence so recovery picks the
            # work back up quickly
            return WAVE_CHECK_INTERVAL
        if not self.wave_settled(name):
            # the previous wave's pods have not all re-seated: no new
            # disruption yet, check back shortly
            return WAVE_CHECK_INTERVAL
        allowed = self._budget_allowed(provisioner)
        if allowed == 0:
            # budget "0": voluntary disruption disabled entirely — don't
            # even pay for planning
            return REQUEUE_INTERVAL
        plan = self.plan(provisioner)
        if plan.worthwhile:
            self.execute(plan)
            with self._wave_lock:
                in_flight = name in self._pending_waves
            if in_flight:
                return WAVE_CHECK_INTERVAL
        return REQUEUE_INTERVAL

    def register(self, manager) -> None:
        def on_provisioner(event: str, provisioner) -> None:
            manager.enqueue("consolidation", provisioner.metadata.name)

        self.cluster.watch("provisioners", on_provisioner)
