"""Consolidation: cost-optimal re-pack of the live cluster.

New capability beyond the reference snapshot (its deprovisioning is only
emptiness/expiry TTLs — node/emptiness.go, node/expiration.go); required by
BASELINE.json config 5 ("Consolidation re-pack of 1k live nodes"). The tensor
formulation makes this natural: feed the *entire* cluster's pods through the
same batched solver used for pending pods and compare the proposed packing's
price against what is currently running.

Plan: collect the provisioner's consolidatable nodes (ready, not deleting,
no do-not-evict pods) and their reschedulable pods, re-solve in one batch,
price both sides. Execute has two migration modes:

- ``bind``: launch replacements and rebind pods directly — valid only where
  the store permits rebinding (the in-memory cluster; a real apiserver
  rejects Binding a pod that already has a nodeName);
- ``evict`` (auto-selected for ``ApiCluster``): delete the old nodes — the
  termination controller cordons/drains them (PDB-respecting evictions),
  workload controllers recreate the pods, and the recreated pending pods
  flow through the NORMAL provisioning path, whose solver launches the
  same cost-optimal capacity the plan priced. No replacements are
  pre-launched: this framework (like the reference) never packs pods onto
  existing nodes itself — that is the kube-scheduler's job — so a
  pre-launched node would sit empty while the provisioner built another.
"""

from __future__ import annotations

import copy
import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.cloudprovider.types import CloudProvider, InstanceType, NodeRequest
from karpenter_tpu.controllers.provisioning import REQUEUE_INTERVAL
from karpenter_tpu.kube.client import Cluster, Conflict
from karpenter_tpu.scheduling.ffd import VirtualNode
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.utils import node as nodeutil
from karpenter_tpu.utils import pod as podutil

logger = logging.getLogger("karpenter.consolidation")

# Savings below this fraction of current cost are not worth the churn.
MIN_SAVINGS_FRACTION = 0.05

# Evict-mode retirement pacing (VERDICT r2 weak #5 / ADVICE r2): at most
# this many nodes are handed to the termination controller per reconcile
# wave, and the next wave waits until the prior wave's nodes are gone AND
# the recreated pods have re-seated. The reference consolidates one command
# at a time and paces evictions through a rate-limited queue
# (termination/eviction.go:45-56); an unpaced 1k-node plan is a
# cluster-wide availability dip with only per-pod PDB retries as a brake.
EVICT_WAVE_SIZE = 5
WAVE_CHECK_INTERVAL = 10.0
# safety valve: a wave that has not settled after this long (e.g. an
# unrelated permanently-unschedulable pod appeared) stops blocking further
# consolidation — bounded disruption must not become unbounded deadlock
WAVE_SETTLE_TIMEOUT = 300.0


@dataclass
class ConsolidationPlan:
    provisioner: Provisioner
    nodes: List[Node] = field(default_factory=list)  # candidates, old world
    pods: List[Pod] = field(default_factory=list)  # reschedulable pods
    proposed: List[VirtualNode] = field(default_factory=list)  # new world
    current_price: float = 0.0
    proposed_price: float = 0.0

    @property
    def savings(self) -> float:
        return self.current_price - self.proposed_price

    @property
    def worthwhile(self) -> bool:
        if not self.nodes or self.current_price <= 0:
            return False
        # every reschedulable pod must have a seat in the new world
        placed = sum(len(v.pods) for v in self.proposed)
        if placed < len(self.pods):
            return False
        return self.savings / self.current_price >= MIN_SAVINGS_FRACTION


class ConsolidationController:
    """Batched re-pack + deprovision. Registered per provisioner; requeues on
    the provisioning cadence."""

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        enabled: bool = True,
        solver_service_address: Optional[str] = None,
        migration: Optional[str] = None,  # "bind" | "evict" | None = auto
        wave_size: int = EVICT_WAVE_SIZE,
        ownership=None,
    ):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.enabled = enabled
        self.solver_service_address = solver_service_address
        # fleet.ShardManager (or None): consolidation disrupts a
        # provisioner's nodes, so only the shard owner may plan/execute a
        # wave — N un-sharded replicas would each retire wave_size nodes
        # concurrently (N× the configured disruption pacing)
        self.ownership = ownership
        from karpenter_tpu.kube.apiserver import ApiCluster

        if migration is None:
            # a real apiserver rejects rebinding a running pod
            migration = "evict" if isinstance(cluster, ApiCluster) else "bind"
        if migration not in ("bind", "evict"):
            raise ValueError(f"migration must be bind|evict, got {migration}")
        self.wave_size = max(1, wave_size)
        # in-flight evict wave PER PROVISIONER (reconciles of different
        # provisioners run concurrently): name -> (node names, pod keys
        # already pending when the wave launched, settle deadline)
        self._wave_lock = threading.Lock()
        self._pending_waves: Dict[str, tuple] = {}
        # brownout ladder rung 1 (resilience/brownout.py): consolidation is
        # VOLUNTARY disruption — evicting pods creates the exact pending
        # work an overloaded provisioner is already drowning in, so it is
        # the first wave the ladder pauses. In-flight waves still settle;
        # only NEW plans are deferred.
        self._paused = False  # guarded-by: self._wave_lock
        if migration == "bind" and isinstance(cluster, ApiCluster):
            # would fail mid-execute on the first rebind (409), leaking the
            # already-launched replacements next to the old capacity
            raise ValueError(
                "bind migration cannot work against a real apiserver "
                "(Binding an assigned pod is rejected); use evict"
            )
        self.migration = migration

    # -- planning ----------------------------------------------------------
    def plan(self, provisioner: Provisioner) -> ConsolidationPlan:
        catalog = self.cloud_provider.get_instance_types(
            provisioner.spec.constraints.provider
        )
        price_by_type: Dict[str, float] = {it.name: it.effective_price() for it in catalog}
        nodes, pods = self._candidates(provisioner)
        plan = ConsolidationPlan(provisioner=provisioner, nodes=nodes, pods=pods)
        if not nodes:
            return plan
        plan.current_price = sum(
            price_by_type.get(n.metadata.labels.get(lbl.INSTANCE_TYPE, ""), 0.0)
            for n in nodes
        )
        # the batched re-pack: the whole cluster's pods in ONE solve. Solve on
        # clones — topology injection writes nodeSelectors — against a shadow
        # cluster with the candidates removed: the candidates' own live pods
        # must not count as existing topology/affinity occupants, or
        # anti-affinity workloads could never consolidate (their old seats
        # would block their new ones).
        clones = [copy.deepcopy(p) for p in pods]
        for clone in clones:
            clone.spec.node_name = ""
        shadow = self._shadow_cluster(nodes, pods)
        scheduler = Scheduler(shadow, solver_service_address=self.solver_service_address)
        plan.proposed = scheduler.solve(provisioner, catalog, clones) if pods else []
        plan.proposed_price = sum(
            v.instance_type_options[0].effective_price() for v in plan.proposed
        )
        return plan

    def _shadow_cluster(self, excluded_nodes: List[Node], excluded_pods: List[Pod]) -> Cluster:
        """The world as it will look once the candidates are gone: every
        other node/pod plus the daemonsets (for overhead computation). The
        shadow is read-only for the solve, so live objects are seeded as-is —
        no O(cluster) deepcopy per planning tick."""
        shadow = Cluster(clock=self.cluster.clock)
        gone_nodes = {n.metadata.name for n in excluded_nodes}
        gone_pods = {(p.metadata.namespace, p.metadata.name) for p in excluded_pods}
        for node in self.cluster.nodes():
            if node.metadata.name not in gone_nodes:
                shadow.seed("nodes", node)
        for pod in self.cluster.pods():
            if (pod.metadata.namespace, pod.metadata.name) not in gone_pods:
                shadow.seed("pods", pod)
        for ds in self.cluster.daemonsets():
            shadow.seed("daemonsets", ds)
        return shadow

    def _candidates(self, provisioner: Provisioner) -> Tuple[List[Node], List[Pod]]:
        """Nodes safe to consolidate and the pods that must be re-seated."""
        nodes: List[Node] = []
        pods: List[Pod] = []
        # one pass over pods instead of a per-node scan (1k nodes × 10k pods
        # would otherwise be 10M predicate evaluations)
        by_node: Dict[str, List[Pod]] = {}
        for p in self.cluster.pods():
            if p.spec.node_name:
                by_node.setdefault(p.spec.node_name, []).append(p)
        for node in self.cluster.nodes():
            if node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) != provisioner.name:
                continue
            if node.metadata.deletion_timestamp is not None:
                continue
            if not nodeutil.is_ready(node) or node.spec.unschedulable:
                continue
            node_pods = [
                p
                for p in by_node.get(node.metadata.name, [])
                if not podutil.is_terminal(p)
                and not podutil.is_owned_by_daemonset(p)
                and not podutil.is_owned_by_node(p)
            ]
            if any(
                p.metadata.annotations.get(lbl.DO_NOT_EVICT_ANNOTATION) == "true"
                for p in node_pods
            ):
                continue
            if self.migration == "evict" and any(
                not p.metadata.owner_references for p in node_pods
            ):
                # voluntary disruption must not destroy workloads: an
                # ownerless pod has no controller to recreate it after the
                # drain, so its node is not a candidate (bind mode migrates
                # the pod itself and has no such constraint)
                continue
            nodes.append(node)
            pods.extend(node_pods)
        return nodes, pods

    # -- execution ---------------------------------------------------------
    def execute(self, plan: ConsolidationPlan) -> List[Node]:
        """Retire the old world; build the new one per the migration mode
        (bind: launch + rebind here; evict: the provisioning path rebuilds
        from the recreated pending pods)."""
        launched: List[Node] = []
        if self.migration == "bind":
            for vnode in plan.proposed:
                node = self.cloud_provider.create(
                    NodeRequest(
                        template=vnode.constraints,
                        instance_type_options=vnode.instance_type_options,
                    )
                )
                template = vnode.constraints.to_node()
                node.metadata.labels = {**template.metadata.labels, **node.metadata.labels}
                node.metadata.labels[lbl.PROVISIONER_NAME_LABEL] = plan.provisioner.name
                node.metadata.finalizers = list(
                    set(node.metadata.finalizers) | set(template.metadata.finalizers)
                )
                # replacement nodes are immediately schedulable:
                # consolidation binds directly, so the not-ready scheduler
                # fence is unnecessary
                node.spec.taints = [
                    t for t in template.spec.taints if t.key != lbl.NOT_READY_TAINT_KEY
                ]
                try:
                    self.cluster.create("nodes", node)
                except Conflict:
                    pass
                launched.append(node)
                for pod in vnode.pods:
                    live = self.cluster.try_get(
                        "pods", pod.metadata.name, pod.metadata.namespace
                    )
                    if live is not None:
                        self.cluster.bind(live, node.metadata.name)
        # retire the old world: deletion hands the nodes to the termination
        # controller, whose cordon/drain evicts the remaining pods with PDB
        # respect. In bind mode every pod was already rebound above, so the
        # drains are empty and all nodes retire at once. In evict mode the
        # drain IS the migration (workload controllers recreate, and the
        # pending recreations drive the provisioner to rebuild capacity) —
        # so retirement is PACED: at most wave_size nodes per reconcile,
        # the rest after this wave settles (reconcile gates on it).
        retire = plan.nodes
        if self.migration == "evict" and len(retire) > self.wave_size:
            retire = retire[: self.wave_size]
        # baseline BEFORE the deletes: pods already pending before this wave
        # must not gate settlement, but pods displaced BY the wave (evicted
        # and recreated while the delete loop runs) must — snapshotting
        # after the deletes would let them slip into the baseline
        baseline = (
            {p.key for p in self.cluster.pods() if podutil.is_provisionable(p)}
            if self.migration == "evict"
            else set()
        )
        for old in retire:
            try:
                self.cluster.delete("nodes", old.metadata.name, namespace="")
            except Exception:
                logger.exception("retiring node %s", old.metadata.name)
        if self.migration == "evict":
            with self._wave_lock:
                self._pending_waves[plan.provisioner.metadata.name] = (
                    [n.metadata.name for n in retire],
                    baseline,
                    self.cluster.clock() + WAVE_SETTLE_TIMEOUT,
                )
        logger.info(
            "consolidating %d of %d candidate nodes -> %d planned (%s migration), "
            "price %.3f -> %.3f (saving %.3f)",
            len(retire), len(plan.nodes), len(plan.proposed), self.migration,
            plan.current_price, plan.proposed_price, plan.savings,
        )
        from karpenter_tpu.kube.events import recorder_for

        recorder_for(self.cluster).event(
            "Provisioner", plan.provisioner.metadata.name, "Consolidated",
            f"retiring {len(retire)} of {len(plan.nodes)} candidate node(s) "
            f"({self.migration} migration), hourly price "
            f"{plan.current_price:.3f} -> {plan.proposed_price:.3f}",
        )
        return launched

    def wave_settled(self, provisioner_name: str) -> bool:
        """Has this provisioner's in-flight evict wave fully landed? True
        when every retired node is gone (termination finished its drain)
        and no pod that appeared SINCE the wave launched is still waiting
        for capacity (pods already pending before the wave don't gate it) —
        only then may the next wave disrupt more nodes. A wave past its
        settle deadline stops gating (logged): bounded disruption must not
        become unbounded deadlock on an unrelated stuck pod."""
        with self._wave_lock:
            wave = self._pending_waves.get(provisioner_name)
        if wave is None:
            return True
        node_names, baseline, deadline = wave
        if self.cluster.clock() >= deadline:
            logger.warning(
                "consolidation wave for %s did not settle within %.0fs; "
                "releasing the gate", provisioner_name, WAVE_SETTLE_TIMEOUT,
            )
            with self._wave_lock:
                self._pending_waves.pop(provisioner_name, None)
            return True
        for name in node_names:
            if self.cluster.try_get("nodes", name, namespace="") is not None:
                return False
        if any(
            podutil.is_provisionable(p) and p.key not in baseline
            for p in self.cluster.pods()
        ):
            return False
        with self._wave_lock:
            self._pending_waves.pop(provisioner_name, None)
        return True

    # -- brownout ----------------------------------------------------------
    def set_paused(self, paused: bool) -> None:
        with self._wave_lock:
            self._paused = bool(paused)

    def paused(self) -> bool:
        with self._wave_lock:
            return self._paused

    # -- reconcile ---------------------------------------------------------
    def reconcile(self, name: str) -> Optional[float]:
        if not self.enabled:
            return None
        provisioner = self.cluster.try_get("provisioners", name, namespace="")
        if provisioner is None:
            return None
        if self.ownership is not None and not self.ownership.owns(name):
            # another replica's shard (docs/fleet.md): re-check on a
            # lease-scale cadence so a rebalance picks the work up here
            from karpenter_tpu.controllers.provisioning import (
                OWNERSHIP_RECHECK_INTERVAL,
            )

            return OWNERSHIP_RECHECK_INTERVAL
        if self.paused():
            # brownout: no new voluntary disruption while the ladder is
            # engaged — re-check on the wave cadence so recovery picks the
            # work back up quickly
            return WAVE_CHECK_INTERVAL
        if not self.wave_settled(name):
            # the previous wave's pods have not all re-seated: no new
            # disruption yet, check back shortly
            return WAVE_CHECK_INTERVAL
        plan = self.plan(provisioner)
        if plan.worthwhile:
            self.execute(plan)
            with self._wave_lock:
                in_flight = name in self._pending_waves
            if in_flight:
                return WAVE_CHECK_INTERVAL
        return REQUEUE_INTERVAL

    def register(self, manager) -> None:
        def on_provisioner(event: str, provisioner) -> None:
            manager.enqueue("consolidation", provisioner.metadata.name)

        self.cluster.watch("provisioners", on_provisioner)
