"""Node metrics: re-publish per-node Prometheus gauges on every
node/pod/provisioner event.

Mirrors ``pkg/controllers/metrics/node``: six gauge families
(allocatable, total pod requests/limits, total daemon requests/limits,
system overhead) labeled by {resource type, node, provisioner, zone, arch,
capacity type, instance type, phase}; label sets are tracked so gauges for
deleted nodes are removed (controller.go:53-196).
"""

from __future__ import annotations

import threading
from typing import Dict, Set, Tuple

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils import resources as res

NODE_GAUGES = (
    metrics.NODES_ALLOCATABLE,
    metrics.NODES_TOTAL_POD_REQUESTS,
    metrics.NODES_TOTAL_POD_LIMITS,
    metrics.NODES_TOTAL_DAEMON_REQUESTS,
    metrics.NODES_TOTAL_DAEMON_LIMITS,
    metrics.NODES_SYSTEM_OVERHEAD,
)


class NodeMetricsController:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self._lock = threading.Lock()
        # node name -> {(gauge index, ordered label values)} published
        self._published: Dict[str, Set[Tuple[int, Tuple[str, ...]]]] = {}

    def reconcile(self, name: str) -> None:
        node = self.cluster.try_get("nodes", name, namespace="")
        if node is None:
            self._forget(name)
            return
        self._publish(node)

    def _base_labels(self, node: Node) -> Dict[str, str]:
        labels = node.metadata.labels
        return {
            "node_name": node.metadata.name,
            "provisioner": labels.get(lbl.PROVISIONER_NAME_LABEL, ""),
            "zone": labels.get(lbl.TOPOLOGY_ZONE, ""),
            "arch": labels.get(lbl.ARCH, ""),
            "capacity_type": labels.get(lbl.CAPACITY_TYPE, ""),
            "instance_type": labels.get(lbl.INSTANCE_TYPE, ""),
            "phase": node.status.phase or ("Ready" if _ready(node) else "NotReady"),
        }

    def _publish(self, node: Node) -> None:
        base = self._base_labels(node)
        pod_requests: Dict[str, float] = {}
        pod_limits: Dict[str, float] = {}
        daemon_requests: Dict[str, float] = {}
        daemon_limits: Dict[str, float] = {}
        for p in self.cluster.pods_on_node(node.metadata.name):
            if podutil.is_terminal(p):
                continue
            if podutil.is_owned_by_daemonset(p):
                daemon_requests = res.merge(daemon_requests, p.resource_requests())
                daemon_limits = res.merge(daemon_limits, p.resource_limits())
            else:
                pod_requests = res.merge(pod_requests, p.resource_requests())
                pod_limits = res.merge(pod_limits, p.resource_limits())
        overhead = {
            k: node.status.capacity.get(k, 0.0) - node.status.allocatable.get(k, 0.0)
            for k in node.status.capacity
        }
        self._forget(node.metadata.name)
        published: Set[Tuple[int, Tuple[str, ...]]] = set()
        families = (
            node.status.allocatable, pod_requests, pod_limits,
            daemon_requests, daemon_limits, overhead,
        )
        for idx, values in enumerate(families):
            for resource_type, value in values.items():
                label_values = {**base, "resource_type": resource_type}
                ordered = tuple(label_values[k] for k in metrics.NODE_GAUGE_LABELS)
                NODE_GAUGES[idx].labels(*ordered).set(value)
                published.add((idx, ordered))
        with self._lock:
            self._published[node.metadata.name] = published

    def _forget(self, name: str) -> None:
        with self._lock:
            published = self._published.pop(name, None)
        if not published:
            return
        for idx, ordered in published:
            try:
                NODE_GAUGES[idx].remove(*ordered)
            except KeyError:
                pass

    def register(self, manager) -> None:
        def on_node(event: str, node) -> None:
            manager.enqueue("metrics_node", node.metadata.name)

        def on_pod(event: str, pod) -> None:
            if pod.spec.node_name:
                manager.enqueue("metrics_node", pod.spec.node_name)

        self.cluster.watch("nodes", on_node)
        self.cluster.watch("pods", on_pod)


def _ready(node: Node) -> bool:
    return any(c.type == "Ready" and c.status == "True" for c in node.status.conditions)
