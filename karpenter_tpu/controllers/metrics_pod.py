"""Pod state metrics: one ``karpenter_pods_state`` gauge per pod labeled by
{name, namespace, owner, node, provisioner, zone, arch, capacity type,
instance type, phase} (reference: pkg/controllers/metrics/pod
controller.go:54-118)."""

from __future__ import annotations

import threading
from typing import Dict, Tuple

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Pod
from karpenter_tpu.kube.client import Cluster

POD_GAUGE_LABELS = [
    "name", "namespace", "owner", "node", "provisioner", "zone", "arch",
    "capacity_type", "instance_type", "phase",
]


class PodMetricsController:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self._lock = threading.Lock()
        self._published: Dict[Tuple[str, str], Tuple[str, ...]] = {}

    def reconcile(self, name: str, namespace: str = "default") -> None:
        pod = self.cluster.try_get("pods", name, namespace)
        key = (namespace, name)
        if pod is None:
            self._forget(key)
            return
        self._record(key, pod)

    def _labels_for(self, pod: Pod) -> Dict[str, str]:
        node_labels: Dict[str, str] = {}
        if pod.spec.node_name:
            node = self.cluster.try_get("nodes", pod.spec.node_name, namespace="")
            if node is not None:
                node_labels = node.metadata.labels
        owner = ""
        if pod.metadata.owner_references:
            ref = pod.metadata.owner_references[0]
            owner = f"{ref.kind}/{ref.name}" if ref.kind else ref.name
        return {
            "name": pod.metadata.name,
            "namespace": pod.metadata.namespace,
            "owner": owner,
            "node": pod.spec.node_name,
            "provisioner": node_labels.get(lbl.PROVISIONER_NAME_LABEL, ""),
            "zone": node_labels.get(lbl.TOPOLOGY_ZONE, ""),
            "arch": node_labels.get(lbl.ARCH, ""),
            "capacity_type": node_labels.get(lbl.CAPACITY_TYPE, ""),
            "instance_type": node_labels.get(lbl.INSTANCE_TYPE, ""),
            "phase": pod.status.phase,
        }

    def _record(self, key: Tuple[str, str], pod: Pod) -> None:
        labels = self._labels_for(pod)
        ordered = tuple(labels[k] for k in POD_GAUGE_LABELS)
        self._forget(key)
        metrics.PODS_STATE_GAUGE.labels(*ordered).set(1)
        with self._lock:
            self._published[key] = ordered

    def _forget(self, key: Tuple[str, str]) -> None:
        with self._lock:
            ordered = self._published.pop(key, None)
        if ordered is None:
            return
        try:
            metrics.PODS_STATE_GAUGE.remove(*ordered)
        except KeyError:
            pass

    def register(self, manager) -> None:
        def on_pod(event: str, pod) -> None:
            manager.enqueue("metrics_pod", (pod.metadata.name, pod.metadata.namespace))

        self.cluster.watch("pods", on_pod)
