"""Requirements: the central constraint representation.

A ``Requirements`` wraps a list of node-selector requirements plus a per-key
``ValueSet`` (possibly a complement set) that is the running intersection of
every requirement seen for that key. Semantics mirror
``pkg/apis/provisioning/v1alpha5/requirements.go:34-191``:

- ``add`` normalizes aliased label keys, drops ignored keys, and intersects
  per-key sets;
- ``compatible`` checks pairwise per-key non-empty intersection, with the
  NotIn/DoesNotExist escape hatch;
- ``from_pod`` folds nodeSelector + the heaviest preferred node-affinity term
  + the first required node-affinity term.

The class is immutable-by-convention: mutating operations return new objects,
like the reference's value-receiver methods.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement, Pod
from karpenter_tpu.utils.sets import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    ValueSet,
    set_for_operator,
)

# Requirement operators a Provisioner may use vs. what pods may add
# (reference: provisioner_validation.go:30-31).
SUPPORTED_PROVISIONER_OPS = {OP_IN, OP_NOT_IN, OP_EXISTS}
SUPPORTED_NODE_SELECTOR_OPS = {OP_IN, OP_NOT_IN, OP_EXISTS, OP_DOES_NOT_EXIST}


@dataclass(frozen=True)
class Requirements:
    requirements: Tuple[NodeSelectorRequirement, ...] = ()
    _sets: Tuple[Tuple[str, ValueSet], ...] = field(default_factory=tuple)

    # -- constructors ------------------------------------------------------
    @staticmethod
    def new(*reqs: NodeSelectorRequirement) -> "Requirements":
        return Requirements().add(*reqs)

    @staticmethod
    def from_labels(labels: Dict[str, str]) -> "Requirements":
        return Requirements.new(
            *(
                NodeSelectorRequirement(key=k, operator=OP_IN, values=[v])
                for k, v in labels.items()
            )
        )

    @staticmethod
    def from_pod(pod: Pod) -> "Requirements":
        """NodeSelector + heaviest preferred node-affinity term + first
        required node-affinity OR-term (reference: requirements.go:55-75)."""
        reqs: List[NodeSelectorRequirement] = [
            NodeSelectorRequirement(key=k, operator=OP_IN, values=[v])
            for k, v in pod.spec.node_selector.items()
        ]
        aff = pod.spec.affinity
        if aff is None or aff.node_affinity is None:
            return Requirements.new(*reqs)
        na = aff.node_affinity
        if na.preferred:
            heaviest = max(na.preferred, key=lambda t: t.weight)
            reqs.extend(heaviest.preference.match_expressions)
        if na.required:
            reqs.extend(na.required[0].match_expressions)
        return Requirements.new(*reqs)

    # -- internal ----------------------------------------------------------
    def _set_map(self) -> Dict[str, ValueSet]:
        return dict(self._sets)

    # -- mutation (returns new object) ------------------------------------
    def add(self, *new_reqs: NodeSelectorRequirement) -> "Requirements":
        """Insert requirements, intersecting per-key sets
        (reference: requirements.go:78-110)."""
        reqs = list(self.requirements)
        sets = self._set_map()
        for req in new_reqs:
            key = lbl.NORMALIZED_LABELS.get(req.key, req.key)
            if key in lbl.IGNORED_LABELS:
                continue
            req = NodeSelectorRequirement(key=key, operator=req.operator, values=list(req.values))
            reqs.append(req)
            try:
                values = set_for_operator(req.operator, req.values)
            except ValueError:
                # Unknown operators behave as the zero-value (empty) set, like
                # the reference's uncovered switch; validation reports them.
                values = ValueSet.empty()
            if key in sets:
                values = values.intersection(sets[key])
            sets[key] = values
        return Requirements(tuple(reqs), tuple(sorted(sets.items())))

    def merge(self, other: "Requirements") -> "Requirements":
        return self.add(*other.requirements)

    # -- queries -----------------------------------------------------------
    def keys(self) -> Set[str]:
        return {r.key for r in self.requirements}

    def has(self, key: str) -> bool:
        return any(k == key for k, _ in self._sets)

    def get(self, key: str) -> ValueSet:
        """The running intersection for a key; missing keys behave as the
        empty finite set, matching the reference's zero-value Set."""
        for k, vs in self._sets:
            if k == key:
                return vs
        return ValueSet.empty()

    def zones(self) -> Set[str]:
        return set(self.get(lbl.TOPOLOGY_ZONE).finite_values())

    def instance_types(self) -> Set[str]:
        return set(self.get(lbl.INSTANCE_TYPE).finite_values())

    def architectures(self) -> Set[str]:
        return set(self.get(lbl.ARCH).finite_values())

    def operating_systems(self) -> Set[str]:
        return set(self.get(lbl.OS).finite_values())

    def capacity_types(self) -> Set[str]:
        return set(self.get(lbl.CAPACITY_TYPE).finite_values())

    # -- validation / compatibility ---------------------------------------
    def validate(self) -> List[str]:
        """Feasibility of the requirements themselves
        (reference: requirements.go:153-172)."""
        errs: List[str] = []
        for req in self.requirements:
            if not _is_qualified_name(req.key):
                errs.append(f"key {req.key} is not a qualified name")
            for value in req.values:
                if not _is_valid_label_value(value):
                    errs.append(f"invalid value {value} for key {req.key}")
            if req.operator not in SUPPORTED_NODE_SELECTOR_OPS:
                errs.append(
                    f"operator {req.operator} not in {sorted(SUPPORTED_NODE_SELECTOR_OPS)} for key {req.key}"
                )
            if self.get(req.key).cardinality == 0 and req.operator != OP_DOES_NOT_EXIST:
                errs.append(f"no feasible value for key {req.key}")
        return errs

    def compatible(self, other: "Requirements") -> List[str]:
        """Can ``other``'s requirements be met alongside ours
        (reference: requirements.go:175-191)? Returns error strings, empty if
        compatible."""
        errs: List[str] = []
        for key, requirement in other._sets:
            mine = self.get(key)
            intersection = requirement.intersection(mine)
            if intersection.cardinality == 0:
                if requirement.op_type() in (OP_NOT_IN, OP_DOES_NOT_EXIST) and mine.op_type() in (
                    OP_NOT_IN,
                    OP_DOES_NOT_EXIST,
                ):
                    continue
                errs.append(f"{requirement} not in {mine}, key {key}")
        return errs

    def __str__(self) -> str:
        parts = []
        for key, vs in self._sets:
            parts.append(f"{key} {vs.op_type()} {vs}")
        return ", ".join(parts)


def _is_qualified_name(key: str) -> bool:
    return not lbl.check_qualified_name(key)


def _is_valid_label_value(value: str) -> bool:
    return not lbl.check_label_value(value)
