from karpenter_tpu.api import labels  # noqa: F401
from karpenter_tpu.api.objects import (  # noqa: F401
    Container,
    DaemonSet,
    LabelSelector,
    Node,
    NodeSelectorRequirement,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodCondition,
    PodDisruptionBudget,
    StorageClass,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.api.requirements import Requirements  # noqa: F401
from karpenter_tpu.api.provisioner import (  # noqa: F401
    Constraints,
    Limits,
    Provisioner,
    ProvisionerSpec,
    ProvisionerStatus,
)
