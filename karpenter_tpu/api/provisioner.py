"""Provisioner CRD-equivalent types.

Mirrors ``pkg/apis/provisioning/v1alpha5``: ``Constraints`` (labels + taints +
requirements + kubelet config + vendor provider block), ``Limits``,
``ProvisionerSpec`` (constraints + TTLs + limits), and ``Provisioner`` with a
status carrying provisioned resources.

New in this framework: ``ProvisionerSpec.solver`` selects the scheduling
backend per provisioner — ``"ffd"`` (in-process first-fit-decreasing, the
reference algorithm) or ``"tpu"`` (the batched tensor solver) — per the
north-star design in BASELINE.json.
"""

from __future__ import annotations

import random
import string
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, NodeSpec, ObjectMeta, Pod, Taint
from karpenter_tpu.api.requirements import Requirements, SUPPORTED_PROVISIONER_OPS
from karpenter_tpu.utils import resources as res

SOLVER_FFD = "ffd"
SOLVER_TPU = "tpu"


def tolerates_all(taints: List[Taint], pod: Pod) -> List[str]:
    """Errors for every taint the pod does not tolerate
    (reference: taints.go:49-60)."""
    errs = []
    for taint in taints:
        if not any(t.tolerates(taint) for t in pod.spec.tolerations):
            errs.append(f"did not tolerate {taint.key}={taint.value}:{taint.effect}")
    return errs


@dataclass
class KubeletConfiguration:
    cluster_dns: List[str] = field(default_factory=list)


@dataclass
class Constraints:
    """Applied to every node the provisioner launches
    (reference: constraints.go:28-49)."""

    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    requirements: Requirements = field(default_factory=Requirements)
    kubelet_configuration: Optional[KubeletConfiguration] = None
    provider: Optional[Dict[str, Any]] = None  # vendor-specific block

    def clone(self) -> "Constraints":
        """Cheap copy: Requirements is immutable-by-convention (mutators
        return new objects), so sharing it is safe; labels/taints are copied
        one level deep. deepcopy here was the decode hot spot — the
        requirements tuples embed the whole catalog vocabulary."""
        return Constraints(
            labels=dict(self.labels),
            taints=list(self.taints),
            requirements=self.requirements,
            kubelet_configuration=self.kubelet_configuration,
            provider=self.provider,
        )

    def validate_pod(self, pod: Pod) -> List[str]:
        """Taint toleration + requirement validity + compatibility
        (reference: constraints.go:52-67). Empty list means the pod fits."""
        errs = tolerates_all(self.taints, pod)
        if errs:
            return errs
        pod_reqs = Requirements.from_pod(pod)
        verrs = pod_reqs.validate()
        if verrs:
            return [f"invalid requirements, {e}" for e in verrs]
        cerrs = self.requirements.compatible(pod_reqs)
        if cerrs:
            return [f"incompatible requirements, {e}" for e in cerrs]
        return []

    def to_node(self) -> Node:
        """Materialize a v1.Node with the termination finalizer and the
        ``karpenter.sh/not-ready:NoSchedule`` startup taint that prevents the
        kube-scheduler from double-booking capacity before our own binds land
        (reference: constraints.go:69-105)."""
        node_labels = dict(self.labels)
        for key, vs in self.requirements._sets:
            if lbl.is_restricted_node_label(key):
                continue
            op = vs.op_type()
            if op == "In":
                node_labels[key] = sorted(vs.finite_values())[0]
            elif op == "Exists":
                node_labels[key] = "".join(random.choices(string.ascii_lowercase + string.digits, k=10))
        return Node(
            metadata=ObjectMeta(labels=node_labels, finalizers=[lbl.TERMINATION_FINALIZER]),
            spec=NodeSpec(
                taints=list(self.taints)
                + [Taint(key=lbl.NOT_READY_TAINT_KEY, effect="NoSchedule")]
            ),
        )


@dataclass
class Limits:
    """Resource ceiling checked before every launch
    (reference: limits.go:24-40)."""

    resources: Dict[str, float] = field(default_factory=dict)

    def exceeded_by(self, usage: Dict[str, float]) -> Optional[str]:
        for name, used in usage.items():
            if name in self.resources and used >= self.resources[name]:
                return f"{name} resource usage of {used:g} exceeds limit of {self.resources[name]:g}"
        return None


@dataclass
class ProvisionerSpec:
    constraints: Constraints = field(default_factory=Constraints)
    ttl_seconds_after_empty: Optional[int] = None
    ttl_seconds_until_expired: Optional[int] = None
    limits: Optional[Limits] = None
    # Scheduling backend: "ffd" (in-process) or "tpu" (batched tensor solve);
    # "" = unset, resolved to the process default at admission/apply.
    solver: str = ""
    # Disruption budget for voluntary consolidation (docs/consolidation.md):
    # a maxUnavailable-style count ("3") or percent ("20%") of this
    # provisioner's nodes that may be disrupted concurrently, across every
    # settling wave. "0" disables voluntary disruption entirely; None
    # defers to the controller-level --consolidation-budget default.
    disruption_budget: Optional[str] = None


def default_provisioner(provisioner: Provisioner, default_solver: str = SOLVER_FFD) -> None:
    """Framework defaulting pass (reference: provisioner_defaults.go:154-161);
    the vendor hook runs separately. The process-level ``--default-solver``
    option lands here for provisioners that leave ``spec.solver`` unset."""
    if not provisioner.spec.solver:
        provisioner.spec.solver = default_solver


# The one condition every Provisioner maintains: it is validated, its
# catalog is reachable, and its worker is running (reference:
# register.go:51-54, provisioner_status.go:38-41 — the knative
# LivingConditionSet over ``Active``).
ACTIVE = "Active"


@dataclass
class Condition:
    """knative-style status condition (reference: provisioner_status.go:28-33
    — ``apis.Conditions``): ``status`` is "True"/"False"/"Unknown", and
    ``last_transition_time`` moves only when ``status`` flips."""

    type: str = ACTIVE
    status: str = "Unknown"
    severity: str = ""
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[float] = None


@dataclass
class ProvisionerStatus:
    last_scale_time: Optional[float] = None
    resources: Dict[str, float] = field(default_factory=dict)
    conditions: List[Condition] = field(default_factory=list)

    def condition(self, type: str = ACTIVE) -> Optional[Condition]:
        for c in self.conditions:
            if c.type == type:
                return c
        return None

    def set_condition(
        self,
        type: str = ACTIVE,
        status: str = "True",
        reason: str = "",
        message: str = "",
        now: Optional[float] = None,
    ) -> bool:
        """Set/refresh a condition with knative ConditionManager semantics:
        ``lastTransitionTime`` bumps only when the status value flips.
        Returns True when anything observable changed, so callers can skip
        the status write on steady-state reconciles."""
        cond = self.condition(type)
        if cond is None:
            self.conditions.append(
                Condition(
                    type=type, status=status, reason=reason, message=message,
                    last_transition_time=now,
                )
            )
            return True
        changed = (
            cond.status != status
            or cond.reason != reason
            or cond.message != message
        )
        if cond.status != status:
            cond.last_transition_time = now
        cond.status = status
        cond.reason = reason
        cond.message = message
        return changed

    def mark_active(self, now: Optional[float] = None) -> bool:
        return self.set_condition(ACTIVE, "True", now=now)

    def mark_not_active(
        self, reason: str, message: str, now: Optional[float] = None
    ) -> bool:
        return self.set_condition(ACTIVE, "False", reason, message, now=now)


@dataclass
class Provisioner:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="default", namespace=""))
    spec: ProvisionerSpec = field(default_factory=ProvisionerSpec)
    status: ProvisionerStatus = field(default_factory=ProvisionerStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


def validate_provisioner(provisioner: Provisioner) -> List[str]:
    """Spec validation (reference: provisioner_validation.go:34-132)."""
    errs: List[str] = []
    spec = provisioner.spec
    if spec.ttl_seconds_after_empty is not None and spec.ttl_seconds_after_empty < 0:
        errs.append("ttlSecondsAfterEmpty must be non-negative")
    if spec.ttl_seconds_until_expired is not None and spec.ttl_seconds_until_expired < 0:
        errs.append("ttlSecondsUntilExpired must be non-negative")
    if spec.solver not in (SOLVER_FFD, SOLVER_TPU):
        errs.append(f"solver must be one of [{SOLVER_FFD}, {SOLVER_TPU}], got {spec.solver}")
    if spec.disruption_budget is not None:
        from karpenter_tpu.controllers.disruption import parse_budget

        try:
            parse_budget(spec.disruption_budget)
        except ValueError as e:
            errs.append(f"disruptionBudget: {e}")
    c = spec.constraints
    for key, value in c.labels.items():
        errs.extend(lbl.check_qualified_name(key))
        err = lbl.check_restricted_label(key)
        if err:
            errs.append(err)
        if not value:
            errs.append(f"label {key} has empty value")
        else:
            errs.extend(lbl.check_label_value(value))
    for taint in c.taints:
        if not taint.key:
            errs.append("taint key must not be empty")
        else:
            errs.extend(lbl.check_qualified_name(taint.key))
        errs.extend(lbl.check_label_value(taint.value))
        if taint.effect not in ("NoSchedule", "PreferNoSchedule", "NoExecute"):
            errs.append(f"invalid taint effect {taint.effect}")
    for req in c.requirements.requirements:
        if req.operator not in SUPPORTED_PROVISIONER_OPS:
            errs.append(
                f"operator {req.operator} not in {sorted(SUPPORTED_PROVISIONER_OPS)} for key {req.key}"
            )
        # key syntax is covered by c.requirements.validate() below
        err = lbl.check_restricted_label(req.key)
        if err:
            errs.append(err)
    errs.extend(c.requirements.validate())
    return errs
