"""Lightweight Kubernetes-shaped object model.

The reference operates on ``k8s.io/api/core/v1`` types; this framework is not
a kubelet client, so it carries only the fields the provisioning logic reads.
Field names are pythonic but map 1:1 onto their Kubernetes counterparts.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_tpu.utils import resources as res

_uid_counter = itertools.count(1)


def _next_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)
    uid: str = field(default_factory=_next_uid)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """Kubernetes Toleration.ToleratesTaint semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if not self.key and self.operator != "Exists":
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)


@dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            val = labels.get(expr.key)
            if expr.operator == "In":
                if val not in expr.values:
                    return False
            elif expr.operator == "NotIn":
                if val in expr.values:
                    return False
            elif expr.operator == "Exists":
                if expr.key not in labels:
                    return False
            elif expr.operator == "DoesNotExist":
                if expr.key in labels:
                    return False
            else:
                return False
        return True


@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    topology_key: str = ""
    namespaces: List[str] = field(default_factory=list)


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class NodeAffinity:
    required: List[NodeSelectorTerm] = field(default_factory=list)  # OR of terms
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: Optional[LabelSelector] = None


@dataclass
class ContainerPort:
    host_port: int = 0
    host_ip: str = ""
    protocol: str = "TCP"


@dataclass
class Container:
    name: str = "app"
    requests: Dict[str, float] = field(default_factory=dict)
    limits: Dict[str, float] = field(default_factory=dict)
    ports: List[ContainerPort] = field(default_factory=list)


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    reason: str = ""


@dataclass
class PodStatus:
    phase: str = "Pending"
    conditions: List[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""


@dataclass
class PodSpec:
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    priority_class_name: str = ""
    volumes: List["Volume"] = field(default_factory=list)
    termination_grace_period_seconds: int = 30


@dataclass
class Volume:
    name: str = ""
    persistent_volume_claim: str = ""  # claim name, "" if not a PVC volume


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def resource_requests(self) -> Dict[str, float]:
        return res.merge(*(c.requests for c in self.spec.containers))

    def resource_limits(self) -> Dict[str, float]:
        return res.merge(*(c.limits for c in self.spec.containers))

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


@dataclass
class NodeStatus:
    capacity: Dict[str, float] = field(default_factory=dict)
    allocatable: Dict[str, float] = field(default_factory=dict)
    conditions: List[PodCondition] = field(default_factory=list)
    phase: str = ""


@dataclass
class NodeSpec:
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    provider_id: str = ""


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    pod_template: PodSpec = field(default_factory=PodSpec)


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class_name: str = ""
    volume_name: str = ""  # bound PV name


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # required node-affinity terms of the PV (zone constraints etc.)
    node_affinity_required: List[NodeSelectorTerm] = field(default_factory=list)


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # allowed topologies: list of terms; each term is a list of requirements
    allowed_topologies: List[NodeSelectorTerm] = field(default_factory=list)


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease — cluster-scoped leader election
    (reference: cmd/controller/main.go:84-85 LeaderElection id
    ``karpenter-leader-election``)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: Optional[float] = None
    renew_time: Optional[float] = None
    lease_transitions: int = 0


@dataclass
class ValidatingWebhookConfiguration:
    """admissionregistration.k8s.io/v1 — the apiserver-side registration of
    the admission webhook (reference: knative certificates.NewController
    keeps clientConfig.caBundle current, cmd/webhook/main.go:46-63).

    ``webhooks`` entries are kept as RAW wire dicts: the caBundle
    reconciler only rewrites ``clientConfig.caBundle`` and must round-trip
    every other field (rules, sideEffects, admissionReviewVersions, ...)
    byte-for-byte."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: List[Dict[str, object]] = field(default_factory=list)


@dataclass
class Event:
    """core/v1 Event — operator-visible record of a controller action
    (launch/terminate/consolidate). The reference snapshot emits none
    (SURVEY §5.5), so this is additive capability: kubectl describe on a
    node or provisioner shows what the controllers did to it."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # Normal | Warning
    count: int = 1
    source_component: str = "karpenter-tpu"
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
