"""Well-known label registry, normalization, and restriction rules.

Mirrors ``pkg/apis/provisioning/v1alpha5/labels.go`` and the group constants in
``register.go:229-246``.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Set

# Kubernetes well-known labels.
TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
TOPOLOGY_REGION = "topology.kubernetes.io/region"
INSTANCE_TYPE = "node.kubernetes.io/instance-type"
ARCH = "kubernetes.io/arch"
OS = "kubernetes.io/os"
HOSTNAME = "kubernetes.io/hostname"

# Group / domain constants (reference: register.go:229-246).
GROUP = "karpenter.sh"
LABEL_DOMAIN = GROUP
CAPACITY_TYPE = LABEL_DOMAIN + "/capacity-type"
PROVISIONER_NAME_LABEL = LABEL_DOMAIN + "/provisioner-name"
NOT_READY_TAINT_KEY = LABEL_DOMAIN + "/not-ready"
INTERRUPTION_TAINT_KEY = LABEL_DOMAIN + "/interruption"
DO_NOT_EVICT_ANNOTATION = LABEL_DOMAIN + "/do-not-evict"
# the client launch token stamped on both the cloud instance (tag/label)
# and the Node object at create — the idempotency key that pairs them for
# crash recovery (launch/journal.py) and the GC/adoption cross-check
LAUNCH_TOKEN_ANNOTATION = LABEL_DOMAIN + "/launch-token"
# present (value "true") on a node the warm-pool controller launched
# speculatively and no demand has claimed yet; removed at claim time by
# the worker's warm-hit steal — its absence is how the GC ladder tells a
# claimed warm node from stale speculation (controllers/warmpool.py)
WARM_POOL_ANNOTATION = LABEL_DOMAIN + "/warm-pool"
EMPTINESS_TIMESTAMP_ANNOTATION = LABEL_DOMAIN + "/emptiness-timestamp"
TERMINATION_FINALIZER = LABEL_DOMAIN + "/termination"

ARCH_AMD64 = "amd64"
ARCH_ARM64 = "arm64"
OS_LINUX = "linux"

CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"

RESTRICTED_LABEL_DOMAINS: Set[str] = {"kubernetes.io", "k8s.io", LABEL_DOMAIN}
LABEL_DOMAIN_EXCEPTIONS: Set[str] = {"kops.k8s.io"}

WELL_KNOWN_LABELS: Set[str] = {
    TOPOLOGY_ZONE,
    INSTANCE_TYPE,
    ARCH,
    OS,
    CAPACITY_TYPE,
}

RESTRICTED_LABELS: Set[str] = {
    EMPTINESS_TIMESTAMP_ANNOTATION,
    HOSTNAME,
}

# Aliased/beta labels → stable labels (reference: labels.go:66-73).
NORMALIZED_LABELS: Dict[str, str] = {
    "failure-domain.beta.kubernetes.io/zone": TOPOLOGY_ZONE,
    "beta.kubernetes.io/arch": ARCH,
    "beta.kubernetes.io/os": OS,
    "beta.kubernetes.io/instance-type": INSTANCE_TYPE,
    "failure-domain.beta.kubernetes.io/region": TOPOLOGY_REGION,
}

IGNORED_LABELS: Set[str] = {TOPOLOGY_REGION}


# Syntax rules (reference: provisioner_validation.go:75-100 via
# k8s.io/apimachinery validation.IsQualifiedName / IsValidLabelValue).
_NAME_RE = re.compile(r"^[A-Za-z0-9]([A-Za-z0-9\-_.]*[A-Za-z0-9])?$")
_DNS1123_SUBDOMAIN_RE = re.compile(
    r"^[a-z0-9]([a-z0-9\-]*[a-z0-9])?(\.[a-z0-9]([a-z0-9\-]*[a-z0-9])?)*$"
)
_MAX_NAME_LEN = 63
_MAX_PREFIX_LEN = 253


def check_qualified_name(key: str) -> List[str]:
    """Syntax errors for a label/taint key: ``[prefix/]name`` where the
    optional prefix is a DNS-1123 subdomain (≤253 chars) and the name is ≤63
    alphanumeric-bounded chars allowing ``-_.`` inside."""
    errs: List[str] = []
    parts = key.split("/")
    if len(parts) == 1:
        name = parts[0]
    elif len(parts) == 2:
        prefix, name = parts
        if not prefix:
            errs.append(f"{key}: prefix part must be non-empty")
        elif len(prefix) > _MAX_PREFIX_LEN:
            errs.append(f"{key}: prefix part must be no more than {_MAX_PREFIX_LEN} characters")
        elif not _DNS1123_SUBDOMAIN_RE.fullmatch(prefix):
            errs.append(f"{key}: prefix part must be a lowercase RFC 1123 subdomain")
    else:
        return [f"{key}: a qualified name must consist of a name part and an optional prefix part separated by a single '/'"]
    if not name:
        errs.append(f"{key}: name part must be non-empty")
    elif len(name) > _MAX_NAME_LEN:
        errs.append(f"{key}: name part must be no more than {_MAX_NAME_LEN} characters")
    elif not _NAME_RE.fullmatch(name):
        errs.append(
            f"{key}: name part must consist of alphanumeric characters, '-', '_' or '.', "
            "and must start and end with an alphanumeric character"
        )
    return errs


def check_label_value(value: str) -> List[str]:
    """Syntax errors for a label or taint value: empty or ≤63
    alphanumeric-bounded chars allowing ``-_.`` inside."""
    if not value:
        return []
    if len(value) > _MAX_NAME_LEN:
        return [f"{value}: must be no more than {_MAX_NAME_LEN} characters"]
    if not _NAME_RE.fullmatch(value):
        return [
            f"{value}: a valid label value must consist of alphanumeric characters, "
            "'-', '_' or '.', and must start and end with an alphanumeric character"
        ]
    return []


def _label_domain(key: str) -> str:
    if "/" in key:
        return key.split("/", 1)[0]
    return ""


def check_restricted_label(key: str) -> Optional[str]:
    """Return an error string if the label may not be used on a provisioner
    (reference: labels.go:83-97)."""
    if key in WELL_KNOWN_LABELS:
        return None
    if key in RESTRICTED_LABELS:
        return f"label is restricted, {key}"
    domain = _label_domain(key)
    if domain in LABEL_DOMAIN_EXCEPTIONS:
        return None
    for restricted in RESTRICTED_LABEL_DOMAINS:
        if domain.endswith(restricted):
            return f"label domain not allowed, {domain}"
    return None


def is_restricted_node_label(key: str) -> bool:
    """True if karpenter must not inject this label onto nodes it creates
    (reference: labels.go:100-109)."""
    domain = _label_domain(key)
    for restricted in RESTRICTED_LABEL_DOMAINS:
        if domain.endswith(restricted):
            return True
    return key in RESTRICTED_LABELS
