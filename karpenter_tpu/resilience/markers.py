"""Idempotency markers for retried callables.

``RetryPolicy.call`` re-invokes its callable on transient failure; that is
only sound for operations whose replay converges to the same state
(DELETE of a named resource, a catalog GET, an event poll with positions).
``@idempotent`` is the explicit, analyzer-enforced declaration of that
property: karplint's ``retry-idempotent`` rule requires it on every
callable a retrying policy can reach, and REJECTS it on create-path
mutators — ``create`` is breaker-only by design (a replayed create after
a partially-committed launch orphans an instance no Node tracks), and
marking it idempotent would invite someone to raise its ``max_attempts``.

The marker is metadata only (``fn.__idempotent__ = True``); it changes no
behavior, so applying it can never regress a passing call path.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)


def idempotent(fn: F) -> F:
    """Declare that replaying ``fn`` converges to the same end state."""
    fn.__idempotent__ = True  # type: ignore[attr-defined]
    return fn


def is_idempotent(fn: Callable) -> bool:
    return bool(getattr(fn, "__idempotent__", False))
