"""Retry policy and time budgets.

Backoff is the AWS-recommended *decorrelated jitter*: each sleep is drawn
uniformly from ``[base, 3 * previous_sleep]`` and capped, which spreads a
thundering herd of retries across the window instead of synchronizing it the
way plain exponential backoff does. Every operation also carries a hard
deadline — a flaky dependency may cost retries, never an unbounded stall —
and the deadline is further capped by the ambient per-reconcile-round
:class:`Budget` when one is active.
"""

from __future__ import annotations

import contextvars
import random
import time
from typing import Callable, Iterator, Optional

from karpenter_tpu import metrics


# The reconcile round currently executing, when the caller activated one.
# RetryPolicy caps its per-operation deadline by the budget's remaining
# time, so retries never outlive the round that issued them.
current_budget: contextvars.ContextVar[Optional["Budget"]] = contextvars.ContextVar(
    "resilience_budget", default=None
)


class Budget:
    """A wall-clock allowance for one reconcile round.

    One Budget object is shared by everything the round does (the launch
    thread pool re-activates it per thread): ``remaining()`` is global to
    the round, so a retry storm in one launch consumes the same allowance
    a slow solve does — the round degrades as a whole instead of each call
    independently stacking its own worst case.
    """

    def __init__(self, seconds: float, clock: Callable[[], float] = time.monotonic):
        self.seconds = float(seconds)
        self._clock = clock
        self._deadline = clock() + self.seconds

    def remaining(self) -> float:
        return max(self._deadline - self._clock(), 0.0)

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def activate(self) -> "_BudgetContext":
        """Install this budget as the calling thread's ambient budget
        (``with budget.activate(): ...``). Each thread activates its own
        context; the underlying deadline is shared."""
        return _BudgetContext(self)


class _BudgetContext:
    def __init__(self, budget: Budget):
        self._budget = budget
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> Budget:
        self._token = current_budget.set(self._budget)
        return self._budget

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            current_budget.reset(self._token)


def decorrelated_jitter(
    base: float,
    cap: float,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """Endless sleep sequence: ``sleep = min(cap, uniform(base, 3 * prev))``."""
    rng = rng or random
    sleep = base
    while True:
        sleep = min(cap, rng.uniform(base, sleep * 3))
        yield sleep


# Exceptions that retrying cannot fix: capacity signals (the ICE caches own
# those), positive not-found answers, validation/programming errors.
# Everything else — throttles, injected control-plane failures, connection
# resets — is presumed transient. Vendor errors are matched by name so this
# module needs no dependency on any provider.
_NON_RETRYABLE_NAMES = frozenset(
    {
        "InsufficientCapacityError",
        "GkeStockoutError",
        "GkeApiError",
        "InstanceNotFoundError",
        # overload-control verdicts (resilience/overload.py): a shed must
        # never become a retry storm, and an expired deadline cannot be
        # retried into existence
        "OverloadedError",
        "DeadlineExceededError",
    }
)


def default_retryable(exc: BaseException) -> bool:
    if isinstance(exc, (ValueError, TypeError, KeyError)):
        return False
    for cls in type(exc).__mro__:
        if cls.__name__ in _NON_RETRYABLE_NAMES:
            return False
    return True


class RetryPolicy:
    """Bounded retries with decorrelated jitter and a hard deadline.

    ``call(fn)`` runs ``fn`` up to ``max_attempts`` times. A retry happens
    only when ``retryable(exc)`` says so AND the next backoff sleep still
    fits inside the per-operation deadline (further capped by the active
    round :class:`Budget`); otherwise the last exception propagates. The
    ``dependency`` label feeds the ``retries_total`` /
    ``deadline_exceeded_total`` counters.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base: float = 0.05,
        cap: float = 2.0,
        deadline: float = 15.0,
        retryable: Callable[[BaseException], bool] = default_retryable,
        dependency: str = "",
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        retry_budget=None,
    ):
        self.max_attempts = max(int(max_attempts), 1)
        self.base = base
        self.cap = cap
        self.deadline = deadline
        self.retryable = retryable
        self.dependency = dependency
        self._rng = rng
        self._clock = clock
        self._sleep = sleep
        # per-dependency retry token bucket (resilience/overload.py):
        # None + a dependency label = the process-shared default budget;
        # budget accounting is skipped entirely for unlabeled policies
        # (no dependency to draw down)
        self._retry_budget = retry_budget

    def effective_deadline(self) -> float:
        """Seconds this operation may spend: the policy deadline, capped by
        the active round budget (if any). The first attempt always runs —
        an exhausted budget degrades to retry-free, not to no work."""
        budget = current_budget.get()
        if budget is None:
            return self.deadline
        return min(self.deadline, max(budget.remaining(), 0.0))

    def _budget(self):
        if self._retry_budget is not None:
            return self._retry_budget
        if not self.dependency:
            return None
        from karpenter_tpu.resilience.overload import default_retry_budget

        return default_retry_budget()

    def call(self, fn: Callable, *args, **kwargs):
        start = self._clock()
        allowance = self.effective_deadline()
        backoffs = decorrelated_jitter(self.base, self.cap, self._rng)
        budget = self._budget()
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                out = fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — classification decides
                last = e
                if attempt + 1 >= self.max_attempts or not self.retryable(e):
                    raise
                pause = next(backoffs)
                if self._clock() - start + pause > allowance:
                    metrics.RESILIENCE_DEADLINE_EXCEEDED.labels(
                        dependency=self.dependency or "unknown"
                    ).inc()
                    raise
                # the retry-budget gate: an overloaded dependency earns
                # fewer retries — once the bucket is dry the failure
                # propagates instead of multiplying offered load
                if budget is not None and not budget.try_spend(self.dependency):
                    metrics.RESILIENCE_RETRIES.labels(
                        dependency=self.dependency or "unknown",
                        outcome="budget_exhausted",
                    ).inc()
                    raise
                metrics.RESILIENCE_RETRIES.labels(
                    dependency=self.dependency or "unknown", outcome="retried"
                ).inc()
                self._sleep(pause)
            else:
                # successes refill the bucket: a recovered dependency
                # re-earns its retry headroom
                if budget is not None:
                    budget.record_success(self.dependency)
                return out
        raise last if last is not None else AssertionError("unreachable")
