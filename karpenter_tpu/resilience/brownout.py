"""The SLO-driven brownout ladder (docs/overload.md).

The PR-8 SLO engine *observes* burn; this controller *acts* on it. When
an objective is burning (both multi-window burn rates >= 1.0 — the page
condition), the controller walks an ordered degradation ladder, one rung
per sustained evaluation, and walks back down one rung at a time once the
burn clears. Every transition is a span + a cluster event + the
``karpenter_brownout_level`` gauge, so each degradation is auditable and
its reversal provable.

The ladder, in order (cheapest capability first):

1. **Pause exploration and voluntary disruption.** Router shadow probes
   re-measure LOSING backends — pure exploration — consolidation
   waves evict pods into the very pending-pod queue an overloaded
   provisioner is drowning in, and warm-pool speculation buys capacity
   for *predicted* demand while real demand burns. None of these costs
   any user anything to stop.
2. **Shrink the batcher admission window.** Small frequent rounds over
   giant stale ones: queued work stops aging a full ``max_duration``
   before its first solve (the queue IS the latency).
3. **Bias the CostRouter toward native/FFD.** Marginal device-vs-native
   races route to the host path; the device/wire budget goes to the
   shapes that need it. EMAs are untouched, so recovery is instant.
4. **Shed queued low-priority work.** Oldest-first, below-default
   priority classes only (``utils/pod.priority_of`` < 0): the one rung
   that drops work outright, and the last before the queues would decide
   for themselves.

Each tick RE-APPLIES the current level: batchers created after an
escalation (worker hot-swap) converge within one tick, and a knob some
other actor reset is re-asserted — the level gauge is always the truth.

The controller is deliberately dumb about *why* an objective burns: the
ladder order is the policy, the SLO engine is the sensor, and every rung
is independently reversible. ``escalate_after`` consecutive burning
evaluations move up one rung; ``recover_after`` consecutive clean ones
move down one — asymmetric on purpose (fast in, cautious out), the same
shape as a circuit breaker's half-open probing.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger("karpenter.brownout")

# ladder geometry
MAX_LEVEL = 4
LEVEL_NAMES = {
    0: "normal",
    1: "pause_probes_and_consolidation",
    2: "shrink_admission_window",
    3: "bias_router_native",
    4: "shed_low_priority_queue",
}
# admission-window pressure by level (utils/batcher.py set_pressure)
PRESSURE_BY_LEVEL = {0: 1.0, 1: 1.0, 2: 0.5, 3: 0.25, 4: 0.25}
# non-native EMA inflation while rung 3+ is engaged (solver/router.py)
ROUTER_BIAS = 8.0
# priority floor for the shed rung: strictly below the default class
# (utils/pod.priority_of maps "low-"/"best-effort-" names to -10)
SHED_PRIORITY_FLOOR = 0

DEFAULT_TICK_INTERVAL = 5.0
ESCALATE_AFTER = 2  # consecutive burning ticks per rung up
RECOVER_AFTER = 3  # consecutive clean ticks per rung down


def _default_burning() -> bool:
    """Any SLO objective currently burning (the multiwindow page
    condition), read from the process-default engine; False when no
    engine is configured."""
    from karpenter_tpu import obs

    engine = obs.slo_engine()
    if engine is None:
        return False
    return any(o.get("burning") for o in engine.burning_panel().values())


class BrownoutController:
    """Walks the degradation ladder off SLO burn state.

    ``burning_fn`` answers "is any objective burning right now";
    ``provisioning`` / ``consolidation`` / ``router`` are the actuation
    surfaces (any may be None — the rung that needs it becomes a no-op,
    the ladder keeps its shape). ``cluster`` receives the audit events.
    """

    def __init__(
        self,
        burning_fn: Optional[Callable[[], bool]] = None,
        provisioning=None,
        consolidation=None,
        router=None,
        warmpool=None,
        cluster=None,
        interval: float = DEFAULT_TICK_INTERVAL,
        escalate_after: int = ESCALATE_AFTER,
        recover_after: int = RECOVER_AFTER,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.burning_fn = burning_fn or _default_burning
        self.provisioning = provisioning
        self.consolidation = consolidation
        self.router = router
        # WarmPoolController: speculation is pure exploration spend, so it
        # pauses at rung 1 with the probes and consolidation waves
        self.warmpool = warmpool
        self.cluster = cluster
        self.interval = float(interval)
        self.escalate_after = max(int(escalate_after), 1)
        self.recover_after = max(int(recover_after), 1)
        self._clock = clock
        self._level = 0  # guarded-by: self._lock
        self._burning_streak = 0  # guarded-by: self._lock
        self._clean_streak = 0  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.transitions: list = []  # guarded-by: self._lock (audit trail)

    # -- state --------------------------------------------------------------

    def level(self) -> int:
        with self._lock:
            return self._level

    def report(self) -> dict:
        """Flight-recorder / debug panel view."""
        with self._lock:
            return {
                "level": self._level,
                "step": LEVEL_NAMES[self._level],
                "burning_streak": self._burning_streak,
                "clean_streak": self._clean_streak,
                "transitions": list(self.transitions[-8:]),
            }

    # -- the tick ------------------------------------------------------------

    def tick(self) -> int:
        """One evaluation: read burn state, maybe move one rung, re-apply
        the current level. Returns the level after the tick."""
        try:
            burning = bool(self.burning_fn())
        except Exception:
            # a broken sensor must not wedge the ladder at its current
            # rung forever — treat as clean so the system recovers
            logger.exception("brownout burn probe failed; treating as clean")
            burning = False
        with self._lock:
            if burning:
                self._burning_streak += 1
                self._clean_streak = 0
            else:
                self._clean_streak += 1
                self._burning_streak = 0
            new_level = self._level
            if burning and self._burning_streak >= self.escalate_after:
                new_level = min(self._level + 1, MAX_LEVEL)
                if new_level != self._level:
                    self._burning_streak = 0
            elif not burning and self._clean_streak >= self.recover_after:
                new_level = max(self._level - 1, 0)
                if new_level != self._level:
                    self._clean_streak = 0
            old_level, self._level = self._level, new_level
        if new_level != old_level:
            self._announce(old_level, new_level)
        self._apply(new_level)
        return new_level

    def _announce(self, old: int, new: int) -> None:
        """The audit trail: span + event + metrics for every transition."""
        direction = "escalate" if new > old else "recover"
        step = LEVEL_NAMES[new if new > old else old]
        from karpenter_tpu import metrics, obs

        with obs.tracer().span(
            "brownout.transition",
            attrs={
                "direction": direction, "from": old, "to": new, "step": step,
            },
        ):
            with self._lock:
                self.transitions.append(
                    {"direction": direction, "from": old, "to": new, "step": step}
                )
            try:
                metrics.BROWNOUT_TRANSITIONS.labels(direction=direction).inc()
            except Exception:
                pass  # trimmed registries
            logger.warning(
                "brownout %s: level %d -> %d (%s)", direction, old, new, step
            )
            if self.cluster is not None:
                from karpenter_tpu.kube.events import recorder_for

                try:
                    recorder_for(self.cluster).event(
                        "Brownout", "controller",
                        "BrownoutEscalated" if direction == "escalate"
                        else "BrownoutRecovered",
                        f"brownout level {old} -> {new} ({step}); "
                        "docs/overload.md has the ladder",
                        type="Warning" if direction == "escalate" else "Normal",
                    )
                except Exception:
                    logger.debug("brownout event write failed", exc_info=True)

    # -- actuation -----------------------------------------------------------

    def _apply(self, level: int) -> None:
        """Re-assert every knob for ``level`` (idempotent; runs each tick
        so late-created batchers and externally-reset knobs converge)."""
        from karpenter_tpu import metrics

        try:
            metrics.BROWNOUT_LEVEL.set(level)
        except Exception:
            pass  # trimmed registries
        if self.router is not None:
            self.router.set_probes_paused(level >= 1)
            self.router.set_brownout_bias(ROUTER_BIAS if level >= 3 else 1.0)
        if self.consolidation is not None:
            self.consolidation.set_paused(level >= 1)
        if self.warmpool is not None:
            self.warmpool.set_paused(level >= 1)
        pressure = PRESSURE_BY_LEVEL.get(level, PRESSURE_BY_LEVEL[MAX_LEVEL])
        for batcher in self._batchers():
            batcher.set_pressure(pressure)
            if level >= 4:
                shed = batcher.shed_low_priority(SHED_PRIORITY_FLOOR)
                if shed:
                    logger.warning(
                        "brownout shed %d queued low-priority pod(s)", shed
                    )

    def _batchers(self):
        if self.provisioning is None:
            return []
        try:
            return [w.batcher for w in self.provisioning.list_workers()]
        except Exception:
            return []

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="karpenter-brownout", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                logger.exception("brownout tick failed")
            self._stop.wait(self.interval)

    def stop(self) -> None:
        """Stop the loop and FULLY REVERSE: whatever rung the ladder was
        on, a stopped controller leaves no degradation behind."""
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)
        with self._lock:
            old, self._level = self._level, 0
            self._burning_streak = self._clean_streak = 0
        if old:
            self._announce(old, 0)
        self._apply(0)
