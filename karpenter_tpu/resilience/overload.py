"""Overload-control vocabulary shared across the wire, pool, and retry
layers (docs/overload.md).

Two failure classes that are NOT failures in the breaker sense:

- :class:`OverloadedError` — the dependency is alive but refusing work
  (bounded admission queue full, HBM pressure). It carries the server's
  retry-after hint. Tripping a circuit breaker on it would amplify the
  brownout into an outage: the breaker's half-open probes and the
  rerouted traffic both land on whatever capacity remains. Callers back
  off for the hint window instead (the pool's soft breaker).
- :class:`DeadlineExceededError` — the work's own deadline (the
  propagated per-round :class:`Budget`) expired. Retrying is by
  definition useless; the only correct move is the degradation floor.

Both are classified non-retryable by ``default_retryable`` so no
RetryPolicy anywhere turns a shed into a retry storm.

:class:`RetryBudget` is the third leg: even for retryable failures, a
dependency that keeps failing earns fewer retries. Tokens are spent per
retry and refilled by successes, so a healthy dependency retries freely
while a drowning one degrades to fail-fast — the client-side half of
admission control.
"""

from __future__ import annotations

import threading
from typing import Dict

# Retry-budget defaults: ~10 retries of burst headroom per dependency,
# earned back at one token per 10 successes. A dependency failing more
# than ~10% of the time exhausts the budget and fails fast — the classic
# retry-budget ratio (each success funds a tenth of a retry).
RETRY_BUDGET_CAPACITY = 10.0
RETRY_BUDGET_REFILL_PER_SUCCESS = 0.1


class OverloadedError(RuntimeError):
    """A dependency shed this request under load (not a failure: the
    dependency is alive and will recover — retry AFTER the hint, or
    route elsewhere).

    ``kind`` names which backpressure mechanism fired: ``"admission"``
    (the sidecar's bounded queue or HBM floor refused the work) or
    ``"credits"`` (the streaming transport's client-side flow-control
    window is empty — docs/solver-transport.md § Credit flow control).
    Consumers treat both identically (soft backoff for the hint window);
    the kind exists so backoff sites and metrics can attribute WHICH
    bound absorbed the excess."""

    def __init__(
        self, message: str, retry_after: float = 1.0, kind: str = "admission"
    ):
        super().__init__(message)
        self.retry_after = max(float(retry_after), 0.0)
        self.kind = kind


class DeadlineExceededError(RuntimeError):
    """The operation's propagated deadline expired before (or while) the
    work ran — non-retryable by construction; take the degradation floor."""


class RetryBudget:
    """Per-dependency retry token bucket, refilled by successes.

    ``try_spend`` consumes one token per retry attempt; ``record_success``
    refills ``refill_per_success`` tokens (capped). Fresh dependencies
    start with a full bucket so transient blips retry normally; a
    sustained failure rate drains it and retries self-limit instead of
    multiplying offered load onto an overloaded dependency.
    """

    def __init__(
        self,
        capacity: float = RETRY_BUDGET_CAPACITY,
        refill_per_success: float = RETRY_BUDGET_REFILL_PER_SUCCESS,
    ):
        self.capacity = float(capacity)
        self.refill_per_success = float(refill_per_success)
        self._tokens: Dict[str, float] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()

    def try_spend(self, dependency: str) -> bool:
        """Spend one retry token; False means the budget is exhausted and
        the caller must propagate the failure instead of retrying."""
        with self._lock:
            tokens = self._tokens.get(dependency, self.capacity)
            if tokens < 1.0:
                return False
            self._tokens[dependency] = tokens - 1.0
            return True

    def record_success(self, dependency: str) -> None:
        with self._lock:
            tokens = self._tokens.get(dependency, self.capacity)
            self._tokens[dependency] = min(
                self.capacity, tokens + self.refill_per_success
            )

    def remaining(self, dependency: str) -> float:
        with self._lock:
            return self._tokens.get(dependency, self.capacity)

    def snapshot(self) -> Dict[str, float]:
        """{dependency: tokens} for dependencies that have drawn down —
        a flight-recorder-friendly view of who is earning retries."""
        with self._lock:
            return {k: round(v, 3) for k, v in sorted(self._tokens.items())}


# Process-shared default: every RetryPolicy with a dependency label draws
# from one bucket per dependency, so concurrent callers (launch pool
# threads, pollers) share the same self-limit instead of each bringing a
# fresh budget to the same drowning dependency.
_default_lock = threading.Lock()
_default: RetryBudget = RetryBudget()


def default_retry_budget() -> RetryBudget:
    with _default_lock:
        return _default


def reset_default_retry_budget() -> None:
    """Tests isolate budget drawdown with this."""
    global _default
    with _default_lock:
        _default = RetryBudget()
