"""Typed integrity verdicts (docs/integrity.md).

Stdlib-only on purpose, exactly like :mod:`resilience.overload`: the solver
sidecar's trimmed images import these through ``solver/service.py``, so the
module must not pull the metrics registry or any third-party dependency.

An :class:`IntegrityError` is the corruption-defense subsystem's one typed
verdict: a frame that failed its end-to-end checksum, a response the codec
could not parse while integrity checking was negotiated, a Pack echoing the
WRONG catalog session key even after a forced re-open, or a pack result
that failed the host-side NaN/bounds screen. It is deliberately NOT a
subclass of the overload verdicts — overload is backpressure (retry
elsewhere, or later); corruption is a correctness failure whose source must
be quarantined:

- **never retryable on the same member** — the pool fails the solve over
  to the next ring member and fires ``CircuitBreaker.trip()`` (the
  immediate-OPEN correctness edge, not the windowed availability path) on
  the member that produced the corrupt frame;
- **always loud** — a checksum mismatch raises, it never degrades into a
  silently wrong array the way a tolerated mis-parse would.
"""

from __future__ import annotations


class IntegrityError(RuntimeError):
    """A wire frame or pack result failed an end-to-end integrity check.

    ``address`` names the peer the corrupt data is attributed to (empty
    for the in-process path); ``kind`` says which defense layer fired:
    ``checksum`` (frame digest mismatch, either side), ``frame`` (the
    codec could not parse a frame while integrity was negotiated —
    truncation), ``session`` (a Pack echoed the wrong catalog session key
    even after a forced re-open), ``screen`` (host-side NaN/bounds screen)
    or ``canary`` (the native cross-check disagreed with the served pack).
    """

    def __init__(self, message: str, address: str = "", kind: str = "checksum"):
        super().__init__(message)
        self.address = address
        self.kind = kind
