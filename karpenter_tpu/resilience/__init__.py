"""Unified failure handling for the provisioning loop's I/O seams.

The reference survives AWS throttling and ICE storms with per-call backoff
(aws/instance.go retries, the 45s unavailable-offerings cache); this package
makes that posture a first-class, observable subsystem shared by every
dependency the controllers talk to — the cloud control plane, the HTTP wire,
and the solver service:

- :class:`RetryPolicy` — decorrelated-jitter exponential backoff with a hard
  per-operation deadline (and a hook into the ambient :class:`Budget`).
- :class:`CircuitBreaker` — closed/open/half-open per dependency, tripping on
  a windowed failure rate so a dead dependency costs one bounded failure,
  not one per call.
- :class:`Budget` — a per-reconcile-round time budget the callers consume;
  retry deadlines never outlive the round that issued them.
- :class:`MissTracker` — N-consecutive-miss liveness accounting, so one
  flaky describe can't orphan a healthy node.

The chaos harness that proves all of this works lives in
``karpenter_tpu/testing/chaos.py``; policy defaults and the thresholds are
documented in ``docs/resilience.md``.
"""

from karpenter_tpu.resilience.breaker import (  # noqa: F401
    BreakerBoard,
    BreakerOpen,
    CircuitBreaker,
)
from karpenter_tpu.resilience.brownout import (  # noqa: F401
    BrownoutController,
    LEVEL_NAMES as BROWNOUT_LEVEL_NAMES,
)
from karpenter_tpu.resilience.integrity import IntegrityError  # noqa: F401
from karpenter_tpu.resilience.liveness import MissTracker  # noqa: F401
from karpenter_tpu.resilience.markers import idempotent, is_idempotent  # noqa: F401
from karpenter_tpu.resilience.overload import (  # noqa: F401
    DeadlineExceededError,
    OverloadedError,
    RetryBudget,
    default_retry_budget,
    reset_default_retry_budget,
)
from karpenter_tpu.resilience.policy import (  # noqa: F401
    Budget,
    RetryPolicy,
    current_budget,
    decorrelated_jitter,
    default_retryable,
)
