"""Circuit breaker: per-dependency closed/open/half-open state machine.

Trips on a *windowed failure rate* (last ``window`` outcomes, at least
``min_volume`` of them, failure fraction ≥ ``failure_rate``) rather than a
consecutive-failure count, so an intermittently flaky dependency under
chaos-level error rates (~10%) keeps flowing while a dead one opens within
a handful of calls. While open, ``allow()`` answers False — the caller
fails fast (or degrades) instead of paying the failure latency per call.
After ``open_seconds`` the breaker admits up to ``half_open_max`` probe
calls; a probe success closes the breaker (window cleared), a probe
failure re-opens it for another ``open_seconds``.

State is exported on the scrape as ``karpenter_resilience_breaker_state``
(0 closed / 1 open / 2 half-open) per dependency.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from karpenter_tpu import metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class BreakerOpen(Exception):
    """The dependency's circuit is open; the call was not attempted."""

    def __init__(self, dependency: str, retry_in: float):
        super().__init__(
            f"circuit breaker for {dependency} is open (retry in {retry_in:.1f}s)"
        )
        self.dependency = dependency
        self.retry_in = retry_in


class CircuitBreaker:
    def __init__(
        self,
        dependency: str = "",
        window: int = 20,
        min_volume: int = 5,
        failure_rate: float = 0.5,
        open_seconds: float = 10.0,
        half_open_max: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.dependency = dependency
        self.window = int(window)
        self.min_volume = int(min_volume)
        self.failure_rate = float(failure_rate)
        self.open_seconds = float(open_seconds)
        self.half_open_max = int(half_open_max)
        self._clock = clock
        self._mu = threading.Lock()
        self._outcomes: deque = deque(maxlen=self.window)  # True = failure; guarded-by: self._mu
        self._state = CLOSED  # guarded-by: self._mu
        self._opened_at = 0.0  # guarded-by: self._mu
        self._probes_in_flight = 0  # guarded-by: self._mu
        self.trips = 0  # times the breaker transitioned to OPEN; guarded-by: self._mu
        self._publish()

    # -- state -------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    def _publish(self) -> None:
        if self.dependency:
            metrics.RESILIENCE_BREAKER_STATE.labels(
                dependency=self.dependency
            ).set(_STATE_CODE[self._state])

    def _retry_in(self) -> float:
        return max(self._opened_at + self.open_seconds - self._clock(), 0.0)

    def available(self) -> bool:
        """Non-consuming peek: would a call be admitted right now? (Open
        breakers whose cool-off elapsed answer True — the next ``allow()``
        turns that into a half-open probe.)"""
        with self._mu:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return self._retry_in() <= 0.0
            return self._probes_in_flight < self.half_open_max

    def allow(self) -> bool:
        """Admit one call. In half-open, reserves a probe slot — the caller
        MUST follow up with record_success/record_failure."""
        with self._mu:
            if self._state == OPEN and self._retry_in() <= 0.0:
                self._state = HALF_OPEN
                self._probes_in_flight = 0
                self._publish()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and self._probes_in_flight < self.half_open_max:
                self._probes_in_flight += 1
                return True
            return False

    # -- outcomes ----------------------------------------------------------
    def record_success(self) -> None:
        with self._mu:
            if self._state == HALF_OPEN:
                # the probe worked: close and forget the failure history
                self._outcomes.clear()
                self._probes_in_flight = 0
                self._state = CLOSED
                self._publish()
                return
            self._outcomes.append(False)

    def record_failure(self) -> bool:
        """Record one failure; returns True when this failure OPENED the
        breaker (callers increment their trip counters on that edge)."""
        with self._mu:
            if self._state == HALF_OPEN:
                self._probes_in_flight = 0
                self._state = OPEN
                self._opened_at = self._clock()
                self.trips += 1
                self._publish()
                return True
            self._outcomes.append(True)
            if self._state != CLOSED:
                return False
            volume = len(self._outcomes)
            if volume < self.min_volume:
                return False
            if sum(self._outcomes) / volume < self.failure_rate:
                return False
            self._state = OPEN
            self._opened_at = self._clock()
            self.trips += 1
            self._publish()
            return True

    def trip(self) -> None:
        """Force the breaker OPEN immediately — the quarantine edge for
        CORRECTNESS violations (e.g. a pack result that failed host-side
        validation), which must not wait out the windowed failure rate the
        availability path uses."""
        with self._mu:
            if self._state != OPEN:
                self.trips += 1
            self._probes_in_flight = 0
            self._state = OPEN
            self._opened_at = self._clock()
            self._publish()

    # -- convenience -------------------------------------------------------
    def call(self, fn: Callable, *args, **kwargs):
        """``allow → fn → record``; raises :class:`BreakerOpen` without
        calling ``fn`` when the circuit is open."""
        if not self.allow():
            with self._mu:
                retry_in = self._retry_in()
            raise BreakerOpen(self.dependency or "dependency", retry_in)
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result


class BreakerBoard:
    """Lazily-created breakers keyed by dependency name, sharing one
    configuration — the per-(provider, method) and per-shape-class breaker
    families both hang off one of these."""

    def __init__(self, clock: Callable[[], float] = time.monotonic, **breaker_kwargs):
        self._clock = clock
        self._kwargs = breaker_kwargs
        self._breakers: Dict[str, CircuitBreaker] = {}  # guarded-by: self._mu
        self._mu = threading.Lock()

    def get(self, dependency: str) -> CircuitBreaker:
        with self._mu:
            breaker = self._breakers.get(dependency)
            if breaker is None:
                breaker = self._breakers[dependency] = CircuitBreaker(
                    dependency=dependency, clock=self._clock, **self._kwargs
                )
            return breaker

    def open_dependencies(self) -> list:
        """Dependencies whose breaker is currently REFUSING calls (open and
        still inside its cool-off) — the bench/e2e check that none stays
        open once a chaos storm window ends. An open breaker whose cool-off
        elapsed is probe-ready, not stuck: the next call re-admits it."""
        with self._mu:
            items = list(self._breakers.items())
        return [
            name for name, b in items if b.state == OPEN and not b.available()
        ]
