"""N-consecutive-miss liveness accounting.

``SimCloudAPI.describe_instances`` (like EC2's) silently drops ids it does
not know — indistinguishable, on one response, from "the instance was
terminated out from under us". Declaring a node dead on a single miss
orphans healthy capacity whenever the control plane flakes; this tracker
requires ``threshold`` consecutive misses before a subject is considered
gone, and any sighting (or an errored describe, which callers report as
neither) resets the count.
"""

from __future__ import annotations

import threading
from typing import Dict


class MissTracker:
    # mid-streak subjects whose probes simply stop (node reaped by another
    # path) would otherwise accumulate forever; evict oldest past this
    MAX_SUBJECTS = 4096

    def __init__(self, threshold: int = 3):
        self.threshold = max(int(threshold), 1)
        self._misses: Dict[str, int] = {}
        self._mu = threading.Lock()

    def observe(self, subject: str, present: bool) -> bool:
        """Record one describe outcome; True once ``subject`` has been
        missing from ``threshold`` consecutive responses."""
        with self._mu:
            if present:
                self._misses.pop(subject, None)
                return False
            count = self._misses.pop(subject, 0) + 1
            # re-insert at the back: dict order makes eviction oldest-first
            self._misses[subject] = count
            while len(self._misses) > self.MAX_SUBJECTS:
                self._misses.pop(next(iter(self._misses)))
            return count >= self.threshold

    def misses(self, subject: str) -> int:
        with self._mu:
            return self._misses.get(subject, 0)

    def forget(self, subject: str) -> None:
        """Drop a subject (its node is gone for a confirmed reason)."""
        with self._mu:
            self._misses.pop(subject, None)
