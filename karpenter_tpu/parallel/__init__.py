from karpenter_tpu.parallel.sharding import (  # noqa: F401
    make_solver_mesh,
    sharded_multi_solve,
)
