"""Multi-chip solver sharding.

The reference scales by multiplying processes (leader-elected controllers,
10k concurrent reconciles — SURVEY.md §2.9); this framework scales the solve
itself across a TPU slice via ``jax.sharding``:

- **data axis**: independent provisioner batches (multi-Provisioner sharding,
  BASELINE config 4) are vmapped and sharded one-per-device-group — the DP
  analog.
- **model axis**: the instance-type dimension of the post-pack
  cheapest-type/feasibility computation is sharded — the TP analog — and XLA
  inserts the cross-shard argmin collectives over ICI.

The packing scan itself is sequential per batch (first-fit is a chain), so
parallelism comes from batching many solves — which is exactly the shape of
the production workload (many Provisioners, consolidation re-packs, and
what-if scoring).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu.solver import kernel


def make_solver_mesh(n_devices: Optional[int] = None, model_parallel: int = 1) -> Mesh:
    """2D mesh over (data, model). ``model_parallel`` shards the instance-type
    axis; the rest of the devices shard independent solve batches."""
    devices = np.array(jax.devices()[: n_devices or len(jax.devices())])
    data = len(devices) // model_parallel
    return Mesh(devices.reshape(data, model_parallel), ("data", "model"))


@partial(jax.jit, static_argnames=("n_max",))
def _packed_multi(
    pod_valid, pod_open_sig, pod_core, pod_host, pod_host_in_base, pod_open_host,
    pod_req, join_table, frontiers, daemon, n_max,
):
    """vmap of the packing kernel over a leading batch axis [B, ...]."""
    return jax.vmap(
        lambda *a: kernel.pack(*a, n_max=n_max)
    )(pod_valid, pod_open_sig, pod_core, pod_host, pod_host_in_base, pod_open_host,
      pod_req, join_table, frontiers, daemon)


@jax.jit
def _cheapest_multi(node_req, node_sig, sig_type_mask, usable, prices):
    """Batched cheapest-fitting-type: [B,N,R]×[B,S,T]×[T,R]×[T] → [B,N].
    With ``usable``/``prices`` sharded over the type axis, XLA turns the
    argmin into a cross-shard reduction over ICI."""
    def one(nr, ns, mask):
        m = mask[jnp.clip(ns, 0)]  # [N, T]
        fits = jnp.all(nr[:, None, :] <= usable[None, :, :], axis=-1)  # [N, T]
        ok = m & fits & (ns >= 0)[:, None]
        cost = jnp.where(ok, prices[None, :], jnp.inf)
        best = jnp.argmin(cost, axis=-1)
        has = jnp.any(ok, axis=-1)
        return jnp.where(has, best, -1).astype(jnp.int32)

    return jax.vmap(one)(node_req, node_sig, sig_type_mask)


_BATCH_SPECS = (
    P("data"), P("data"), P("data"), P("data"), P("data"), P("data"),
    P("data", None, None),  # pod_req [B, P, R]
    P("data", None, None),  # join_table [B, S, C]
    P("data", None, None, None),  # frontiers [B, S, F, R]
    P("data", None),  # daemon [B, R]
)


def _pallas_v2_multi(mesh: Mesh, batch_arrays: Tuple, n_max: int):
    """Per-shard vmapped v2 (matmul-gather) Pallas kernel for
    constraint-diverse stacks whose S·F exceeds the v1 unroll budget
    (VERDICT r2 #4: these used to fall silently to the vmapped lax.scan).
    The per-batch join-table/frontier precompute runs on host (numpy, B is
    small); the kernels run sharded over the 'data' axis."""
    from jax.experimental.shard_map import shard_map

    from karpenter_tpu.solver import pallas_kernel_v2 as v2

    (pod_valid, pod_open_sig, pod_core, pod_host, pod_host_in_base,
     pod_open_host, pod_req, join_table, frontiers, daemon) = [
        np.asarray(a) for a in batch_arrays
    ]
    B, P_pods, R = pod_req.shape
    F = frontiers.shape[2]
    fj, cj, jv, of = [], [], [], []
    for b in range(B):
        f_b, c_b, j_b, _ = v2._precompute(
            join_table[b], frontiers[b].astype(np.float32)
        )
        fj.append(f_b)
        cj.append(c_b)
        jv.append(j_b)
        of.append(
            v2._open_fits_host(
                pod_open_sig[b], pod_req[b].astype(np.float32),
                frontiers[b].astype(np.float32), daemon[b].astype(np.float32),
            ).reshape(1, P_pods).astype(np.int32)
        )
    pod_scal = np.stack(
        [
            np.stack(
                [
                    pod_valid[b].astype(np.int32),
                    pod_open_sig[b].astype(np.int32),
                    pod_core[b].astype(np.int32),
                    pod_host[b].astype(np.int32),
                    pod_host_in_base[b].astype(np.int32),
                    pod_open_host[b].astype(np.int32),
                ]
            )
            for b in range(B)
        ]
    )  # [B, 6, P]
    args = (
        pod_scal,
        np.transpose(pod_req, (0, 2, 1)).astype(np.float32),  # [B, R, P]
        np.stack(fj),
        np.stack(cj),
        np.stack(jv),
        np.stack(of),
        daemon.astype(np.float32).reshape(B, R, 1),
    )
    specs = tuple(P("data", *([None] * (a.ndim - 1))) for a in args)

    def per_device(*local):
        # sequential over the device's local batches — B/data is small and
        # each pack saturates its core's VPU/MXU anyway
        return jax.lax.map(
            lambda xs: v2._pack_v2_call(*xs, n_max=n_max, F=F, R=R), local
        )

    run = partial(
        shard_map,
        mesh=mesh,
        in_specs=specs,
        out_specs=(P("data", None, None),) * 4 + (P("data", None, None),),
        check_rep=False,
    )(per_device)
    assignment, node_sig, node_host, node_req_t, count = run(*args)
    return kernel.PackResult(
        assignment=assignment[:, 0, :],
        node_sig=node_sig[:, 0, :n_max],
        node_host=node_host[:, 0, :n_max],
        node_req=jnp.transpose(node_req_t[:, :, :n_max], (0, 2, 1)),
        n_nodes=count[:, 0, 0],
    )


@partial(jax.jit, static_argnames=("mesh", "n_max"))
def _pallas_multi(mesh: Mesh, *placed, n_max: int):
    """Per-shard vmapped Pallas kernel via shard_map: each device packs its
    local slice of the batch axis in-kernel (VERDICT r1: the multi-solve
    used to vmap the slow lax.scan kernel even on TPU)."""
    from jax.experimental.shard_map import shard_map

    from karpenter_tpu.solver.pallas_kernel import pack_pallas

    run = partial(
        shard_map,
        mesh=mesh,
        in_specs=_BATCH_SPECS,
        out_specs=kernel.PackResult(
            P("data"), P("data"), P("data"), P("data"), P("data")
        ),
        check_rep=False,
    )(lambda *a: jax.vmap(lambda *x: pack_pallas(*x, n_max=n_max))(*a))
    return run(*placed)


def sharded_multi_solve(
    mesh: Mesh,
    batch_arrays: Tuple,  # stacked [B, ...] kernel inputs
    sig_type_mask,  # [B, S, T] bool
    usable,  # [T, R] f32
    prices,  # [T] f32
    n_max: int,
):
    """Run B independent packing problems across the mesh and pick each
    node's cheapest launchable type, with the batch axis sharded over 'data'
    and the instance-type axis over 'model'. On a TPU backend the per-shard
    pack runs as the Pallas kernel (assignment-identical; parity-tested),
    falling back to the vmapped lax.scan kernel elsewhere.

    Returns ``(PackResult, cheapest, route)`` — ``route`` is this call's
    route + shape-gate report (returned, not a module global, so concurrent
    sharded solves can't clobber each other's report — ADVICE r4)."""
    def shard(spec):
        return NamedSharding(mesh, spec)

    placed = tuple(
        jax.device_put(a, shard(s)) for a, s in zip(batch_arrays, _BATCH_SPECS)
    )
    result = None
    from karpenter_tpu.solver.pallas_kernel import (
        _pallas_failed_shapes,
        pallas_available,
    )
    from karpenter_tpu.solver.pallas_kernel_v2 import v2_vmem_ok

    B, P_pods = batch_arrays[6].shape[0], batch_arrays[6].shape[1]
    S, F = batch_arrays[8].shape[1], batch_arrays[8].shape[2]
    R = batch_arrays[6].shape[2]
    C = batch_arrays[7].shape[2]
    from karpenter_tpu.solver.pallas_kernel import BLOCK, PALLAS_UNROLL_BUDGET

    # PURE shape gates, evaluated unconditionally so the route report (and
    # the CPU-mesh dryrun) always traverses them; pallas_available() is
    # applied only at dispatch below
    v1_shape_ok = (
        P_pods % BLOCK == 0
        and S * F <= PALLAS_UNROLL_BUDGET
        and B % mesh.shape["data"] == 0
    )
    v2_shape_ok = (
        P_pods % 128 == 0
        and B % mesh.shape["data"] == 0
        and v2_vmem_ok(S, n_max, C, F * R)
    )
    route = {
        "route": "lax.scan-multi",
        "v1_shape_eligible": bool(v1_shape_ok),
        "v2_shape_eligible": bool(v2_shape_ok),
        "S": int(S), "F": int(F), "B": int(B), "P": int(P_pods),
    }
    shape_key = ("multi", B, P_pods, n_max)
    if shape_key not in _pallas_failed_shapes and v1_shape_ok and pallas_available():
        try:
            result = _pallas_multi(mesh, *placed, n_max=n_max)
            route["route"] = "pallas-v1-multi"
        except Exception:
            import logging

            # memoized: a pathological shape must pay the failed Mosaic
            # compile once, not on every solve tick
            _pallas_failed_shapes.add(shape_key)
            logging.getLogger("karpenter.solver").exception(
                "pallas multi-solve failed for %s; lax.scan fallback", shape_key
            )
    if result is None:
        # constraint-diverse stacks past the v1 unroll budget: the v2
        # (matmul-gather, compile O(F)) kernel — same ladder as pack_best
        v2_key = ("multi-v2", B, P_pods, n_max)
        if v2_key not in _pallas_failed_shapes and pallas_available() and v2_shape_ok:
            try:
                result = _pallas_v2_multi(mesh, batch_arrays, n_max=n_max)
                route["route"] = "pallas-v2-multi"
            except Exception:
                import logging

                _pallas_failed_shapes.add(v2_key)
                logging.getLogger("karpenter.solver").exception(
                    "pallas v2 multi-solve failed for %s; lax.scan fallback", v2_key
                )
    if result is None:
        result = _packed_multi(*placed, n_max=n_max)

    mask_s = jax.device_put(sig_type_mask, shard(P("data", None, "model")))
    usable_s = jax.device_put(usable, shard(P("model", None)))
    prices_s = jax.device_put(prices, shard(P("model")))
    cheapest = _cheapest_multi(result.node_req, result.node_sig, mask_s, usable_s, prices_s)
    return result, cheapest, route
