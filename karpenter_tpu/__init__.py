"""karpenter-tpu: a TPU-native node-provisioning autoscaler framework.

A ground-up rebuild of the capabilities of Karpenter (reference snapshot
~v0.8.0, Go) with the scheduling hot loop re-designed as a batched tensor
solver on TPU (JAX/XLA), selected per-Provisioner via ``spec.solver``.

Package map (mirrors reference layer map, SURVEY.md §1):

- ``api``            Provisioner CRD types, Requirements algebra, labels
                     (reference: pkg/apis/provisioning/v1alpha5)
- ``utils``          complement sets, resource arithmetic, pod predicates,
                     batcher, clocks (reference: pkg/utils)
- ``cloudprovider``  CloudProvider/InstanceType interfaces, fake + simulated
                     providers (reference: pkg/cloudprovider)
- ``scheduling``     FFD reference scheduler + topology (reference:
                     pkg/controllers/provisioning/scheduling)
- ``solver``         the TPU-native batch bin-pack solver: tensor encoding,
                     jitted kernels, multi-chip sharding, solve service
                     (new capability; replaces the FFD hot loop)
- ``controllers``    reconcile loops: provisioning, selection, node lifecycle,
                     termination, counter, metrics (reference: pkg/controllers)
- ``kube``           in-memory cluster state store with watches (the test/e2e
                     substrate; reference uses envtest + controller-runtime)
"""

__version__ = "0.1.0"
