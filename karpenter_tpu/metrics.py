"""Prometheus metrics with the reference's metric names
(reference: pkg/metrics/constants.go, scheduling/scheduler.go:37-50,
provisioning/provisioner.go:183-196).

Uses its own registry so repeated imports/tests don't collide with the global
default registry.
"""

from __future__ import annotations

from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram

NAMESPACE = "karpenter"

REGISTRY = CollectorRegistry()

# controller-runtime-compatible duration buckets
# (reference: pkg/metrics/constants.go:33-40).
DURATION_BUCKETS = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.15, 0.2, 0.25, 0.3, 0.35, 0.4, 0.45, 0.5,
    0.6, 0.7, 0.8, 0.9, 1.0, 1.25, 1.5, 1.75, 2.0, 2.5, 3.0, 3.5, 4.0, 4.5,
    5.0, 6.0, 7.0, 8.0, 9.0, 10.0, 15.0, 20.0, 25.0, 30.0, 40.0, 50.0, 60.0,
]

SCHEDULING_DURATION = Histogram(
    "scheduling_duration_seconds",
    "Duration of scheduling process in seconds. Broken down by provisioner.",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="allocation_controller",
    buckets=DURATION_BUCKETS,
    registry=REGISTRY,
)

BIND_DURATION = Histogram(
    "bind_duration_seconds",
    "Duration of bind process in seconds. Broken down by result.",
    ["result"],
    namespace=NAMESPACE,
    subsystem="allocation_controller",
    buckets=DURATION_BUCKETS,
    registry=REGISTRY,
)

CLOUDPROVIDER_DURATION = Histogram(
    "duration_seconds",
    "Duration of cloud provider method calls.",
    ["controller", "method", "provider"],
    namespace=NAMESPACE,
    subsystem="cloudprovider",
    buckets=DURATION_BUCKETS,
    registry=REGISTRY,
)

# Per-node resource gauges (reference: metrics/node/controller.go:53-110).
NODE_GAUGE_LABELS = [
    "node_name", "provisioner", "zone", "arch", "capacity_type",
    "instance_type", "phase", "resource_type",
]


def _node_gauge(name: str, doc: str) -> Gauge:
    return Gauge(name, doc, NODE_GAUGE_LABELS, registry=REGISTRY)


NODES_ALLOCATABLE = _node_gauge(
    "karpenter_nodes_allocatable", "Resources allocatable by nodes."
)
NODES_TOTAL_POD_REQUESTS = _node_gauge(
    "karpenter_nodes_total_pod_requests",
    "Total resources requested by non-daemonset pods on the node.",
)
NODES_TOTAL_POD_LIMITS = _node_gauge(
    "karpenter_nodes_total_pod_limits",
    "Total resource limits of non-daemonset pods on the node.",
)
NODES_TOTAL_DAEMON_REQUESTS = _node_gauge(
    "karpenter_nodes_total_daemon_requests",
    "Total resources requested by daemonset pods on the node.",
)
NODES_TOTAL_DAEMON_LIMITS = _node_gauge(
    "karpenter_nodes_total_daemon_limits",
    "Total resource limits of daemonset pods on the node.",
)
NODES_SYSTEM_OVERHEAD = _node_gauge(
    "karpenter_nodes_system_overhead",
    "Difference between node capacity and allocatable.",
)

# back-compat alias
NODES_GAUGE = NODES_ALLOCATABLE

PODS_STATE_GAUGE = Gauge(
    "karpenter_pods_state",
    "Pod state is the current state of pods.",
    ["name", "namespace", "owner", "node", "provisioner", "zone", "arch",
     "capacity_type", "instance_type", "phase"],
    registry=REGISTRY,
)

# Sidecar circuit-breaker observability (VERDICT r1 weak #7): a dead solver
# service must be visible on the scrape, not only in logs.
SOLVER_BREAKER_OPEN = Gauge(
    "breaker_open",
    "1 while the solver-service circuit breaker is open (requests served in-process).",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_BREAKER_TRIPS = Counter(
    "breaker_trips_total",
    "Times the solver-service circuit breaker opened after an RPC failure.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

# Provisioner readiness on the scrape (reference: the knative Active
# condition, provisioner_status.go:38-41): 1 while the last Apply
# succeeded, 0 while it is failing.
PROVISIONER_ACTIVE = Gauge(
    "provisioner_active",
    "1 while the Provisioner's Active condition is True (last Apply succeeded).",
    ["provisioner"],
    namespace=NAMESPACE,
    registry=REGISTRY,
)

# Interruption subsystem (karpenter_tpu/interruption): cloud-initiated
# disruption handling must be visible on the scrape — notices in, drains
# through, and the two outcome measures: pods evicted with no replacement
# ready (the number that must stay 0 under clean preemption) and how long
# replaced workloads waited for new capacity.
INTERRUPTION_NOTICES = Counter(
    "notices_total",
    "Disruption notices received, by kind (preemption/maintenance/"
    "capacity-reclaim) and cloud provider.",
    ["kind", "provider"],
    namespace=NAMESPACE,
    subsystem="interruption",
    registry=REGISTRY,
)

INTERRUPTION_DRAINS_STARTED = Counter(
    "drains_started_total",
    "Nodes handed to termination because of a disruption notice.",
    namespace=NAMESPACE,
    subsystem="interruption",
    registry=REGISTRY,
)

INTERRUPTION_DRAINS_COMPLETED = Counter(
    "drains_completed_total",
    "Disrupted nodes fully terminated (gracefully or at the deadline).",
    namespace=NAMESPACE,
    subsystem="interruption",
    registry=REGISTRY,
)

INTERRUPTION_EVICTED_UNREADY = Counter(
    "evicted_without_replacement_total",
    "Pods still on a disrupted node when its grace period expired — "
    "evicted without replacement capacity ready.",
    namespace=NAMESPACE,
    subsystem="interruption",
    registry=REGISTRY,
)

INTERRUPTION_REPLACEMENT_LEAD_TIME = Histogram(
    "replacement_lead_time_seconds",
    "Seconds from disruption notice to the replaced pod's re-bind on "
    "fresh capacity.",
    namespace=NAMESPACE,
    subsystem="interruption",
    buckets=DURATION_BUCKETS,
    registry=REGISTRY,
)

# Resilience layer (karpenter_tpu/resilience): every dependency the
# controllers talk to — cloud control plane, HTTP wire, solver service —
# shares one retry/breaker vocabulary, and its state must be scrapeable.
RESILIENCE_BREAKER_STATE = Gauge(
    "breaker_state",
    "Circuit breaker state per dependency: 0 closed, 1 open, 2 half-open.",
    ["dependency"],
    namespace=NAMESPACE,
    subsystem="resilience",
    registry=REGISTRY,
)

RESILIENCE_RETRIES = Counter(
    "retries_total",
    "Retry decisions, by dependency and outcome: `retried` spent a retry "
    "token and ran again; `budget_exhausted` means the per-dependency retry "
    "budget was dry — the failure propagated instead of amplifying the "
    "storm (docs/overload.md).",
    ["dependency", "outcome"],
    namespace=NAMESPACE,
    subsystem="resilience",
    registry=REGISTRY,
)

RESILIENCE_DEADLINE_EXCEEDED = Counter(
    "deadline_exceeded_total",
    "Operations abandoned because the retry deadline (or the reconcile-round "
    "budget) ran out before the attempts did.",
    ["dependency"],
    namespace=NAMESPACE,
    subsystem="resilience",
    registry=REGISTRY,
)

# Solver degradation: batches that fell back to the host FFD scheduler
# because the accelerated path was broken (breaker open) or failed mid-solve.
# `address` is the pack's PROVENANCE — the pool member (or single sidecar)
# that served the rejected result, "" for the in-process path — so one bad
# member's invalid packs attribute to IT instead of smearing across the
# whole remote path.
SOLVER_DEGRADED = Counter(
    "degraded_solves_total",
    "Solves served by the FFD fallback because the accelerated path was "
    "unavailable or untrusted, by reason "
    "(breaker_open/pack_failure/invalid_pack/integrity_screen/deadline/"
    "overload) and the serving member's address ('' = in-process).",
    ["reason", "address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_WARMUP_FAILURES = Counter(
    "warmup_failures_total",
    "Provisioner-worker solver warmup attempts that failed (the first real "
    "batch pays the compile when the background retry also fails).",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_BATCH_SIZE = Histogram(
    "batch_size_pods",
    "Pods per solver batch.",
    ["backend"],
    namespace=NAMESPACE,
    subsystem="solver",
    buckets=[1, 10, 50, 100, 500, 1000, 2000, 5000, 10000],
    registry=REGISTRY,
)

# Session-based solver transport (v3 wire / docs/solver-transport.md): the
# steady-state Pack must ship only pod deltas — catalog residency has to be
# visible on the scrape, or a silently-thrashing session cache re-pays the
# catalog upload every solve with nothing flagging it.
SOLVER_SESSION_UPLOADS = Counter(
    "session_catalog_uploads_total",
    "Catalog-side tensor uploads to the device side (OpenSession or an "
    "in-process invariants device_put) — steady state approaches zero.",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_SESSION_HIT_RATE = Gauge(
    "session_catalog_hit_rate",
    "Fraction of solves served against already-resident catalog tensors "
    "(no catalog bytes shipped) since process start.",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_SESSION_EVICTIONS = Counter(
    "session_evictions_total",
    "Resident catalog entries evicted (session LRU pressure or TTL expiry).",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

# Encode-cache effectiveness: the signature table / capacity matrix rebuild
# is ~40ms of the 10k-pod budget, so a thrashing EncodeCache is a latency
# regression the p99 alone can't attribute.
SOLVER_ENCODE_CACHE_HITS = Counter(
    "encode_cache_hits_total",
    "Solves that reused a cached (signature table, usable-capacity) entry.",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_ENCODE_CACHE_MISSES = Counter(
    "encode_cache_misses_total",
    "Solves that had to rebuild the signature table / capacity matrix.",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

# Resident delta encoding (docs/delta-encoding.md): the steady-state path
# keeps encoded tensors resident across rounds and patches them from
# per-pod deltas. A spiking full_reencodes rate is the "solves got slow"
# smoking gun (operations.md has the runbook row); epoch mismatches are the
# fail-loud guard firing — each one is a stale-tensor solve that did NOT
# happen.
SOLVER_DELTA_APPLIED = Counter(
    "delta_applied_total",
    "Rounds served by the resident delta path instead of a full re-encode "
    "(path: host = resident host tensors, wire = elided/patched v3 frame, "
    "device = reused/patched device-resident pod upload).",
    ["path"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_DELTA_FULL_REENCODES = Counter(
    "delta_full_reencodes_total",
    "Delta-mode rounds that fell back to a full re-encode, by reason "
    "(cold, epoch, table, topology, wire).",
    ["reason"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_DELTA_EPOCH_MISMATCHES = Counter(
    "delta_epoch_mismatches_total",
    "Delta frames refused because the resident base epoch was missing or "
    "the patched content failed its epoch check (side: client, sidecar). "
    "Every one is a would-have-been stale-tensor solve caught loud.",
    ["side"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_DELTA_RESIDENT_BYTES = Gauge(
    "delta_resident_bytes",
    "Bytes of pod-side tensors held resident for the delta path "
    "(side: host = controller resident batch, sidecar = the wire store, "
    "device = the resident device upload).",
    ["side"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

# Tracing subsystem (karpenter_tpu/obs): span volume and ring-buffer loss
# must be observable — a silently-dropping exporter reads as "nothing slow
# happened", and the flight recorder's write rate IS the slow-solve rate.
TRACE_SPANS = Counter(
    "spans_total",
    "Spans completed and exported by the in-process tracer.",
    namespace=NAMESPACE,
    subsystem="trace",
    registry=REGISTRY,
)

TRACE_DROPPED = Counter(
    "dropped_total",
    "Spans evicted from the in-memory trace ring before anyone read them.",
    namespace=NAMESPACE,
    subsystem="trace",
    registry=REGISTRY,
)

FLIGHT_RECORDS = Counter(
    "flight_records_total",
    "Slow-solve incidents written to the on-disk flight ring (a watched "
    "span exceeded its latency budget).",
    namespace=NAMESPACE,
    registry=REGISTRY,
)

FLIGHT_PANEL_ERRORS = Counter(
    "flight_panel_errors_total",
    "Registered flight-recorder state panels that RAISED while being "
    "snapshotted for a record, by panel name — the record still lands "
    "(span tree + the other panels), the broken panel contributes its "
    "error string.",
    ["panel"],
    namespace=NAMESPACE,
    registry=REGISTRY,
)

# Decision observability plane (obs/decisions.py, docs/decisions.md):
# every provisioning round is recorded into the decision audit ring with
# per-pod elimination attribution for whatever the solve left unplaced.
DECISIONS_RECORDED = Counter(
    "decisions_recorded_total",
    "Provisioning-round decision records appended to the decision audit "
    "log (in-memory ring always; the on-disk replayable ring when "
    "--decision-dir is set).",
    namespace=NAMESPACE,
    registry=REGISTRY,
)

DECISIONS_DROPPED = Counter(
    "decisions_dropped_total",
    "Decision records lost, by reason: \"evicted\" = the capped on-disk "
    "ring pruned an old record, \"write_failed\" = a full/read-only "
    "--decision-dir refused the write (the round itself never fails — "
    "best-effort by contract), \"queue_full\" = the async writer's "
    "bounded queue refused the enqueue, \"error\" = the record builder "
    "broke.",
    ["reason"],
    namespace=NAMESPACE,
    registry=REGISTRY,
)

PODS_UNSCHEDULABLE = Gauge(
    "pods_unschedulable",
    "Pods currently on an unbroken selection/placement failure streak, "
    "by top elimination reason (solver/explain.py vocabulary: "
    "resource_fit, requirement, zone_topology, daemon_overhead, "
    "capacity_frontier, hostname, taint; \"unknown\" = the round could "
    "not attribute, e.g. an FFD-degraded solve).",
    ["reason"],
    namespace=NAMESPACE,
    registry=REGISTRY,
)

DECISION_EXPLAIN_DURATION = Histogram(
    "decision_explain_duration_seconds",
    "Time spent building one round's decision record: elimination "
    "attribution (mask reductions off the hot path) plus the bounded "
    "record assembly — the explain_overhead_pct bench bar (<1%) is "
    "judged on this work.",
    namespace=NAMESPACE,
    buckets=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0],
    registry=REGISTRY,
)

# Fleet telemetry plane (obs/collector.py, docs/telemetry.md): flush /
# stitch / profiler accounting. Every process — controller replicas and
# sidecars — publishes these about its OWN half of the plane.
TELEMETRY_FLUSHES = Counter(
    "flushes_total",
    "Member telemetry payloads (span trees + SLO histogram snapshot + "
    "profile folds) this process published to the shared backend.",
    namespace=NAMESPACE,
    subsystem="telemetry",
    registry=REGISTRY,
)

TELEMETRY_STITCHED = Counter(
    "stitched_traces_total",
    "NEW cross-process trace joins performed by the collector: a foreign "
    "member's span tree (e.g. the sidecar's sidecar.pack) attached into "
    "its parent trace's tree (re-stitching the same flushed tree on a "
    "later poll does not re-count).",
    namespace=NAMESPACE,
    subsystem="telemetry",
    registry=REGISTRY,
)

TELEMETRY_PROFILE_SAMPLES = Counter(
    "profile_samples_total",
    "Thread-stack samples folded by the in-process sampling profiler "
    "(one per thread per tick at --profile-hz).",
    namespace=NAMESPACE,
    subsystem="telemetry",
    registry=REGISTRY,
)

TELEMETRY_PROFILE_OVERHEAD = Gauge(
    "profile_overhead_ratio",
    "Sampling-profiler busy time over wall time since it started — the "
    "self-accounted cost of always-on profiling (bench bar: < 0.01).",
    namespace=NAMESPACE,
    subsystem="telemetry",
    registry=REGISTRY,
)

# Trace ring residency (obs/export.py): /debug/traces serves whatever the
# ring holds, and the drop counter alone cannot say whether the ring is
# near capacity — the gauges make eviction pressure scrapeable per process
# (controller and sidecar each publish their own ring's numbers).
TRACE_RING_TREES = Gauge(
    "ring_trees",
    "Root span trees currently held in the in-memory trace ring.",
    namespace=NAMESPACE,
    subsystem="trace",
    registry=REGISTRY,
)

TRACE_RING_SPANS = Gauge(
    "ring_spans",
    "Total spans (across all held trees) currently in the trace ring.",
    namespace=NAMESPACE,
    subsystem="trace",
    registry=REGISTRY,
)

# Online SLO engine (obs/slo.py, docs/observability.md): declarative
# objectives evaluated from the tracer finish-hook. The gauges are the
# autopilot's sensor surface AND the alerting surface: `burning` is the
# multiwindow page condition (fast AND slow windows over budget).
SLO_OBJECTIVE_OK = Gauge(
    "objective_ok",
    "1 while the objective's fast-window value meets its threshold "
    "(e.g. solve p99 under 100ms); unset until the window has data.",
    ["objective"],
    namespace=NAMESPACE,
    subsystem="slo",
    registry=REGISTRY,
)

SLO_BURN_RATE = Gauge(
    "burn_rate",
    "Error-budget burn rate per objective and window (fast/slow): "
    "observed bad-event fraction divided by the objective's budget — "
    "1.0 means the budget is being consumed exactly as fast as allowed.",
    ["objective", "window"],
    namespace=NAMESPACE,
    subsystem="slo",
    registry=REGISTRY,
)

SLO_BURNING = Gauge(
    "burning",
    "1 while BOTH burn-rate windows of the objective exceed 1.0 — the "
    "multiwindow page condition.",
    ["objective"],
    namespace=NAMESPACE,
    subsystem="slo",
    registry=REGISTRY,
)

SLO_EVENTS = Counter(
    "events_total",
    "SLO-relevant events observed per objective, by verdict (good/bad — "
    "bad events consume error budget).",
    ["objective", "verdict"],
    namespace=NAMESPACE,
    subsystem="slo",
    registry=REGISTRY,
)

# Device-memory telemetry for the session store (solver/service.py): the
# histograms can see that pack_fetch spiked, but only the resource side
# can say WHY — a session churn filling HBM shows up here first.
SOLVER_SESSION_HBM = Gauge(
    "session_hbm_bytes",
    "Bytes of catalog tensors pinned on device per live solver session "
    "(label: the 12-hex-char session key prefix).",
    ["session"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_HBM_HEADROOM = Gauge(
    "device_hbm_headroom_bytes",
    "Device memory limit minus bytes in use, from the backend's "
    "memory_stats. Labeled by device index so the child only exists once "
    "a backend actually reported memory — on the CPU test rig the metric "
    "is ABSENT, never a lying zero.",
    ["device"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

# Breaker-open fast-fails on the metered cloud path: these calls never run,
# so they vanish from the duration histogram — without this counter a
# launch gap during an outage has no latency attribution at all.
CLOUDPROVIDER_BREAKER_SHORTCIRCUIT = Counter(
    "breaker_shortcircuit_total",
    "Cloud-provider calls answered by an open circuit breaker without "
    "reaching the control plane, by provider and method.",
    ["provider", "method"],
    namespace=NAMESPACE,
    subsystem="cloudprovider",
    registry=REGISTRY,
)

# Fleet-scale HA (karpenter_tpu/fleet): per-provisioner shard leases across
# controller replicas, and the failover-aware solver sidecar pool. Shard
# ownership must be visible per replica — a rebalance storm or a stuck
# duplicate-launch guard is invisible in logs at fleet scale.
FLEET_SHARDS_OWNED = Gauge(
    "shards_owned",
    "Provisioner shards this controller replica currently holds the lease "
    "for (the fleet's shard counts should sum to the provisioner count).",
    namespace=NAMESPACE,
    subsystem="fleet",
    registry=REGISTRY,
)

FLEET_REBALANCES = Counter(
    "shard_rebalances_total",
    "Shard takeovers: acquisitions of a shard lease previously held by a "
    "different replica (rebalance-on-death or membership change).",
    namespace=NAMESPACE,
    subsystem="fleet",
    registry=REGISTRY,
)

FLEET_SHARD_LOSSES = Counter(
    "shard_losses_total",
    "Shard leases this replica failed to renew and released its workers "
    "for (at most once per holding epoch).",
    namespace=NAMESPACE,
    subsystem="fleet",
    registry=REGISTRY,
)

FLEET_DUPLICATE_LAUNCH_GUARD = Counter(
    "duplicate_launch_guard_total",
    "Launches or binds skipped by the fleet split-brain guards, by reason "
    "(lost_ownership: shard lease gone mid-round; already_bound: the live "
    "pod was bound by another replica between solve and bind).",
    ["reason"],
    namespace=NAMESPACE,
    subsystem="fleet",
    registry=REGISTRY,
)

FLEET_FOREIGN_NOTICES = Counter(
    "foreign_notices_total",
    "Disruption notices drained by a replica that does not own the node's "
    "shard — requeued to the provider stream for the owner to pick up.",
    namespace=NAMESPACE,
    subsystem="fleet",
    registry=REGISTRY,
)

# Solver sidecar pool: consistent-hash routing on the catalog session key
# with per-member breakers — a failover means a catalog re-upload on the
# next member, so the rate must be scrapeable next to the session metrics.
SOLVER_POOL_FAILOVERS = Counter(
    "pool_failovers_total",
    "Solves rerouted off a dead or breaker-open sidecar pool member, "
    "labeled by the FAILED member's address.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_POOL_MEMBERS = Gauge(
    "pool_members_available",
    "Sidecar pool members currently admitting solves (breaker closed or "
    "probe-ready).",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

# Streaming solver transport (solver/stream.py, docs/solver-transport.md):
# the persistent multiplexed stream per pool member. Establishment state
# and break rate say whether the fleet is actually riding the stream or
# silently living on the unary fallback; credit stalls are the
# flow-control backpressure signal (the streamed twin of
# STATUS_OVERLOADED); the coalescing counters say how often concurrent
# streamed solves shared one device dispatch.
SOLVER_STREAM_STATE = Gauge(
    "stream_established",
    "1 while a persistent solve stream to this sidecar address is "
    "established, 0 while solves fall back to the unary path.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_STREAM_BREAKS = Counter(
    "stream_breaks_total",
    "Established solve streams that broke (sidecar restart, transport "
    "error, or a client-side teardown after a wedged future); in-flight "
    "solves fall back to unary and the stream re-establishes in the "
    "background.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_STREAM_SOLVES = Counter(
    "stream_solves_total",
    "Solve dispatches by transport: stream_shm (zero-copy arena), stream "
    "(inline frames over the stream), or unary (no stream up).",
    ["address", "transport"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_STREAM_CREDIT_STALLS = Counter(
    "stream_credit_stalls_total",
    "Streamed solves refused at the SENDER because the flow-control "
    "credit window was empty — backpressure before any bytes move; the "
    "pool's soft backoff consumes the hint, no breaker ever trips.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_STREAM_FALLBACKS = Counter(
    "stream_fallback_total",
    "Streamed solves that completed over the unary path after a stream "
    "error, by reason (broken/timeout/retry/open/envelope).",
    ["address", "reason"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_STREAM_COALESCED_DISPATCHES = Counter(
    "stream_coalesced_dispatches_total",
    "Device dispatches that carried MORE than one coalesced streamed "
    "solve (same session, same padded shapes, one vmapped kernel call).",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_STREAM_COALESCED_SOLVES = Counter(
    "stream_coalesced_solves_total",
    "Streamed solves that rode a shared (coalesced) device dispatch.",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

# Crash-consistent launch path (karpenter_tpu/launch + the GC controller):
# the journal/adopt/reap loop's three outcomes must be scrapeable — an
# adoption is a crash the system healed, a leak termination is capacity
# nobody accounted for, and the replay rate is the crash rate itself.
LAUNCH_ORPHANS_ADOPTED = Counter(
    "orphans_adopted_total",
    "Orphan instances adopted by the GC controller: a journaled launch "
    "whose process died before the Node object was written.",
    namespace=NAMESPACE,
    subsystem="launch",
    registry=REGISTRY,
)

LAUNCH_INSTANCES_LEAKED = Counter(
    "instances_leaked_total",
    "Leaked instances terminated by the GC sweep: live past the grace "
    "period with no Node tracking them and no journal entry explaining "
    "them (out-of-band or pre-token launches).",
    namespace=NAMESPACE,
    subsystem="launch",
    registry=REGISTRY,
)

LAUNCH_JOURNAL_REPLAYS = Counter(
    "journal_replays_total",
    "Unresolved journal entries replayed by recovery, by outcome "
    "(adopted/node_exists/never_launched).",
    ["outcome"],
    namespace=NAMESPACE,
    subsystem="launch",
    registry=REGISTRY,
)

# Disruption-safe consolidation (docs/consolidation.md): the whole-cluster
# re-pack's safety ledger. Voluntary disruption is the one place this
# controller CHOOSES to hurt availability for cost, so every wave, move,
# budget refusal, and reclaimed node must be attributable on the scrape —
# and evicted_unready_total is the contract itself: it must stay 0, every
# displaced pod replaced before its node drains.
CONSOLIDATION_WAVES = Counter(
    "waves_total",
    "Consolidation waves executed, per provisioner: one journaled "
    "taint→replace→drain pass over the budget-admitted victims.",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="consolidation",
    registry=REGISTRY,
)

CONSOLIDATION_MOVES = Counter(
    "moves_total",
    "Pod moves executed by consolidation waves, per provisioner: each is "
    "one release+replacement injection (the minimal-move objective exists "
    "to keep this small relative to nodes reclaimed).",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="consolidation",
    registry=REGISTRY,
)

CONSOLIDATION_BUDGET_BLOCKED = Counter(
    "budget_blocked_total",
    "Consolidation victims refused by the disruption budget, per "
    "provisioner: the plan wanted the node but the maxUnavailable-style "
    "budget (per wave AND across settling waves) had no room.",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="consolidation",
    registry=REGISTRY,
)

CONSOLIDATION_EVICTED_UNREADY = Counter(
    "evicted_unready_total",
    "Pods a consolidation wave evicted without a replacement ready — the "
    "hard bar of voluntary disruption; any non-zero value is a bug.",
    namespace=NAMESPACE,
    subsystem="consolidation",
    registry=REGISTRY,
)

CONSOLIDATION_RECLAIMED_NODES = Counter(
    "reclaimed_nodes_total",
    "Nodes fully retired by settled consolidation waves, per provisioner.",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="consolidation",
    registry=REGISTRY,
)

CONSOLIDATION_COST_DELTA = Gauge(
    "cost_delta_usd",
    "Cumulative hourly-price delta from executed consolidation waves, per "
    "provisioner (negative = cheaper cluster; the $-readout of the "
    "re-pack).",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="consolidation",
    registry=REGISTRY,
)

# Predictive provisioning (docs/forecasting.md): the arrival forecaster's
# readout and the warm-pool controller's speculation ledger. A speculative
# node is capacity bought on a prediction — every launch, hit, and
# expiry-reclaim must be attributable on the scrape or the warm pool is
# just a slow leak with extra steps.
FORECAST_RATE = Gauge(
    "predicted_rate_pods_per_s",
    "Predicted pod-arrival rate per provisioner shard, by band (point: "
    "the model level; upper: point + band-sigma standard deviations — "
    "what the warm pool speculates against).",
    ["provisioner", "band"],
    namespace=NAMESPACE,
    subsystem="forecast",
    registry=REGISTRY,
)

FORECAST_HORIZON = Gauge(
    "horizon_seconds",
    "The forecast horizon: measured launch-to-ready p99 off node.ready "
    "spans (clamped; the configured default until the first ready "
    "transition lands). Predictions are pod counts expected within one "
    "horizon.",
    namespace=NAMESPACE,
    subsystem="forecast",
    registry=REGISTRY,
)

FORECAST_ARRIVALS = Counter(
    "observed_arrivals_total",
    "Pod admissions observed by the forecaster off provision.round spans, "
    "per provisioner shard — the arrival series the models train on.",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="forecast",
    registry=REGISTRY,
)

WARMPOOL_SPECULATIVE_LAUNCHES = Counter(
    "speculative_launches_total",
    "Speculative (warm-pool) node launches, per provisioner: capacity "
    "created ahead of demand on the forecaster's upper band, journaled "
    "with the speculative marker.",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="warmpool",
    registry=REGISTRY,
)

WARMPOOL_HITS = Counter(
    "hits_total",
    "Warm-pool hits, per provisioner: pods bound onto a standing warm "
    "node by the pre-solve steal, skipping the launch path entirely.",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="warmpool",
    registry=REGISTRY,
)

WARMPOOL_MISSES = Counter(
    "misses_total",
    "Warm-pool misses, per provisioner: pods that reached the solver with "
    "no compatible warm node standing — the counterpart of hits_total for "
    "the hit-rate denominator.",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="warmpool",
    registry=REGISTRY,
)

WARMPOOL_EXPIRED = Counter(
    "expired_total",
    "Speculative launches reclaimed by the GC ladder after --warm-pool-ttl "
    "with no demand landing (the speculation_expired replay outcome).",
    namespace=NAMESPACE,
    subsystem="warmpool",
    registry=REGISTRY,
)

WARMPOOL_SIZE = Gauge(
    "size",
    "Unclaimed warm nodes currently standing, per provisioner.",
    ["provisioner"],
    namespace=NAMESPACE,
    subsystem="warmpool",
    registry=REGISTRY,
)

WARMPOOL_PAUSED = Gauge(
    "paused",
    "1 while warm-pool speculation is paused (brownout rung 1+ — "
    "speculative capacity is the cheapest thing to stop buying under "
    "burn), 0 otherwise.",
    namespace=NAMESPACE,
    subsystem="warmpool",
    registry=REGISTRY,
)

# Overload control (docs/overload.md): past saturation the system decides
# what to drop instead of letting the queues decide. Every shed — batcher
# or sidecar admission — must be attributable on the scrape, and the
# brownout ladder's current rung is the one number an operator checks
# first when latency climbs.
BATCHER_SHED = Counter(
    "shed_total",
    "Pods shed from a full admission batcher, by reason (queue_full: a "
    "full-queue add displaced the oldest lowest-priority entry; brownout: "
    "the ladder's shed rung drained queued low-priority work).",
    ["reason"],
    namespace=NAMESPACE,
    subsystem="batcher",
    registry=REGISTRY,
)

SOLVER_ADMISSION_SHED = Counter(
    "admission_shed_total",
    "Sidecar solve/open requests refused by admission control, by reason "
    "(queue_full: depth + inflight caps hit, answered STATUS_OVERLOADED "
    "with a retry-after hint; deadline: the propagated round budget "
    "expired before device dispatch, answered STATUS_DEADLINE_EXCEEDED; "
    "hbm_pressure: device headroom under the floor, new session uploads "
    "refused while resident-session solves keep flowing).",
    ["reason"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_ADMISSION_DEPTH = Gauge(
    "admission_queue_depth",
    "Solve requests currently queued or executing behind the sidecar "
    "admission gate (bounded by --solver-max-inflight + "
    "--solver-queue-depth).",
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_POOL_OVERLOAD_SKIPS = Counter(
    "pool_overload_skips_total",
    "Solves routed past a pool member sitting out an overload retry-after "
    "window (the soft breaker: overload is backpressure, not failure — "
    "the member's real circuit breaker is untouched).",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

BROWNOUT_LEVEL = Gauge(
    "brownout_level",
    "Current rung of the SLO-driven brownout ladder (0 = normal service; "
    "each rung above sheds progressively more deferrable work — "
    "docs/overload.md has the ladder order and rationale).",
    namespace=NAMESPACE,
    registry=REGISTRY,
)

BROWNOUT_TRANSITIONS = Counter(
    "brownout_transitions_total",
    "Brownout ladder steps taken, by direction (escalate/recover) — every "
    "step also lands as a span and a Warning/Normal event, so each "
    "degradation is auditable.",
    ["direction"],
    namespace=NAMESPACE,
    registry=REGISTRY,
)

# Pack integrity (docs/integrity.md): the corruption-defense subsystem's
# scrape surface. Every counter is labeled by the address the corrupt data
# is ATTRIBUTED to ("" for the in-process device path) — silent data
# corruption is only actionable when it names a specific sidecar/device.
SOLVER_INTEGRITY_CHECKSUM_FAILURES = Counter(
    "integrity_checksum_failures_total",
    "Wire frames rejected by the end-to-end checksum (request rejected "
    "server-side as STATUS_INTEGRITY, response rejected client-side, or "
    "a frame too mangled to parse under negotiated integrity), by the "
    "member address the corruption is attributed to.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_INTEGRITY_SESSION_MISMATCHES = Counter(
    "integrity_session_mismatches_total",
    "Pack responses that echoed a DIFFERENT catalog session key than the "
    "solve was dispatched against (stale-session replay, store rollback, "
    "evict/re-open race) — rejected before decode, recovered via a forced "
    "re-open.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_INTEGRITY_CANARY_SOLVES = Counter(
    "integrity_canary_solves_total",
    "Device/pool packs re-solved on the in-process native packer off the "
    "hot path and compared (the --canary-rate cross-check).",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_INTEGRITY_CANARY_MISMATCHES = Counter(
    "integrity_canary_mismatches_total",
    "Canary cross-checks where the native re-solve DISAGREED with the "
    "served pack — a plausible-shaped but wrong result (silent data "
    "corruption); the serving member is quarantined.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_INTEGRITY_SCREEN_FAILURES = Counter(
    "integrity_screen_failures_total",
    "Accelerated pack results that failed the host-side NaN/bounds screen "
    "(non-finite node requests, assignment outside the node table, "
    "impossible node counts) before decode.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

SOLVER_INTEGRITY_QUARANTINES = Counter(
    "integrity_quarantines_total",
    "Integrity quarantines fired: a member (or the in-process shape class) "
    "breaker forced OPEN by a corruption verdict — checksum failure, "
    "canary mismatch, screen failure, or session mismatch that survived "
    "the re-open.",
    ["address"],
    namespace=NAMESPACE,
    subsystem="solver",
    registry=REGISTRY,
)

# Per-stage solve latency, observed by the provisioning worker after each
# batch (sort / inject / encode / wire_ser / pack_fetch / wire_deser /
# decode) — the <100ms p99 target's attribution on the scrape, not only in
# bench output.
SOLVER_STAGE_DURATION = Histogram(
    "stage_duration_seconds",
    "Per-stage duration of one accelerated solve, by stage "
    "(sort/inject/encode/wire_ser/pack_fetch/wire_deser/decode).",
    ["stage"],
    namespace=NAMESPACE,
    subsystem="solver",
    buckets=DURATION_BUCKETS,
    registry=REGISTRY,
)

# Kube client transport (docs/partition.md): every apiserver request —
# reads, writes, watch re-lists, lease renewals, event writes — crosses the
# kube/transport.py choke point, and these are its scrape surface. The
# duration histogram is per ATTEMPT (client-go's request-duration shape) so
# a retried call shows each round trip; `code` is the HTTP status, or
# "error" for a connection-level failure.
KUBE_REQUEST_DURATION = Histogram(
    "request_duration_seconds",
    "Kubernetes apiserver request latency per attempt, by HTTP verb, "
    "resource kind, and response code (\"error\" = connection failure).",
    ["verb", "kind", "code"],
    namespace=NAMESPACE,
    subsystem="kube",
    buckets=DURATION_BUCKETS,
    registry=REGISTRY,
)

KUBE_REQUEST_RETRIES = Counter(
    "request_retries_total",
    "Kube transport retries, by verb class (read/mutate/watch — creates "
    "and events are never retried at the transport).",
    ["verb_class"],
    namespace=NAMESPACE,
    subsystem="kube",
    registry=REGISTRY,
)

KUBE_THROTTLED = Counter(
    "throttled_total",
    "Kube requests delayed or refused by flow control, by source: "
    "\"server\" = an apiserver 429 (its Retry-After is honored), "
    "\"client\" = the local QPS/burst limiter made the call wait.",
    ["source"],
    namespace=NAMESPACE,
    subsystem="kube",
    registry=REGISTRY,
)

KUBE_EVENTS_DROPPED = Counter(
    "events_dropped_total",
    "Kubernetes Event writes dropped by the zero-retry/short-deadline "
    "events policy — an Event must never hold a reconcile hostage to a "
    "slow apiserver; drops lose audit detail, not correctness.",
    namespace=NAMESPACE,
    subsystem="kube",
    registry=REGISTRY,
)

KUBE_DEGRADED_READS = Counter(
    "degraded_reads_total",
    "Live reads served from the informer cache because the apiserver "
    "breaker is open (degraded read-from-cache mode).",
    namespace=NAMESPACE,
    subsystem="kube",
    registry=REGISTRY,
)

KUBE_RELISTS = Counter(
    "relists_total",
    "Informer full re-LISTs, by kind — each one re-dispatches MODIFIED "
    "for every cached object; a down apiserver paces these with jittered "
    "exponential backoff instead of a hot loop.",
    ["kind"],
    namespace=NAMESPACE,
    subsystem="kube",
    registry=REGISTRY,
)

# Regression sentinel (obs/sentinel.py, docs/observability.md): online
# per-(stage, route, shape) latency baselines learned off the tracer
# finish-hook, a windowed-median change-point detector, and the correlated
# incident plane (obs/incidents.py) sustained deviations escalate into.
SENTINEL_BASELINES = Counter(
    "baselines_total",
    "Sentinel baseline lifecycle events, by event: \"learned\" = a new "
    "(stage, route, shape) key entered the table, \"loaded\" = baselines "
    "restored from --sentinel-dir at startup, \"persisted\" = a successful "
    "baseline-file write, \"persist_failed\" = an unwritable/full "
    "--sentinel-dir degraded the store to memory-only (counted, never "
    "fatal), \"corrupt\" = the baseline file failed to parse and the "
    "sentinel re-learns from scratch.",
    ["event"],
    namespace=NAMESPACE,
    subsystem="sentinel",
    registry=REGISTRY,
)

SENTINEL_DEVIATIONS = Counter(
    "deviations_total",
    "Sustained latency deviations detected by the sentinel's change-point "
    "check (windowed median past the learned level's threshold, held for "
    "the sustain count), by span stage — each one either minted an "
    "incident or attached to the open one.",
    ["stage"],
    namespace=NAMESPACE,
    subsystem="sentinel",
    registry=REGISTRY,
)

SENTINEL_INCIDENTS = Counter(
    "incidents_total",
    "Incident records minted by the sentinel (one per regime change, not "
    "per deviating window — correlated deviations attach instead), by the "
    "first deviating span stage.",
    ["stage"],
    namespace=NAMESPACE,
    subsystem="sentinel",
    registry=REGISTRY,
)

FLEET_FENCED = Gauge(
    "fenced",
    "1 while this replica is FENCED: the apiserver has been unreachable "
    "past its shard leases' expiry margin, so a peer may legitimately own "
    "its shards — cloud creates and GC terminates are refused until the "
    "control plane answers again (docs/partition.md).",
    namespace=NAMESPACE,
    subsystem="fleet",
    registry=REGISTRY,
)
