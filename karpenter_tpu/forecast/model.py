"""Arrival-rate forecasting: the sensor behind predictive provisioning.

Everything upstream of this module *reacts* — the batcher admits pending
pods, the solver packs them, the cloud launches. This module closes
ROADMAP item 5's loop by predicting the NEXT window's demand from the
span stream the system already emits:

- **Feed.** The :class:`ArrivalForecaster` is a tracer finish-hook (the
  ``SloEngine`` discipline: O(1) per span, never raises). Every
  ``provision.round`` span carries the round's admission count in its
  ``batch`` attribute — that count, bucketed into fixed-width intervals,
  is the per-provisioner arrival series. No new instrumentation, no
  second pipeline: the SLO stream IS the sensor.
- **Model.** Per-provisioner-shard :class:`Ewma` over the bucketed rate
  (level + EWMA of squared residuals for the upper band), with a
  :class:`HoltWinters` additive-seasonal option for workloads with a
  diurnal shape — both stdlib arithmetic, fake-clock testable, no
  fitting step (online updates only).
- **Horizon.** A prediction is only actionable over the time it takes a
  launch to become schedulable capacity. The forecaster measures that
  itself: ``node.ready`` spans carry ``since_creation_s`` (the launch
  trace's closing bookend), and the horizon is their p99 off the same
  log-linear sketch the SLO engine uses — so "how far ahead to predict"
  tracks the fleet's OBSERVED launch-to-ready tail, not a config guess.
- **Output.** ``predict(provisioner)`` returns a point and upper-band
  arrival rate plus the pod count expected within one horizon — what the
  warm-pool controller (controllers/warmpool.py) converts into
  speculative launches, and what ``tools/whatif.py`` replays offline
  against recorded decision windows.

Never import this module from jit/vmap/pallas-reachable solver code —
it is host-side span machinery like the rest of ``obs`` (karplint
``span-closed``).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from karpenter_tpu.obs.slo import Histogram
from karpenter_tpu.obs.trace import Span

# Arrival series geometry: one bucket per this many seconds. Small enough
# that a flash crowd registers within a couple of updates, large enough
# that a single batcher window never splits one burst across many buckets.
DEFAULT_BUCKET_S = 10.0

# Upper-band width in standard deviations. 2 sigma over an EWMA variance
# tracks ~p97 of a roughly-normal arrival process — speculation should
# lean high (a warm node that idles is TTL-reclaimed; a cold spike pays
# full launch latency).
DEFAULT_BAND_SIGMA = 2.0

# Horizon clamps: below the floor speculation can't beat the batcher's
# own admission window; above the ceiling a forecast this stale is noise.
MIN_HORIZON_S = 5.0
MAX_HORIZON_S = 900.0
# Horizon before any node.ready observation lands (cold process): one
# typical cloud launch-to-schedulable envelope.
DEFAULT_HORIZON_S = 60.0

MODEL_EWMA = "ewma"
MODEL_HOLT_WINTERS = "holt-winters"


class Ewma:
    """Exponentially weighted level + variance over a series.

    ``alpha`` weights the newest observation; the variance EWMA (same
    alpha) tracks squared residuals against the pre-update level, so the
    band widens exactly when the series starts surprising the model."""

    def __init__(self, alpha: float = 0.3):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.level: Optional[float] = None
        self.variance = 0.0
        self.observations = 0

    def update(self, value: float) -> None:
        v = float(value)
        if self.level is None:
            self.level = v
        else:
            residual = v - self.level
            self.variance = (
                (1.0 - self.alpha) * self.variance
                + self.alpha * residual * residual
            )
            self.level = self.level + self.alpha * residual
        self.observations += 1

    def predict(self, steps_ahead: int = 1) -> float:
        """EWMA is level-only: the forecast is flat at the current level."""
        return self.level or 0.0

    def std(self) -> float:
        return math.sqrt(max(self.variance, 0.0))


class HoltWinters:
    """Additive Holt-Winters: level + trend + seasonal components.

    The seasonal option for arrival series with a repeating shape (the
    diurnal curve the bench generator emits). ``season_len`` is in
    BUCKETS, not seconds; seasonal indices initialize to zero and learn
    online — the first season behaves like plain double-exponential
    smoothing, which is the right cold-start (no fabricated seasonality).
    Variance rides the same residual EWMA as :class:`Ewma` so the upper
    band is model-agnostic."""

    def __init__(
        self,
        alpha: float = 0.3,
        beta: float = 0.05,
        gamma: float = 0.1,
        season_len: int = 24,
    ):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if not 0.0 <= beta <= 1.0:
            raise ValueError(f"beta must be in [0, 1], got {beta}")
        if not 0.0 <= gamma <= 1.0:
            raise ValueError(f"gamma must be in [0, 1], got {gamma}")
        if season_len < 2:
            raise ValueError(f"season_len must be >= 2, got {season_len}")
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.gamma = float(gamma)
        self.season_len = int(season_len)
        self.level: Optional[float] = None
        self.trend = 0.0
        self.seasonal: List[float] = [0.0] * self.season_len
        self.variance = 0.0
        self.observations = 0
        self._phase = 0  # index into the seasonal cycle of the NEXT update

    def update(self, value: float) -> None:
        v = float(value)
        i = self._phase % self.season_len
        if self.level is None:
            self.level = v
        else:
            predicted = self.level + self.trend + self.seasonal[i]
            residual = v - predicted
            self.variance = (
                (1.0 - self.alpha) * self.variance
                + self.alpha * residual * residual
            )
            last_level = self.level
            self.level = (
                self.alpha * (v - self.seasonal[i])
                + (1.0 - self.alpha) * (self.level + self.trend)
            )
            self.trend = (
                self.beta * (self.level - last_level)
                + (1.0 - self.beta) * self.trend
            )
            self.seasonal[i] = (
                self.gamma * (v - self.level)
                + (1.0 - self.gamma) * self.seasonal[i]
            )
        self._phase += 1
        self.observations += 1

    def predict(self, steps_ahead: int = 1) -> float:
        if self.level is None:
            return 0.0
        i = (self._phase + max(steps_ahead, 1) - 1) % self.season_len
        return max(self.level + self.trend * max(steps_ahead, 1) + self.seasonal[i], 0.0)

    def std(self) -> float:
        return math.sqrt(max(self.variance, 0.0))


def build_model(
    model: str = MODEL_EWMA,
    alpha: float = 0.3,
    season_len: int = 24,
):
    """The ``--forecast-model`` grammar: ``ewma`` or ``holt-winters``."""
    if model == MODEL_EWMA:
        return Ewma(alpha=alpha)
    if model == MODEL_HOLT_WINTERS:
        return HoltWinters(alpha=alpha, season_len=season_len)
    raise ValueError(
        f"unknown forecast model {model!r} "
        f"(known: {MODEL_EWMA}, {MODEL_HOLT_WINTERS})"
    )


class ShardForecast:
    """One provisioner shard's arrival stream.

    Admission counts accumulate into the CURRENT fixed-width bucket;
    when the clock crosses a bucket boundary every closed bucket —
    including empty ones a quiet period skipped — feeds the model, so
    silence decays the predicted rate instead of freezing it."""

    # a gap longer than this many buckets resets instead of replaying
    # zeros one by one (an overnight idle must not spin the loop)
    MAX_GAP_BUCKETS = 720

    def __init__(
        self,
        bucket_s: float = DEFAULT_BUCKET_S,
        model: str = MODEL_EWMA,
        alpha: float = 0.3,
        season_len: int = 24,
    ):
        self.bucket_s = float(bucket_s)
        self._model_kwargs = dict(
            model=model, alpha=alpha, season_len=season_len
        )
        self.model = build_model(**self._model_kwargs)
        self._bucket_index: Optional[int] = None
        self._bucket_count = 0.0
        self.total_arrivals = 0

    def _roll(self, now: float) -> None:
        idx = int(now / self.bucket_s)
        if self._bucket_index is None:
            self._bucket_index = idx
            return
        if idx == self._bucket_index:
            return
        gap = idx - self._bucket_index
        self.model.update(self._bucket_count / self.bucket_s)
        if gap > self.MAX_GAP_BUCKETS:
            # long silence: the pre-gap level is noise now, and replaying
            # thousands of zero buckets one by one would spin the loop —
            # cold-start the model instead (predicts zero until new data)
            self.model = build_model(**self._model_kwargs)
        else:
            for _ in range(gap - 1):
                self.model.update(0.0)
        self._bucket_index = idx
        self._bucket_count = 0.0

    def observe(self, count: float, now: float) -> None:
        self._roll(now)
        self._bucket_count += max(float(count), 0.0)
        self.total_arrivals += int(max(count, 0))

    def rate(self, now: float, band_sigma: float = DEFAULT_BAND_SIGMA):
        """``(point, upper)`` pods/second as of ``now`` (rolls buckets
        first, so a silent stretch is priced in)."""
        self._roll(now)
        point = max(float(self.model.predict(1)), 0.0)
        upper = max(point + band_sigma * self.model.std(), point)
        return point, upper


class ArrivalForecaster:
    """The tracer finish-hook: per-provisioner arrival models plus the
    launch-to-ready sketch that sets the prediction horizon.

    Install with ``obs.configure_forecast`` (hook + flight-recorder
    ``forecast`` state panel). The hook contract is the SLO engine's:
    dispatch on span name first, O(1) work under a short lock, never
    raise."""

    WATCHED = ("provision.round", "node.ready")

    def __init__(
        self,
        bucket_s: float = DEFAULT_BUCKET_S,
        model: str = MODEL_EWMA,
        alpha: float = 0.3,
        season_len: int = 24,
        band_sigma: float = DEFAULT_BAND_SIGMA,
        default_horizon_s: float = DEFAULT_HORIZON_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        build_model(model, alpha=alpha, season_len=season_len)  # validate eagerly
        self.bucket_s = float(bucket_s)
        self.model_name = model
        self.alpha = float(alpha)
        self.season_len = int(season_len)
        self.band_sigma = float(band_sigma)
        self.default_horizon_s = float(default_horizon_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._shards: Dict[str, ShardForecast] = {}  # guarded-by: self._lock
        # launch-to-ready sketch: node.ready's since_creation_s in the
        # shared log-linear geometry (obs/slo.py) — mergeable, ~2.5% error
        self._ready = Histogram()  # guarded-by: self._lock
        # pods-per-node EWMA off the same round spans: the unit conversion
        # between a pod-count prediction and a node-count speculation
        self._pods_per_node = Ewma(alpha=0.2)  # guarded-by: self._lock

    # -- intake --------------------------------------------------------------

    def __call__(self, span: Span) -> None:
        """Tracer finish-hook. Must stay fast and never raise (the tracer
        contains hook exceptions, but a slow hook taxes every span)."""
        if span.name == "provision.round":
            self._observe_round(span)
        elif span.name == "node.ready":
            self._observe_ready(span)

    def _observe_round(self, span: Span) -> None:
        provisioner = str(span.attrs.get("provisioner") or "")
        if not provisioner:
            return
        try:
            count = float(span.attrs.get("batch") or 0.0)
        except (TypeError, ValueError):
            return
        now = self._clock()
        with self._lock:
            shard = self._shards.get(provisioner)
            if shard is None:
                shard = self._shards[provisioner] = ShardForecast(
                    bucket_s=self.bucket_s, model=self.model_name,
                    alpha=self.alpha, season_len=self.season_len,
                )
            shard.observe(count, now)
            try:
                nodes = float(span.attrs.get("nodes") or 0.0)
            except (TypeError, ValueError):
                nodes = 0.0
            if nodes > 0 and count > 0:
                self._pods_per_node.update(count / nodes)
        try:
            from karpenter_tpu import metrics

            metrics.FORECAST_ARRIVALS.labels(provisioner=provisioner).inc(
                max(count, 0.0)
            )
        except Exception:
            pass  # trimmed registries

    def _observe_ready(self, span: Span) -> None:
        try:
            seconds = float(span.attrs.get("since_creation_s") or 0.0)
        except (TypeError, ValueError):
            return
        if seconds <= 0:
            return
        with self._lock:
            self._ready.observe(seconds)

    # -- readout -------------------------------------------------------------

    def horizon_s(self) -> float:
        """Measured launch-to-ready p99 clamped to sane bounds; the
        configured default until the first ready transition lands."""
        with self._lock:
            p99 = self._ready.quantile(0.99)
        if p99 is None:
            return self.default_horizon_s
        return min(max(p99, MIN_HORIZON_S), MAX_HORIZON_S)

    def pods_per_node(self) -> float:
        with self._lock:
            ppn = self._pods_per_node.level
        return max(ppn or 1.0, 1.0)

    def predict(self, provisioner: str) -> Dict[str, Any]:
        """Point + upper-band arrival rate and the pod count expected
        within one launch-to-ready horizon. All-zero until the shard has
        seen a round — the warm pool never speculates on no data."""
        now = self._clock()
        horizon = self.horizon_s()
        with self._lock:
            shard = self._shards.get(provisioner)
            if shard is None:
                point = upper = 0.0
                observations = 0
            else:
                # roll FIRST: a closed-but-unrolled first bucket is data,
                # not the no-data case the zero guard below protects
                point, upper = shard.rate(now, band_sigma=self.band_sigma)
                observations = shard.model.observations
                if observations == 0:
                    point = upper = 0.0
        out = {
            "provisioner": provisioner,
            "rate_point_per_s": point,
            "rate_upper_per_s": upper,
            "horizon_s": horizon,
            "predicted_pods": point * horizon,
            "predicted_pods_upper": upper * horizon,
            "observations": observations,
        }
        try:
            from karpenter_tpu import metrics

            metrics.FORECAST_RATE.labels(
                provisioner=provisioner, band="point"
            ).set(point)
            metrics.FORECAST_RATE.labels(
                provisioner=provisioner, band="upper"
            ).set(upper)
            metrics.FORECAST_HORIZON.set(horizon)
        except Exception:
            pass  # trimmed registries
        return out

    def provisioners(self) -> List[str]:
        with self._lock:
            return sorted(self._shards)

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug/forecast`` payload."""
        with self._lock:
            ready_events = self._ready.total()
        return {
            "model": self.model_name,
            "bucket_s": self.bucket_s,
            "band_sigma": self.band_sigma,
            "horizon_s": self.horizon_s(),
            "ready_observations": ready_events,
            "pods_per_node": self.pods_per_node(),
            "shards": {
                name: self.predict(name) for name in self.provisioners()
            },
        }

    def panel(self) -> Dict[str, Any]:
        """Flight-recorder state panel: compact per-shard predictions so a
        slow-solve record shows what the forecaster believed at the time."""
        return {
            "horizon_s": round(self.horizon_s(), 3),
            "shards": {
                name: round(self.predict(name)["rate_upper_per_s"], 4)
                for name in self.provisioners()
            },
        }
