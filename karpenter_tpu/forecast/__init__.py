"""karpenter_tpu.forecast — arrival-rate forecasting (ROADMAP item 5).

The predictive half of provisioning: an online per-provisioner arrival
model fed by the span stream (``forecast/model.py``), consumed by the
speculative warm-pool controller (``controllers/warmpool.py``) and the
offline what-if simulator (``tools/whatif.py``). Install the process
forecaster with ``obs.configure_forecast``; read it back with
``obs.forecaster()``.
"""

from karpenter_tpu.forecast.model import (  # noqa: F401
    DEFAULT_BAND_SIGMA,
    DEFAULT_BUCKET_S,
    DEFAULT_HORIZON_S,
    MAX_HORIZON_S,
    MIN_HORIZON_S,
    MODEL_EWMA,
    MODEL_HOLT_WINTERS,
    ArrivalForecaster,
    Ewma,
    HoltWinters,
    ShardForecast,
    build_model,
)
