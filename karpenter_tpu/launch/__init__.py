"""Crash-consistent launch path (docs/launch-journal.md).

A launch is three writes against three stores — the cloud create, the
Node object, the pod binds — and a process can die between any two of
them. The :mod:`journal` records intent *before* the cloud call and is
resolved only after the bind, so an interrupted launch always leaves a
breadcrumb: recovery re-describes the journal entry's launch token
against ``CloudProvider.list_instances()`` and either **adopts** the
instance (writes the Node object it never got) or confirms it never
launched (drops the entry). The sweep lives in
``controllers/garbage_collection.py``.
"""

from karpenter_tpu.launch.journal import (
    STATE_CREATED,
    STATE_INTENT,
    FileLaunchJournal,
    KubeLaunchJournal,
    LaunchJournal,
    LaunchRecord,
    MemoryLaunchJournal,
    build_journal,
)

__all__ = [
    "STATE_CREATED",
    "STATE_INTENT",
    "FileLaunchJournal",
    "KubeLaunchJournal",
    "LaunchJournal",
    "LaunchRecord",
    "MemoryLaunchJournal",
    "build_journal",
]
