"""Replay of unresolved launch-journal entries — the adopt/confirm ladder.

An unresolved entry is a launch whose process may have died mid-flight.
Replay re-describes the entry's launch token against the provider's live
inventory and lands on exactly one of four outcomes:

- ``ADOPTED``        — the instance exists and no Node object tracks it:
  the crash hit between the cloud create and the Node write. Recovery
  writes the Node the dead process never got to (template from the
  entry's provisioner, capacity from the live instance's type), rejoining
  the original launch trace via the entry's stored traceparent, and
  resolves the entry.
- ``NODE_EXISTS``    — the instance exists and a Node already tracks it:
  the crash hit between the Node write and the bind. The capacity is
  tracked; any unbound pods re-enter selection on their own. Resolve.
- ``NEVER_LAUNCHED`` — no live instance carries the token: the create
  never committed (or the instance already died). Nothing leaked. Resolve.
- ``PENDING``        — the entry is younger than the replay grace: the
  launching process may still be alive and mid-create, so recovery must
  not race it. Leave the entry for the next sweep.

The grace window is what separates a *crashed* launch from a *slow* one:
journal entries carry their write time, and replay only touches entries
older than ``replay_after`` seconds. The garbage-collection controller
(controllers/garbage_collection.py) drives this on its sweep cadence.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, NodeSpec, NodeStatus, ObjectMeta
from karpenter_tpu.cloudprovider.types import LiveInstance
from karpenter_tpu.launch.journal import LaunchJournal, LaunchRecord

logger = logging.getLogger("karpenter.launch")

# Replay outcomes (returned so the controller can count and log them).
ADOPTED = "adopted"
NODE_EXISTS = "node_exists"
NEVER_LAUNCHED = "never_launched"
PENDING = "pending"
# a speculative (warm-pool) entry aged past --warm-pool-ttl with no
# demand claiming its node: the instance is reclaimed even though it is
# live — the one case where "journaled and running" is NOT protection
SPECULATION_EXPIRED = "speculation_expired"
# a consolidation-wave entry whose owning replica crashed mid-wave: the
# surviving cordoned victims are un-cordoned (schedulable again) and the
# entry resolved — the half-executed wave is rolled forward to a safe
# state, and the next consolidation pass re-plans from scratch
CONSOLIDATION_REPLAYED = "consolidation_replayed"

# LaunchRecord.marker value for journaled consolidation waves
# (controllers/consolidation.py writes it, replay matches on it).
CONSOLIDATION_MARKER = "consolidation"

# Default --warm-pool-ttl: how long an unclaimed speculative launch may
# stand before the GC ladder reclaims it (controllers/warmpool.py).
DEFAULT_WARM_POOL_TTL = 600.0

# How old an unresolved entry must be before replay touches it: younger
# entries may belong to a live process still between its journal write and
# its bind. The bound must exceed the WORST-case intent-to-commit window,
# not the typical one: a create can sit the simulated fleet limiter's full
# 60s take() timeout AND the metered retry policy's 20s deadline before
# the instance exists — resolving such an entry NEVER_LAUNCHED while the
# create is still in flight destroys the breadcrumb, so a post-commit
# crash would then LEAK (grace-period termination, capacity double-paid)
# instead of adopting. 60 + 20 + slack:
DEFAULT_REPLAY_AFTER = 120.0


def node_for_instance(
    cluster,
    cloud_provider,
    live: LiveInstance,
    provisioner_name: str = "",
    trace: str = "",
    speculative: bool = False,
) -> Node:
    """Fabricate the Node object a crashed launch never wrote.

    Mirrors what ``ProvisionerWorker._launch_one`` builds: the cloud
    half (name/provider-id/capacity/zone labels) comes from the live
    instance + its catalog type; the template half (labels, taints incl.
    not-ready, the termination finalizer) from the provisioner's
    constraints — the finalizer matters most, it is what routes the
    adopted node's eventual deletion through the terminator so the
    INSTANCE dies with the Node."""
    provisioner = (
        cluster.try_get("provisioners", provisioner_name, namespace="")
        if provisioner_name else None
    )
    itype = None
    if live.instance_type:
        try:
            provider_cfg = (
                provisioner.spec.constraints.provider
                if provisioner is not None else None
            )
            for it in cloud_provider.get_instance_types(provider_cfg):
                if it.name == live.instance_type:
                    itype = it
                    break
        except Exception:
            logger.debug("catalog lookup failed during adoption", exc_info=True)

    labels: Dict[str, str] = {}
    taints = []
    finalizers = [lbl.TERMINATION_FINALIZER]
    if provisioner is not None:
        template = provisioner.spec.constraints.to_node()
        labels.update(template.metadata.labels)
        taints = list(template.spec.taints)
        finalizers = list(
            set(template.metadata.finalizers) | {lbl.TERMINATION_FINALIZER}
        )
        labels[lbl.PROVISIONER_NAME_LABEL] = provisioner_name
    if itype is not None:
        labels[lbl.ARCH] = itype.architecture
        labels[lbl.OS] = lbl.OS_LINUX
    if live.instance_type:
        labels[lbl.INSTANCE_TYPE] = live.instance_type
    if live.zone:
        labels[lbl.TOPOLOGY_ZONE] = live.zone
    if live.capacity_type:
        labels[lbl.CAPACITY_TYPE] = live.capacity_type
    labels.update(live.labels)

    annotations = {"karpenter.sh/adopted": "true"}
    if speculative:
        # an adopted speculative orphan re-enters the warm pool: claimable
        # by the worker's warm-hit steal, reclaimable past the TTL
        annotations[lbl.WARM_POOL_ANNOTATION] = "true"
    if live.launch_token:
        annotations[lbl.LAUNCH_TOKEN_ANNOTATION] = live.launch_token
    if trace:
        from karpenter_tpu import obs

        annotations[obs.TRACE_ANNOTATION] = trace

    resources = dict(itype.resources) if itype is not None else {}
    return Node(
        metadata=ObjectMeta(
            name=live.id,
            namespace="",
            labels=labels,
            annotations=annotations,
            finalizers=finalizers,
        ),
        spec=NodeSpec(provider_id=live.provider_id, taints=taints),
        status=NodeStatus(capacity=dict(resources), allocatable=resources),
    )


class NodeIndex:
    """One sweep's snapshot of the cluster's Nodes, keyed three ways for
    the instance↔Node pairing: node name (the providers name Nodes after
    the instance id), provider-id (the authoritative pairing), and
    launch-token annotation (covers renamed/self-registered nodes). Built
    ONCE per GC sweep — the naive per-instance ``cluster.nodes()`` scan
    made each sweep O(instances × nodes) in full list copies under the
    cluster lock."""

    def __init__(self, cluster):
        self.by_name: Dict[str, Node] = {}
        self.by_provider_id: Dict[str, Node] = {}
        self.by_token: Dict[str, Node] = {}
        for node in cluster.nodes():
            self.by_name[node.metadata.name] = node
            if node.spec.provider_id:
                self.by_provider_id[node.spec.provider_id] = node
            token = node.metadata.annotations.get(lbl.LAUNCH_TOKEN_ANNOTATION)
            if token:
                self.by_token[token] = node

    def find(self, live: LiveInstance) -> Optional[Node]:
        node = self.by_name.get(live.id)
        if node is not None:
            return node
        if live.provider_id:
            node = self.by_provider_id.get(live.provider_id)
            if node is not None:
                return node
        if live.launch_token:
            return self.by_token.get(live.launch_token)
        return None


def node_tracking(cluster, live: LiveInstance, index: Optional[NodeIndex] = None) -> Optional[Node]:
    """The Node object already tracking ``live``, or None — matched through
    ``index`` when the caller (the GC sweep) already built one, else
    through a fresh snapshot."""
    if index is not None:
        return index.find(live)
    return NodeIndex(cluster).find(live)


def replay_entry(
    journal: LaunchJournal,
    cluster,
    cloud_provider,
    entry: LaunchRecord,
    instances_by_token: Dict[str, LiveInstance],
    now: float,
    replay_after: float = DEFAULT_REPLAY_AFTER,
    index: Optional[NodeIndex] = None,
    warm_pool_ttl: float = DEFAULT_WARM_POOL_TTL,
    reap=None,
) -> str:
    """Run the adopt/confirm ladder for ONE unresolved entry; returns the
    outcome constant. Safe against the live launch path: a racing resolve
    (the launching process finished after all) is a benign no-op, and the
    grace window keeps replay off entries young enough to have one.

    Speculative (warm-pool) entries get the extra rungs: a STANDING warm
    node keeps its entry open (the entry is the TTL breadcrumb, not an
    orphan), a CLAIMED one resolves, and one past ``warm_pool_ttl`` is
    reclaimed through ``reap`` even though the instance is live — without
    this rung an untracked-but-journaled instance is protected forever.
    ``reap`` terminates one live instance (the GC controller passes its
    terminator-backed reaper); None falls back to the provider delete."""
    if now - entry.created_at < replay_after:
        return PENDING
    if entry.marker == CONSOLIDATION_MARKER:
        # BEFORE the live-instance lookup: a wave entry carries no launch
        # token of its own (replacement launches journal separately), so
        # the ladder below would wrongly read it as NEVER_LAUNCHED
        return _replay_consolidation(journal, cluster, entry)
    live = instances_by_token.get(entry.token)
    if live is None:
        # the create never committed (or the instance already terminated):
        # confirmed never launched — nothing to adopt, nothing leaked
        journal.resolve(entry.token)
        return NEVER_LAUNCHED
    tracked = node_tracking(cluster, live, index=index)
    if entry.speculative:
        return _replay_speculative(
            journal, cluster, cloud_provider, entry, live, tracked,
            now, warm_pool_ttl, reap,
        )
    if tracked is not None:
        # crash landed between Node write and bind: the Node tracks the
        # instance, unbound pods re-enter selection on their own
        journal.resolve(entry.token)
        return NODE_EXISTS
    node = node_for_instance(
        cluster, cloud_provider, live,
        provisioner_name=entry.provisioner, trace=entry.trace,
    )
    from karpenter_tpu.kube.client import Conflict

    try:
        cluster.create("nodes", node)
    except Conflict:
        pass  # a racer (another replica's sweep, or self-registration) won
    journal.resolve(entry.token)
    logger.warning(
        "adopted orphan instance %s (token %s, provisioner %s) — "
        "its launching process died before the Node write",
        live.id, entry.token[:12], entry.provisioner,
    )
    return ADOPTED


def _replay_consolidation(journal, cluster, entry: LaunchRecord) -> str:
    """Roll a crashed consolidation wave forward to safety. The entry was
    written BEFORE the first victim was touched, so the victims list is
    the complete blast radius; any subset may be cordoned, drained, or
    already deleted. Surviving victims are un-cordoned — the consolidation
    taint removed and scheduling re-enabled — because a dead wave's
    cordons are pure capacity loss (its replacements journaled and
    recovered separately through the ordinary ladder; displaced pods are
    pending and re-enter selection on their own). Deleted victims need
    nothing: their drains finished. Then the entry resolves — the next
    consolidation pass re-plans from the real, recovered world."""
    uncordoned = 0
    for name in entry.victims:
        node = cluster.try_get("nodes", name, namespace="")
        if node is None or node.metadata.deletion_timestamp is not None:
            continue
        from karpenter_tpu.kube.serde import taint_to_wire

        taints_wire = [
            taint_to_wire(t) for t in node.spec.taints
            if not (
                t.key == lbl.INTERRUPTION_TAINT_KEY
                and t.value == CONSOLIDATION_MARKER
            )
        ]
        try:
            cluster.merge_patch(
                "nodes", name,
                {"spec": {"unschedulable": False, "taints": taints_wire}},
                namespace="",
            )
            uncordoned += 1
        except Exception:
            logger.warning(
                "un-cordon of crashed-wave victim %s failed; next sweep "
                "retries", name, exc_info=True,
            )
            return PENDING
    journal.resolve(entry.token)
    logger.warning(
        "replayed crashed consolidation wave %s (provisioner %s, decision "
        "%s): %d of %d victim(s) un-cordoned, entry resolved",
        entry.token[:20], entry.provisioner, entry.decision_id or "-",
        uncordoned, len(entry.victims),
    )
    return CONSOLIDATION_REPLAYED


def _replay_speculative(
    journal: LaunchJournal,
    cluster,
    cloud_provider,
    entry: LaunchRecord,
    live: LiveInstance,
    tracked: Optional[Node],
    now: float,
    warm_pool_ttl: float,
    reap,
) -> str:
    """The warm-pool rungs of the ladder (one live instance, speculative
    entry). Claimed → resolve; standing within TTL → leave open; past
    TTL → reclaim instance AND entry, zero leaks, zero double-launches
    (the instance dies under its own token, so a token replay can never
    resurrect it)."""
    expired = (now - entry.created_at) >= warm_pool_ttl
    if tracked is not None:
        claimed = (
            lbl.WARM_POOL_ANNOTATION not in tracked.metadata.annotations
        )
        if claimed:
            # demand landed: the worker's warm-hit steal removed the
            # marker (its resolve may have raced this sweep — benign)
            journal.resolve(entry.token)
            return NODE_EXISTS
        if not expired:
            # standing warm capacity awaiting demand: the open entry IS
            # the TTL breadcrumb — resolving it would protect the
            # instance forever (the bug this rung exists to fix)
            return PENDING
        _reap_speculative(cluster, cloud_provider, live, tracked, reap)
        journal.resolve(entry.token)
        logger.warning(
            "reclaimed expired speculative node %s (token %s, provisioner "
            "%s): no demand landed within the warm-pool TTL (%.0fs)",
            tracked.metadata.name, entry.token[:12], entry.provisioner,
            warm_pool_ttl,
        )
        return SPECULATION_EXPIRED
    if expired:
        # untracked AND stale: the crash ate the Node write and the TTL
        # already passed — reclaim straight from the cloud
        _reap_speculative(cluster, cloud_provider, live, None, reap)
        journal.resolve(entry.token)
        logger.warning(
            "reclaimed expired speculative instance %s (token %s, "
            "provisioner %s): untracked past the warm-pool TTL (%.0fs)",
            live.id, entry.token[:12], entry.provisioner, warm_pool_ttl,
        )
        return SPECULATION_EXPIRED
    # untracked, within TTL: adopt back INTO the warm pool (Node carries
    # the warm marker, entry stays open so the TTL still applies)
    node = node_for_instance(
        cluster, cloud_provider, live,
        provisioner_name=entry.provisioner, trace=entry.trace,
        speculative=True,
    )
    from karpenter_tpu.kube.client import Conflict

    try:
        cluster.create("nodes", node)
    except Conflict:
        pass  # a racer won the write
    logger.warning(
        "adopted speculative orphan %s (token %s, provisioner %s) back "
        "into the warm pool — its launching process died before the Node "
        "write",
        live.id, entry.token[:12], entry.provisioner,
    )
    return ADOPTED


def _reap_speculative(
    cluster, cloud_provider, live: LiveInstance, tracked: Optional[Node],
    reap,
) -> None:
    """Terminate one expired speculative launch: instance first (under
    its own token, so the fleet ledger forgets it), then the Node object
    (unclaimed warm nodes carry no pods, so no drain is owed)."""
    if reap is not None:
        reap(live)
    else:
        node = tracked or node_for_instance(cluster, cloud_provider, live)
        node.metadata.finalizers = []
        cloud_provider.delete(node)
    if tracked is not None:
        try:
            if tracked.metadata.finalizers:
                tracked.metadata.finalizers = []
                cluster.update("nodes", tracked)
            cluster.delete("nodes", tracked.metadata.name, namespace="")
        except Exception:
            logger.debug(
                "warm node object delete raced for %s",
                tracked.metadata.name, exc_info=True,
            )
