"""Write-ahead launch journal: the breadcrumb a crashed launch leaves.

The provisioning worker writes ``record_intent`` (token, provisioner,
trace) BEFORE the cloud create, advances the entry to ``created`` after
the Node object is written, and ``resolve``s it only after the pods are
bound. Any entry still present is a launch that may have died mid-flight;
recovery (controllers/garbage_collection.py) re-describes its token
against ``CloudProvider.list_instances()``:

- instance found, no Node      → ADOPT (write the Node, rejoin the trace)
- instance found, Node exists  → the crash landed between Node write and
  bind; the Node already tracks the instance — resolve the entry (the
  unbound pods re-enter selection on their own)
- no instance with that token  → the create never committed — resolve
  (confirmed never launched)

Two durable backends share the contract: a flock'd shared file (the
``FileLeaseSet`` discipline — single host, multi-process) and a
kube-object twin (one coordination Lease per open entry, so recovery
works across hosts against a real apiserver). ``MemoryLaunchJournal``
serves tests.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional

from karpenter_tpu.utils.lease import FileLease

logger = logging.getLogger("karpenter.launch")

STATE_INTENT = "intent"    # recorded; the cloud create may or may not have run
STATE_CREATED = "created"  # Node object written; binds still pending


@dataclass
class LaunchRecord:
    """One open launch. ``token`` is the client launch token the create
    stamps on the instance; ``trace`` is the launch span's traceparent so
    an adoption rejoins the original provisioning trace."""

    token: str
    provisioner: str
    state: str = STATE_INTENT
    node_name: str = ""
    trace: str = ""
    created_at: float = 0.0
    # warm-pool marker (controllers/warmpool.py): this launch was created
    # ahead of demand. A speculative entry stays OPEN after the Node write
    # — it resolves when a warm-hit claims the node, and the GC ladder
    # reclaims it past --warm-pool-ttl if demand never lands. Defaults
    # keep old journal docs (no key) parsing as ordinary launches.
    speculative: bool = False
    # wave marker (controllers/consolidation.py): a "consolidation" entry
    # is not a launch at all but a whole disruption wave journaled BEFORE
    # the first victim is touched — ``victims`` names the nodes the wave
    # cordons, ``decision_id`` ties it to the audit record that proposed
    # it. A crash mid-wave leaves the entry open; recovery replays it by
    # un-cordoning surviving victims (launch/recovery.py) instead of the
    # adopt/reap ladder. Defaults keep old docs parsing unchanged.
    marker: str = ""
    victims: List[str] = field(default_factory=list)
    decision_id: str = ""

    def to_doc(self) -> Dict:
        return asdict(self)

    @staticmethod
    def from_doc(doc: Dict) -> "LaunchRecord":
        return LaunchRecord(
            token=str(doc.get("token", "")),
            provisioner=str(doc.get("provisioner", "")),
            state=str(doc.get("state", STATE_INTENT)),
            node_name=str(doc.get("node_name", "")),
            trace=str(doc.get("trace", "")),
            created_at=float(doc.get("created_at", 0.0)),
            speculative=bool(doc.get("speculative", False)),
            marker=str(doc.get("marker", "")),
            victims=[str(v) for v in doc.get("victims", []) or []],
            decision_id=str(doc.get("decision_id", "")),
        )


class LaunchJournal:
    """The contract all backends implement. Methods are best-effort safe to
    call with unknown tokens (a resolve of an already-resolved entry is a
    no-op) — recovery and the live launch path may race benignly."""

    def record_intent(
        self, token: str, provisioner: str, trace: str = "",
        speculative: bool = False, marker: str = "",
        victims: Optional[List[str]] = None, decision_id: str = "",
    ) -> None:
        raise NotImplementedError

    def mark_created(self, token: str, node_name: str) -> None:
        raise NotImplementedError

    def resolve(self, token: str) -> None:
        raise NotImplementedError

    def get(self, token: str) -> Optional[LaunchRecord]:
        raise NotImplementedError

    def unresolved(self) -> List[LaunchRecord]:
        raise NotImplementedError


class MemoryLaunchJournal(LaunchJournal):
    """In-process backend: exercises the contract without I/O (a crashed
    process loses it, so production deployments configure file or kube)."""

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self.clock = clock or time.time
        self._mu = threading.Lock()
        self._entries: Dict[str, LaunchRecord] = {}  # guarded-by: self._mu

    def record_intent(
        self, token: str, provisioner: str, trace: str = "",
        speculative: bool = False, marker: str = "",
        victims: Optional[List[str]] = None, decision_id: str = "",
    ) -> None:
        with self._mu:
            self._entries[token] = LaunchRecord(
                token=token, provisioner=provisioner, trace=trace,
                created_at=self.clock(), speculative=speculative,
                marker=marker, victims=list(victims or []),
                decision_id=decision_id,
            )

    def mark_created(self, token: str, node_name: str) -> None:
        with self._mu:
            entry = self._entries.get(token)
            if entry is not None:
                entry.state = STATE_CREATED
                entry.node_name = node_name

    def resolve(self, token: str) -> None:
        with self._mu:
            self._entries.pop(token, None)

    def get(self, token: str) -> Optional[LaunchRecord]:
        with self._mu:
            return self._entries.get(token)

    def unresolved(self) -> List[LaunchRecord]:
        with self._mu:
            return list(self._entries.values())


class FileLaunchJournal(LaunchJournal):
    """Shared-file backend: one JSON record ``{"entries": {token: doc}}``
    under the same flock-serialized RMW discipline as ``FileLeaseSet`` —
    the write-to-temp + rename is atomic, and the flock keeps two
    replicas' read-modify-writes from interleaving. Entries survive the
    writing process's death by construction; that persistence IS the
    journal's reason to exist."""

    def __init__(
        self,
        path: str,
        clock: Optional[Callable[[], float]] = None,
        identity: Optional[str] = None,
    ):
        self.path = path
        self.clock = clock or time.time
        # tmp-file suffix namespace (same crash-sweep story as FileLease)
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        # sweep horizon for crashed writers' temp files
        self.duration = 15.0

    _locked = FileLease._locked
    _sweep_stale_tmp = FileLease._sweep_stale_tmp

    def _read(self) -> Dict:
        try:
            with open(self.path) as f:
                record = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            record = {}
        record.setdefault("entries", {})
        return record

    def _write(self, record: Dict) -> None:
        tmp = f"{self.path}.{self.identity}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, self.path)

    def record_intent(
        self, token: str, provisioner: str, trace: str = "",
        speculative: bool = False, marker: str = "",
        victims: Optional[List[str]] = None, decision_id: str = "",
    ) -> None:
        entry = LaunchRecord(
            token=token, provisioner=provisioner, trace=trace,
            created_at=self.clock(), speculative=speculative,
            marker=marker, victims=list(victims or []),
            decision_id=decision_id,
        )
        with self._locked():
            self._sweep_stale_tmp()
            record = self._read()
            record["entries"][token] = entry.to_doc()
            self._write(record)

    def mark_created(self, token: str, node_name: str) -> None:
        with self._locked():
            record = self._read()
            doc = record["entries"].get(token)
            if doc is None:
                return
            doc["state"] = STATE_CREATED
            doc["node_name"] = node_name
            self._write(record)

    def resolve(self, token: str) -> None:
        with self._locked():
            record = self._read()
            if record["entries"].pop(token, None) is not None:
                self._write(record)

    def get(self, token: str) -> Optional[LaunchRecord]:
        with self._locked():
            record = self._read()
        doc = record["entries"].get(token)
        return LaunchRecord.from_doc(doc) if doc is not None else None

    def unresolved(self) -> List[LaunchRecord]:
        with self._locked():
            record = self._read()
        return [LaunchRecord.from_doc(d) for d in record["entries"].values()]


class KubeLaunchJournal(LaunchJournal):
    """Kube-object twin: one coordination Lease per open entry
    (``<prefix>-<token>``), the record JSON-encoded in ``holderIdentity``
    (a free-form string on the wire). Apiserver writes are durable across
    host loss, so any replica's GC sweep can replay a dead peer's
    entries. Resolution DELETES the Lease — like the shard-member leases,
    the token is baked into the object name, so a kept-but-blanked object
    would be permanent garbage."""

    def __init__(
        self,
        cluster,
        prefix: str = "karpenter-launch",
        namespace: str = "kube-system",
        clock: Optional[Callable[[], float]] = None,
    ):
        self.cluster = cluster
        self.prefix = prefix
        self.namespace = namespace
        self.clock = clock or cluster.clock

    def _name_for(self, token: str) -> str:
        return f"{self.prefix}-{token[:48].lower()}"

    def _put(self, entry: LaunchRecord) -> None:
        from karpenter_tpu.api.objects import Lease, ObjectMeta
        from karpenter_tpu.kube.client import Conflict, NotFound

        name = self._name_for(entry.token)
        payload = json.dumps(entry.to_doc())
        existing = self.cluster.try_get("leases", name, namespace=self.namespace)
        if existing is None:
            lease = Lease(
                metadata=ObjectMeta(name=name, namespace=self.namespace),
                holder_identity=payload,
                # journal entries do not expire on their own — the GC
                # ladder (adopt / confirm-never-launched) retires them;
                # the duration only signals "not a coordination lease"
                lease_duration_seconds=1,
                acquire_time=self.clock(),
                renew_time=self.clock(),
            )
            try:
                self.cluster.create("leases", lease)
            except Conflict:
                # a racer (the same token's retried write) landed first;
                # fall through to the update path below
                existing = self.cluster.try_get(
                    "leases", name, namespace=self.namespace
                )
        if existing is not None:
            existing.holder_identity = payload
            existing.renew_time = self.clock()
            try:
                self.cluster.update("leases", existing)
            except (Conflict, NotFound):
                logger.debug("journal lease update raced for %s", name)

    def record_intent(
        self, token: str, provisioner: str, trace: str = "",
        speculative: bool = False, marker: str = "",
        victims: Optional[List[str]] = None, decision_id: str = "",
    ) -> None:
        self._put(LaunchRecord(
            token=token, provisioner=provisioner, trace=trace,
            created_at=self.clock(), speculative=speculative,
            marker=marker, victims=list(victims or []),
            decision_id=decision_id,
        ))

    def mark_created(self, token: str, node_name: str) -> None:
        entry = self.get(token)
        if entry is None:
            return
        entry.state = STATE_CREATED
        entry.node_name = node_name
        self._put(entry)

    def resolve(self, token: str) -> None:
        from karpenter_tpu.kube.client import NotFound

        try:
            self.cluster.delete(
                "leases", self._name_for(token), namespace=self.namespace
            )
        except NotFound:
            pass

    def _decode(self, lease) -> Optional[LaunchRecord]:
        try:
            return LaunchRecord.from_doc(json.loads(lease.holder_identity))
        except (json.JSONDecodeError, TypeError, ValueError):
            return None

    def get(self, token: str) -> Optional[LaunchRecord]:
        lease = self.cluster.try_get(
            "leases", self._name_for(token), namespace=self.namespace
        )
        if lease is None:
            return None
        return self._decode(lease)

    def unresolved(self) -> List[LaunchRecord]:
        # journal leases are deliberately not informer-watched (same story
        # as the shard leases): list LIVE when the backend can, so this
        # replica sees entries a dead PEER wrote
        lister = getattr(self.cluster, "list_live", None)
        if lister is not None:
            leases = lister("leases", namespace=self.namespace)
        else:
            leases = self.cluster.list("leases", namespace=self.namespace)
        out: List[LaunchRecord] = []
        prefix = f"{self.prefix}-"
        for lease in leases:
            if not lease.metadata.name.startswith(prefix):
                continue
            entry = self._decode(lease)
            if entry is not None:
                out.append(entry)
        return out


def build_journal(spec: str, cluster=None, clock=None) -> Optional[LaunchJournal]:
    """``""`` → no journal; ``kube:<namespace>/<prefix>`` →
    :class:`KubeLaunchJournal`; ``memory:`` → in-process; anything else is
    a shared file path — the same spec grammar as ``build_lease_set``."""
    if not spec:
        return None
    if spec == "memory:":
        return MemoryLaunchJournal(clock=clock)
    if spec.startswith("kube:"):
        ns_prefix = spec[len("kube:"):]
        if "/" in ns_prefix:
            namespace, _, prefix = ns_prefix.partition("/")
        else:
            namespace, prefix = "kube-system", ns_prefix
        return KubeLaunchJournal(
            cluster,
            prefix=prefix or "karpenter-launch",
            namespace=namespace or "kube-system",
            clock=clock,
        )
    return FileLaunchJournal(spec, clock=clock)
