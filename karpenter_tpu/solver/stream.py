"""Streaming solver transport (docs/solver-transport.md § Streaming).

BENCH_r05 measured ``transport_rtt_floor_ms ≈ 106`` against an 80 ms
device leg — more than half the per-solve budget was unary-RPC transport,
not solve time. This module replaces the per-solve unary RPC with ONE
persistent bidirectional gRPC stream per sidecar (per ``SolverPool``
member): solves are multiplexed over it with a per-message correlation
id, responses complete **out of order** into the existing
``pack_begin``/``wait()`` futures, and stream breakage falls back
transparently to the unary path while a background thread re-establishes
the stream.

Three layers live here:

- **Envelope codec** — each stream message wraps an UNCHANGED unary v3
  frame (``service.pack_arrays`` bytes) in a 20-byte envelope::

      magic "KSTM" | u16 version=1 | u16 msg type | u64 correlation id
                   | u32 crc32(version, msg_type, corr_id) | payload

  Because the payload IS the unary frame, the full v3 capability set
  (PROTO_TRACE_TRAILER / PROTO_DEADLINE / PROTO_CHECKSUM) rides the
  stream byte-for-byte unchanged. The envelope CRC covers the words the
  inner frame's checksum cannot: a flipped correlation id would complete
  the WRONG client future with another solve's (checksum-valid!) result —
  the one silent-corruption hole multiplexing opens — so a header flip is
  a detected drop, never a misroute (tests/test_serde_fuzz.py extends
  the byte-flip corpus over enveloped messages).

- **Flow-control credits** — the server's first message grants the client
  a credit window (the sidecar's ``max_inflight + queue_depth`` bound —
  the same bound the PR-9 ``AdmissionGate`` enforces by refusal on the
  unary path) plus a retry-after hint. Each solve spends a credit; each
  result returns one. Exhaustion raises a typed
  :class:`~karpenter_tpu.resilience.overload.OverloadedError` with
  ``kind="credits"`` AT THE SENDER — backpressure before any bytes move,
  which ``SolverPool`` consumes through the same soft-backoff path as a
  ``STATUS_OVERLOADED`` refusal. No real breaker ever trips on it.

- **Zero-copy colocated fast path** — when controller and sidecar share a
  host (``--solver-shm-dir`` on both), the client moves the 7 pod-side
  arrays through a shared-memory arena (mmap, dlpack-style per-block
  header, CRC over the header ONLY — hashing the payload would re-pay the
  serialization the path exists to skip) and the stream message carries
  just an i32 descriptor. ``wire_ser_s``/``wire_deser_s`` measure the
  delta. The arena is negotiated in-stream (MSG_ARENA → MSG_ARENA_ACK):
  a server without the directory simply declines and the client stays on
  inline stream frames.

**Cross-stream dispatch coalescing** (server side): concurrent streamed
solves whose session key, padded pod shapes, and ``n_max`` agree are
grouped by a small collection window and dispatched as ONE vmapped device
call (``jax.vmap`` over the scan kernel with the catalog-side tensors
broadcast), then de-multiplexed into per-message responses. The vmapped
scan kernel is bit-exact with the single-dispatch path (the sharded
multi-solve's long-standing parity property; the PR-10 canary covers the
results like any other accelerated solve), and one dispatch for B solves
pays the device/tunnel round trip once instead of B times.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib
from collections import OrderedDict
from concurrent import futures
from dataclasses import dataclass
from queue import Empty, Queue
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.resilience.overload import OverloadedError

logger = logging.getLogger("karpenter.solver.stream")

# ---------------------------------------------------------------------------
# envelope codec
# ---------------------------------------------------------------------------

STREAM_MAGIC = b"KSTM"
STREAM_VERSION = 1
ENVELOPE_BYTES = 20  # magic + <HH + <Q + <I

MSG_SOLVE = 1  # payload: a unary v3 Pack request frame
MSG_OPEN = 2  # payload: a unary v3 OpenSession request frame
MSG_RESULT = 3  # payload: the matching unary v3 response frame
MSG_CREDITS = 4  # payload: <if credits delta (initial grant), retry hint
MSG_ARENA = 5  # payload: UTF-8 arena file basename (client → server)
MSG_ARENA_ACK = 6  # payload: <i ok word (+ UTF-8 detail on refusal)
MSG_SOLVE_SHM = 7  # payload: a Pack frame with the pod arrays replaced
#                    by one shm descriptor array (see ShmArena)


class EnvelopeCorrupt(ValueError):
    """The envelope header failed its CRC: the correlation id cannot be
    trusted, so the message is DROPPED (counted), never routed — the
    sender's future times out and falls back to the unary path."""


def _envelope_crc(msg_type: int, corr_id: int) -> int:
    return zlib.crc32(struct.pack("<HHQ", STREAM_VERSION, msg_type, corr_id))


def pack_stream_msg(msg_type: int, corr_id: int, payload: bytes = b"") -> bytes:
    """One stream message: envelope header + payload bytes."""
    return (
        STREAM_MAGIC
        + struct.pack(
            "<HHQI",
            STREAM_VERSION,
            msg_type,
            corr_id,
            _envelope_crc(msg_type, corr_id),
        )
        + payload
    )


def unpack_stream_msg(data: bytes) -> Tuple[int, int, bytes]:
    """``(msg_type, corr_id, payload)``. Bad magic / version skew /
    truncation raise ``ValueError`` LOUDLY (the codec contract); a CRC
    mismatch raises :class:`EnvelopeCorrupt` (detected drop)."""
    if data[:4] != STREAM_MAGIC:
        raise ValueError("bad stream magic")
    if len(data) < ENVELOPE_BYTES:
        raise ValueError("truncated stream envelope")
    version, msg_type, corr_id, crc = struct.unpack_from("<HHQI", data, 4)
    if version != STREAM_VERSION:
        raise ValueError(f"unsupported stream version {version}")
    if crc != _envelope_crc(msg_type, corr_id):
        raise EnvelopeCorrupt("stream envelope failed CRC")
    return msg_type, corr_id, data[ENVELOPE_BYTES:]


# ---------------------------------------------------------------------------
# shared-memory arena (the zero-copy colocated fast path)
# ---------------------------------------------------------------------------

ARENA_MAGIC = 0x4B41524E  # "KARN"
DEFAULT_ARENA_BYTES = 64 << 20
_BLOCK_HEADER = struct.Struct("<IIQI")  # magic, token, payload nbytes, crc
_ALIGN = 8

# dtype codes shared with the v3 framing (service._DTYPES) — redeclared
# here to keep this module importable without a service import cycle
_SHM_DTYPES = {0: np.dtype(np.bool_), 1: np.dtype(np.int32), 2: np.dtype(np.float32)}
_SHM_DTYPE_CODES = {v: k for k, v in _SHM_DTYPES.items()}


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _header_crc(token: int, nbytes: int) -> int:
    return zlib.crc32(struct.pack("<IIQ", ARENA_MAGIC, token, nbytes))


class ShmArena:
    """Client-side writer over one mmap'd arena file.

    Allocation is a bump pointer with wraparound over a free span; blocks
    are freed on solve completion, and the bounded credit window keeps the
    live set small. A write that does not fit returns ``None`` — the
    caller falls back to an inline stream frame, never an error.

    Block layout at ``offset``::

        <IIQI  magic | token | payload nbytes | crc32(header)   (24 B, padded)
        raw C-order array bytes, each 8-byte aligned

    The CRC covers the HEADER ONLY: the point of the arena is to skip
    touching the payload bytes (``wire_ser_s → ~0``); payload integrity is
    the same trust domain as process memory (the two processes share a
    host). The descriptor that crosses the stream — and is covered by the
    frame checksum when PROTO_CHECKSUM is negotiated — carries offset,
    token, and the per-array dtype/shape table, so the reader can verify
    the header before trusting a byte of it.
    """

    def __init__(
        self,
        directory: str,
        size: int = DEFAULT_ARENA_BYTES,
        name: Optional[str] = None,
    ):
        import mmap

        os.makedirs(directory, exist_ok=True)
        self.name = name or f"arena-{os.getpid()}-{os.urandom(4).hex()}.shm"
        self.path = os.path.join(directory, self.name)
        self.size = int(size)
        with open(self.path, "wb") as f:
            f.truncate(self.size)
        self._f = open(self.path, "r+b")
        self._map = mmap.mmap(self._f.fileno(), self.size)
        self._mu = threading.Lock()
        self._next = 0  # guarded-by: self._mu
        self._live: "OrderedDict[int, Tuple[int, int]]" = OrderedDict()  # guarded-by: self._mu
        self._token = 0  # guarded-by: self._mu

    # -- allocation ---------------------------------------------------------
    def _reserve_locked(self, nbytes: int) -> Optional[int]:
        """Bump-pointer allocation, wrapping to the front once; None =
        no free span right now (the caller falls back to inline frames).
        The live set is bounded by the credit window, so the overlap scan
        is a handful of comparisons."""
        total = _aligned(_BLOCK_HEADER.size) + nbytes
        if total > self.size:
            return None
        for base in (self._next, 0):
            end = base + total
            if end > self.size:
                continue
            if any(
                not (end <= s or base >= e) for s, e in self._live.values()
            ):
                continue
            return base
        return None

    def write(self, arrays: Sequence[np.ndarray]) -> Optional[Tuple[int, np.ndarray]]:
        """Copy ``arrays`` into the arena; returns ``(token, descriptor)``
        or ``None`` when the arena cannot hold them right now. The
        descriptor is the i32 array that replaces the pod arrays on the
        wire: ``[token, offset_lo, offset_hi, n_arrays,
        (dtype, ndim, *shape) per array]``."""
        # NOT ascontiguousarray: it promotes 0-d scalars to 1-d (the same
        # contract pack_arrays keeps)
        arrs = [np.asarray(a, order="C") for a in arrays]
        if any(a.dtype not in _SHM_DTYPE_CODES for a in arrs):
            return None
        payload = sum(_aligned(a.nbytes) for a in arrs)
        with self._mu:
            base = self._reserve_locked(payload)
            if base is None:
                return None
            self._token += 1
            token = self._token & 0xFFFFFFFF
            total = _aligned(_BLOCK_HEADER.size) + payload
            self._live[token] = (base, base + total)
            self._next = base + total
            _BLOCK_HEADER.pack_into(
                self._map, base,
                ARENA_MAGIC, token, payload, _header_crc(token, payload),
            )
        # payload copies happen OFF the lock: the region is reserved, and
        # concurrent writers own disjoint regions
        cursor = base + _aligned(_BLOCK_HEADER.size)
        desc: List[int] = [token, base & 0x7FFFFFFF, base >> 31, len(arrs)]
        for a in arrs:
            self._map[cursor:cursor + a.nbytes] = a.tobytes()
            desc += [_SHM_DTYPE_CODES[a.dtype], a.ndim, *a.shape]
            cursor += _aligned(a.nbytes)
        return token, np.asarray(desc, np.int32)

    def free(self, token: int) -> None:
        with self._mu:
            self._live.pop(token, None)

    def live_blocks(self) -> int:
        with self._mu:
            return len(self._live)

    def close(self) -> None:
        try:
            self._map.close()
            self._f.close()
        except Exception:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ShmArenaReader:
    """Server-side read-only view of a client's arena file. ``read``
    validates the block header (magic + token + length + CRC) before
    trusting any offset, then returns zero-copy numpy views onto the
    mmap — the device upload is the first (and only) copy."""

    def __init__(self, path: str):
        import mmap

        self._f = open(path, "rb")
        self.size = os.fstat(self._f.fileno()).st_size
        self._map = mmap.mmap(self._f.fileno(), self.size, prot=mmap.PROT_READ)

    def read(self, desc: np.ndarray) -> List[np.ndarray]:
        d = np.asarray(desc).reshape(-1)
        if d.dtype != np.int32 or d.size < 4:
            raise ValueError("malformed shm descriptor")
        token = int(d[0]) & 0xFFFFFFFF
        base = int(d[1]) | (int(d[2]) << 31)
        n_arrays = int(d[3])
        if not 0 <= base <= self.size - _BLOCK_HEADER.size:
            raise ValueError("shm descriptor offset out of bounds")
        magic, htoken, nbytes, crc = _BLOCK_HEADER.unpack_from(self._map, base)
        if magic != ARENA_MAGIC or htoken != token:
            raise ValueError("shm block header does not match descriptor")
        if crc != _header_crc(htoken, nbytes):
            raise ValueError("shm block header failed CRC")
        cursor = base + _aligned(_BLOCK_HEADER.size)
        if cursor + nbytes > self.size:
            raise ValueError("shm block payload out of bounds")
        out: List[np.ndarray] = []
        i = 4
        for _ in range(n_arrays):
            if i + 2 > d.size:
                raise ValueError("truncated shm descriptor")
            dtype = _SHM_DTYPES.get(int(d[i]))
            ndim = int(d[i + 1])
            if dtype is None or i + 2 + ndim > d.size:
                raise ValueError("malformed shm descriptor entry")
            shape = tuple(int(x) for x in d[i + 2:i + 2 + ndim])
            i += 2 + ndim
            n_items = int(np.prod(shape, dtype=np.int64))
            arr_bytes = n_items * dtype.itemsize
            if cursor + arr_bytes > base + _aligned(_BLOCK_HEADER.size) + nbytes:
                raise ValueError("shm array exceeds block payload")
            out.append(
                np.frombuffer(
                    self._map, dtype=dtype, count=n_items, offset=cursor
                ).reshape(shape)
            )
            cursor += _aligned(arr_bytes)
        return out

    def close(self) -> None:
        try:
            self._map.close()
            self._f.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# server half
# ---------------------------------------------------------------------------

DEFAULT_COALESCE_WINDOW_S = 0.002
COALESCE_MAX = 8


@dataclass
class StreamSolve:
    """One parsed streamed solve awaiting dispatch (server side)."""

    key: bytes
    n_max: int
    record: bool
    flags: int
    pod_arrays: List[np.ndarray]
    ctx: object  # SpanContext | None
    deadline: Optional[float]  # absolute, on the service clock
    checksummed: bool
    respond: Callable[[bytes], None]
    shm: bool = False
    answered: bool = False

    def reply(self, response: bytes) -> bool:
        """Answer this solve EXACTLY once (every answer decrements the
        stream's inflight count and returns the sender a credit — a
        double reply would corrupt both ledgers). False = already
        answered; only dispatch threads touch an entry, so no lock."""
        if self.answered:
            return False
        self.answered = True
        self.respond(response)
        return True

    @property
    def group_key(self) -> tuple:
        return (
            self.key,
            self.n_max,
            tuple((a.shape, str(a.dtype)) for a in self.pod_arrays),
        )


class _CoalescingDispatcher:
    """Cross-stream dispatch coalescing: one queue fed by EVERY stream's
    reader; a dispatcher thread drains it in small collection windows,
    groups entries whose (session key, pod shapes, n_max) agree, and
    submits each group to the solve executor as ONE device dispatch."""

    def __init__(
        self,
        service,
        executor: futures.ThreadPoolExecutor,
        window_s: float = DEFAULT_COALESCE_WINDOW_S,
        max_batch: int = COALESCE_MAX,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.service = service
        self.window_s = max(float(window_s), 0.0)
        self.max_batch = max(int(max_batch), 1)
        self._executor = executor
        self._clock = clock
        self._q: "Queue[StreamSolve]" = Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="solver-stream-coalescer", daemon=True
        )
        self._thread.start()

    def submit(self, entry: StreamSolve) -> None:
        self._q.put(entry)

    def stop(self) -> None:
        self._stop.set()

    def _busy(self) -> bool:
        """Solves already admitted or queued at the device side — the
        signal that waiting the collection window costs nothing (this
        entry would queue at the gate anyway)."""
        try:
            return self.service.admission.depth() > 0
        except Exception:
            return False

    def _collect(self) -> List[StreamSolve]:
        try:
            first = self._q.get(timeout=0.25)
        except Empty:
            return []
        batch = [first]
        # free coalescing first: everything already queued groups at zero
        # added latency
        while True:
            try:
                batch.append(self._q.get_nowait())
            except Empty:
                break
        # linger the window for stragglers ONLY when there is concurrency
        # to harvest — companions already arrived, or the device side is
        # busy (this work would queue at the admission gate anyway). A
        # solo solve against an idle device dispatches IMMEDIATELY: the
        # streamed RTT floor must never pay the window.
        if self.window_s > 0 and (len(batch) > 1 or self._busy()):
            deadline = self._clock() + self.window_s
            while True:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except Empty:
                    break
        return batch

    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self._collect()
            if not batch:
                continue
            groups: "OrderedDict[tuple, List[StreamSolve]]" = OrderedDict()
            for entry in batch:
                groups.setdefault(entry.group_key, []).append(entry)
            for entries in groups.values():
                for i in range(0, len(entries), self.max_batch):
                    chunk = entries[i:i + self.max_batch]
                    self._executor.submit(self._run_group, chunk)

    def _run_group(self, entries: List[StreamSolve]) -> None:
        try:
            self.service.solve_stream_group(entries)
        except Exception as e:  # a handler crash must fail ITS solves only
            logger.exception("coalesced stream dispatch failed")
            from karpenter_tpu.solver import service as svc

            for entry in entries:
                try:
                    # only entries the dispatch had NOT yet answered
                    # (reply() is once-only), and SEALED per the entry's
                    # own negotiation — an unsealed refusal to an
                    # integrity-negotiated client would read as frame
                    # corruption and quarantine a healthy member. An
                    # in-sidecar crash is transient from the client's
                    # view: OVERLOADED with a short hint, so the pool's
                    # soft backoff (not a breaker trip) absorbs it.
                    entry.reply(
                        svc.SolverService._seal(
                            svc._status_response(
                                svc.STATUS_OVERLOADED,
                                [np.asarray([0.2], np.float32)],
                            ),
                            entry.checksummed,
                        )
                    )
                except Exception:
                    logger.debug(
                        "stream error response failed for %s", e, exc_info=True
                    )


class StreamServer:
    """The sidecar's half of the persistent stream: one instance per
    :func:`service.serve` call, handling every ``SolveStream`` RPC against
    one (possibly chaos-wrapped) ``SolverService``."""

    def __init__(
        self,
        service,
        max_workers: int = 4,
        coalesce_window_s: float = DEFAULT_COALESCE_WINDOW_S,
        coalesce_max: int = COALESCE_MAX,
        shm_dir: str = "",
        clock: Callable[[], float] = time.monotonic,
    ):
        self.service = service
        self.shm_dir = shm_dir
        self._clock = clock
        self._executor = futures.ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="solver-stream-solve",
        )
        self.dispatcher = _CoalescingDispatcher(
            service, self._executor,
            window_s=coalesce_window_s, max_batch=coalesce_max, clock=clock,
        )
        self.stats: Dict[str, int] = {
            "streams_opened": 0, "stream_solves": 0, "shm_solves": 0,
            "stream_opens": 0, "envelope_rejects": 0,
        }  # guarded-by: self._stats_mu
        self._stats_mu = threading.Lock()

    def _count(self, key: str, n: int = 1) -> None:
        with self._stats_mu:
            self.stats[key] = self.stats.get(key, 0) + n

    def snapshot(self) -> Dict[str, int]:
        with self._stats_mu:
            out = dict(self.stats)
        # the service owns the coalescing counters (they are dispatch
        # facts, not transport facts)
        for k in ("coalesced_dispatches", "coalesced_solves"):
            out[k] = int(getattr(self.service, "stream_stats", {}).get(k, 0))
        return out

    def stop(self) -> None:
        self.dispatcher.stop()
        self._executor.shutdown(wait=False)

    # -- per-stream machinery -----------------------------------------------
    def _credit_window(self) -> Tuple[int, float]:
        adm = self.service.admission
        return (
            adm.max_inflight + adm.queue_depth,
            float(self.service.overload_retry_after),
        )

    def _attach_arena(self, payload: bytes) -> Tuple[Optional[ShmArenaReader], bytes]:
        """MSG_ARENA: mmap the client's arena iff colocation is configured
        and the file resolves INSIDE our shm dir (basename-only joins, so
        a hostile path cannot escape it)."""
        if not self.shm_dir:
            return None, b"sidecar has no --solver-shm-dir"
        name = os.path.basename(payload.decode("utf-8", "replace"))
        path = os.path.realpath(os.path.join(self.shm_dir, name))
        if not path.startswith(os.path.realpath(self.shm_dir) + os.sep):
            return None, b"arena path escapes shm dir"
        try:
            return ShmArenaReader(path), b""
        except OSError as e:
            return None, str(e).encode()

    def handle(self, request_iterator, grpc_context):
        """The gRPC stream_stream handler: a generator yielding response
        messages as solves complete (out of order by construction — the
        executor finishes them in whatever order the device does)."""
        self._count("streams_opened")
        out_q: "Queue[bytes]" = Queue()
        state = {"inflight": 0, "closed": False, "abort": None}  # guarded-by: mu
        mu = threading.Lock()
        arena_box: List[Optional[ShmArenaReader]] = [None]
        credits, hint = self._credit_window()
        out_q.put(
            pack_stream_msg(
                MSG_CREDITS, 0, struct.pack("<if", credits, hint)
            )
        )

        def done(corr_id: int, response: bytes) -> None:
            out_q.put(pack_stream_msg(MSG_RESULT, corr_id, response))
            with mu:
                state["inflight"] -= 1

        def reader() -> None:
            try:
                for raw in request_iterator:
                    try:
                        msg_type, corr_id, payload = unpack_stream_msg(raw)
                    except EnvelopeCorrupt:
                        # the corr id cannot be trusted: a response would
                        # risk completing the wrong future — drop, count,
                        # let the sender's timeout take the unary fallback
                        self._count("envelope_rejects")
                        logger.error(
                            "stream envelope failed CRC; dropping message"
                        )
                        continue
                    if msg_type == MSG_ARENA:
                        arena, err = self._attach_arena(payload)
                        arena_box[0] = arena
                        ok = 1 if arena is not None else 0
                        out_q.put(
                            pack_stream_msg(
                                MSG_ARENA_ACK, corr_id,
                                struct.pack("<i", ok) + err,
                            )
                        )
                        continue
                    if msg_type == MSG_OPEN:
                        self._count("stream_opens")
                        with mu:
                            state["inflight"] += 1
                        self._executor.submit(
                            self._run_open, payload, corr_id, done
                        )
                        continue
                    if msg_type in (MSG_SOLVE, MSG_SOLVE_SHM):
                        self._count("stream_solves")
                        if msg_type == MSG_SOLVE_SHM:
                            self._count("shm_solves")
                        try:
                            entry_or_resp = self.service.stream_parse_solve(
                                payload,
                                respond=lambda b, c=corr_id: done(c, b),
                                arena=(
                                    arena_box[0]
                                    if msg_type == MSG_SOLVE_SHM else None
                                ),
                            )
                        except Exception as e:
                            # version skew (and anything else the typed
                            # refusals don't cover) must break the stream
                            # LOUDLY: the abort fails the RPC itself, the
                            # client breaks immediately and its unary
                            # fallback re-raises the skew at the codec —
                            # never a silently wedged reader
                            logger.error(
                                "stream reader aborting: unparseable solve "
                                "message (%s)", e,
                            )
                            with mu:
                                state["abort"] = e
                            return
                        # inflight counts only messages that will produce
                        # a response (parse failures above never would,
                        # and must not wedge the drain condition)
                        with mu:
                            state["inflight"] += 1
                        if isinstance(entry_or_resp, bytes):
                            done(corr_id, entry_or_resp)
                            continue
                        # earliest-possible deadline shed: an already-
                        # doomed solve never pays the dispatcher hop or
                        # an executor slot
                        shed = self.service.shed_if_expired(entry_or_resp)
                        if shed is not None:
                            entry_or_resp.reply(shed)
                        else:
                            self.dispatcher.submit(entry_or_resp)
                        continue
                    logger.warning(
                        "unknown stream message type %d; ignoring", msg_type
                    )
            except Exception:
                logger.debug("stream reader ended", exc_info=True)
            finally:
                with mu:
                    state["closed"] = True

        t = threading.Thread(
            target=reader, name="solver-stream-reader", daemon=True
        )
        t.start()
        try:
            while True:
                try:
                    yield out_q.get(timeout=0.25)
                    continue
                except Empty:
                    pass
                with mu:
                    abort = state["abort"]
                    drained = state["closed"] and state["inflight"] <= 0
                if abort is not None:
                    # fail the RPC itself: the client sees the break NOW
                    # instead of each in-flight solve burning its timeout
                    raise RuntimeError(f"solve stream aborted: {abort}")
                if drained and out_q.empty():
                    return
                if grpc_context is not None and not grpc_context.is_active():
                    return
        finally:
            arena = arena_box[0]
            if arena is not None:
                arena.close()

    def _run_open(self, payload: bytes, corr_id: int, done) -> None:
        try:
            response = self.service.open_session_bytes(payload)
        except Exception as e:
            # version skew and other loud protocol errors: the unary
            # handler would fail the RPC; over the stream the closest
            # equivalent is failing THIS message with a typed refusal
            logger.error("streamed open failed: %s", e)
            from karpenter_tpu.solver import service as svc

            response = svc._status_response(svc.STATUS_INTEGRITY)
        done(corr_id, response)


# ---------------------------------------------------------------------------
# client half
# ---------------------------------------------------------------------------


class StreamUnavailable(RuntimeError):
    """No established stream right now — callers take the unary path."""


class StreamBrokenError(RuntimeError):
    """The stream died with this solve in flight — the caller retries it
    over the unary path (the result may simply have been lost in
    transit; the solve itself is idempotent)."""


def _count_metric(name: str, address: str, **labels) -> None:
    try:
        from karpenter_tpu import metrics

        getattr(metrics, name).labels(address=address, **labels).inc()
    except Exception:
        pass  # trimmed registries


class StreamClient:
    """The controller's half of the persistent stream toward ONE sidecar.

    Lifecycle: ``ensure()`` establishes lazily (the server's MSG_CREDITS
    grant is the "stream is up" signal); any receive-loop error fails all
    in-flight futures with :class:`StreamBrokenError`, flips the state to
    down, and starts ONE background reconnect thread with decorrelated-
    jitter backoff — the hot path never blocks on a dead stream, it just
    sees :class:`StreamUnavailable` and stays on unary."""

    ESTABLISH_TIMEOUT_S = 5.0
    RECONNECT_CAP_S = 15.0

    def __init__(
        self,
        channel,
        address: str,
        shm_dir: str = "",
        arena_bytes: int = DEFAULT_ARENA_BYTES,
        clock: Callable[[], float] = time.monotonic,
    ):
        from karpenter_tpu.solver import service as svc

        self.address = address
        self._call_factory = channel.stream_stream(svc.STREAM_METHOD)
        self._clock = clock
        self._shm_dir = shm_dir
        self._arena_bytes = arena_bytes
        self._mu = threading.Lock()
        # serializes whole establish attempts (they block up to
        # ESTABLISH_TIMEOUT_S): two racing establishes would each bump
        # the epoch and orphan the first one's receiver mid-handshake.
        # A flag, not a lock — holding a lock across the handshake wait
        # would stall the loser the full timeout against a healthy
        # stream; losers return False and take the unary fallback.
        self._establishing = False  # guarded-by: self._mu
        self._state = "down"  # guarded-by: self._mu — down|up|closed
        self._credits = 0  # guarded-by: self._mu
        self._hint = 0.05  # guarded-by: self._mu
        # corr id -> (future, spent_credit) — guarded-by: self._mu
        self._pending: Dict[int, tuple] = {}
        self._corr = 0  # guarded-by: self._mu
        self._out: Optional[Queue] = None  # guarded-by: self._mu
        self._epoch = 0  # guarded-by: self._mu
        self._reconnecting = False  # guarded-by: self._mu
        self._arena: Optional[ShmArena] = None  # guarded-by: self._mu
        self._shm_ready = threading.Event()
        # failed-establish cooldown: the hot path must not re-pay the
        # establish timeout per solve against a wedged peer
        self._cooldown_until = 0.0  # guarded-by: self._mu
        self.credit_stalls = 0  # guarded-by: self._mu
        self.breaks = 0  # guarded-by: self._mu
        self.established_count = 0  # guarded-by: self._mu

    # -- state --------------------------------------------------------------
    @property
    def up(self) -> bool:
        with self._mu:
            return self._state == "up"

    @property
    def shm_active(self) -> bool:
        with self._mu:
            return (
                self._state == "up"
                and self._arena is not None
                and self._shm_ready.is_set()
            )

    def ensure(self) -> bool:
        """Establish if down (bounded); True when the stream is usable.
        While a background reconnect is in flight this returns False
        immediately — the caller's unary path is the wait-free fallback."""
        with self._mu:
            if self._state == "up":
                return True
            if self._state == "closed" or self._reconnecting:
                return False
            if self._clock() < self._cooldown_until:
                return False
        return self._establish()

    def _establish(self) -> bool:
        import grpc  # noqa: F401 — establishing requires a live channel

        with self._mu:
            if self._establishing:
                return False  # another attempt owns the handshake
            self._establishing = True
        try:
            return self._establish_once()
        finally:
            with self._mu:
                self._establishing = False

    def _establish_once(self) -> bool:
        out: "Queue[object]" = Queue()
        credits_evt = threading.Event()
        with self._mu:
            if self._state in ("up", "closed"):
                return self._state == "up"
            self._epoch += 1
            epoch = self._epoch
            self._out = out
            self._shm_ready.clear()

        sentinel = object()

        def gen():
            while True:
                try:
                    item = out.get(timeout=1.0)
                except Empty:
                    with self._mu:
                        dead = self._epoch != epoch or self._state == "closed"
                    if dead:
                        return
                    continue
                if item is sentinel:
                    return
                yield item

        try:
            call = self._call_factory(gen())
        except Exception as e:
            logger.info("stream establish to %s failed: %s", self.address, e)
            with self._mu:
                self._cooldown_until = self._clock() + 2.0
            return False

        def receiver():
            try:
                for raw in call:
                    try:
                        msg_type, corr_id, payload = unpack_stream_msg(raw)
                    except EnvelopeCorrupt:
                        logger.error(
                            "response stream envelope failed CRC; dropping"
                        )
                        _count_metric(
                            "SOLVER_STREAM_FALLBACKS", self.address,
                            reason="envelope",
                        )
                        continue
                    if msg_type == MSG_CREDITS:
                        delta, hint = struct.unpack("<if", payload[:8])
                        with self._mu:
                            if self._epoch != epoch:
                                return
                            self._credits += delta
                            self._hint = max(float(hint), 0.0)
                            if not credits_evt.is_set():
                                self._state = "up"
                                self.established_count += 1
                        credits_evt.set()
                        continue
                    if msg_type == MSG_ARENA_ACK:
                        with self._mu:
                            if self._epoch != epoch:
                                # a stale receiver's late ack must not
                                # arm shm for a fresh stream whose server
                                # never attached the arena
                                return
                        ok = struct.unpack("<i", payload[:4])[0]
                        if ok:
                            self._shm_ready.set()
                        else:
                            logger.info(
                                "sidecar %s declined shm arena: %s",
                                self.address, payload[4:].decode("utf-8", "replace"),
                            )
                        continue
                    if msg_type == MSG_RESULT:
                        with self._mu:
                            if self._epoch != epoch:
                                return
                            hit = self._pending.pop(corr_id, None)
                            # a credit returns ONLY if this request spent
                            # one: opens never do, and an unknown corr id
                            # must not mint credits past the server's
                            # admission bound (the window resets on the
                            # next stream break anyway)
                            if hit is not None and hit[1]:
                                self._credits += 1
                        if hit is None:
                            logger.warning(
                                "stream result for unknown correlation id %d",
                                corr_id,
                            )
                        else:
                            hit[0].set_result(payload)
                        continue
                    logger.warning(
                        "unknown stream response type %d; ignoring", msg_type
                    )
            except Exception as e:
                self._on_break(epoch, e)
            else:
                self._on_break(epoch, StreamBrokenError("stream closed by peer"))

        threading.Thread(
            target=receiver,
            name=f"solver-stream-recv-{self.address}",
            daemon=True,
        ).start()
        if not credits_evt.wait(self.ESTABLISH_TIMEOUT_S):
            try:
                call.cancel()
            except Exception:
                pass
            with self._mu:
                self._cooldown_until = self._clock() + 2.0
            logger.info(
                "stream to %s not established within %.1fs; staying unary",
                self.address, self.ESTABLISH_TIMEOUT_S,
            )
            return False
        # negotiate the zero-copy arena AFTER the stream is up: colocation
        # is optional and its failure must not cost stream establishment
        if self._shm_dir:
            with self._mu:
                if self._arena is None:
                    try:
                        self._arena = ShmArena(
                            self._shm_dir, size=self._arena_bytes
                        )
                    except OSError as e:
                        logger.info("shm arena unavailable: %s", e)
                arena = self._arena
            if arena is not None:
                out.put(
                    pack_stream_msg(MSG_ARENA, 0, arena.name.encode("utf-8"))
                )
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_STREAM_STATE.labels(address=self.address).set(1)
        except Exception:
            pass
        logger.info("solver stream established to %s", self.address)
        return True

    def _on_break(self, epoch: int, exc: Exception) -> None:
        with self._mu:
            if self._epoch != epoch or self._state == "closed":
                return
            if self._state != "up":
                # this epoch never established (establish's own timeout /
                # cooldown handles retry pacing) — no break accounting,
                # and no reconnect thread hammering a peer that may
                # simply not serve streams
                return
            self._state = "down"
            self._credits = 0
            self.breaks += 1
            pending = [fut for fut, _ in self._pending.values()]
            self._pending.clear()
            already = self._reconnecting
            self._reconnecting = True
            self._shm_ready.clear()
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_STREAM_STATE.labels(address=self.address).set(0)
            metrics.SOLVER_STREAM_BREAKS.labels(address=self.address).inc()
        except Exception:
            pass
        logger.warning(
            "solver stream to %s broke (%s); %d in-flight solves fall back "
            "to unary; re-establishing in the background",
            self.address, exc, len(pending),
        )
        for fut in pending:
            if not fut.done():
                fut.set_exception(
                    StreamBrokenError(f"stream to {self.address} broke: {exc}")
                )
        if not already:
            threading.Thread(
                target=self._reconnect_loop,
                name=f"solver-stream-reconnect-{self.address}",
                daemon=True,
            ).start()

    def _reconnect_loop(self) -> None:
        from karpenter_tpu.resilience import decorrelated_jitter

        backoffs = decorrelated_jitter(0.2, cap=self.RECONNECT_CAP_S)
        try:
            while True:
                with self._mu:
                    if self._state in ("up", "closed"):
                        return
                if self._establish():
                    return
                time.sleep(next(backoffs))
        finally:
            with self._mu:
                self._reconnecting = False

    def break_stream(self, reason: str = "client-side") -> None:
        """Force a teardown (a wedged stream whose future timed out must
        not keep eating solves); the background loop re-establishes."""
        with self._mu:
            epoch = self._epoch
        self._on_break(epoch, StreamBrokenError(reason))

    # -- dispatch -----------------------------------------------------------
    def _next_corr_locked(self) -> int:
        self._corr += 1
        return self._corr

    def _send(self, msg_type: int, payload: bytes, spend_credit: bool):
        with self._mu:
            if self._state != "up" or self._out is None:
                raise StreamUnavailable(f"no stream to {self.address}")
            if spend_credit:
                if self._credits <= 0:
                    self.credit_stalls += 1
                    hint = self._hint
                    _count_metric("SOLVER_STREAM_CREDIT_STALLS", self.address)
                    raise OverloadedError(
                        f"solver stream to {self.address} out of credits",
                        retry_after=hint, kind="credits",
                    )
                self._credits -= 1
            corr = self._next_corr_locked()
            fut: futures.Future = futures.Future()
            self._pending[corr] = (fut, spend_credit)
            out = self._out
        try:
            out.put(pack_stream_msg(msg_type, corr, payload))
        except Exception:
            with self._mu:
                self._pending.pop(corr, None)
                if spend_credit:
                    self._credits += 1
            raise
        return fut

    def solve(self, frame: bytes) -> futures.Future:
        """Dispatch one solve frame; the future resolves to the response
        frame bytes (out of order with other solves). Raises
        :class:`StreamUnavailable` (go unary) or typed ``OverloadedError``
        (``kind="credits"`` — the pool's soft-backoff signal)."""
        return self._send(MSG_SOLVE, frame, spend_credit=True)

    def solve_shm(self, frame: bytes) -> futures.Future:
        return self._send(MSG_SOLVE_SHM, frame, spend_credit=True)

    def open(self, frame: bytes) -> futures.Future:
        """Session open over the stream (the NEEDS_CATALOG re-open path
        rides the same multiplexed transport as the solves)."""
        return self._send(MSG_OPEN, frame, spend_credit=False)

    def write_arena(self, arrays: Sequence[np.ndarray]):
        """``(token, descriptor)`` when the zero-copy path can carry these
        arrays right now, else None (inline frame fallback)."""
        if not self.shm_active:
            return None
        with self._mu:
            arena = self._arena
        if arena is None:
            return None
        return arena.write(arrays)

    def free_arena(self, token: int) -> None:
        with self._mu:
            arena = self._arena
        if arena is not None:
            arena.free(token)

    def credits_available(self) -> int:
        with self._mu:
            return self._credits

    def close(self) -> None:
        with self._mu:
            self._state = "closed"
            pending = [fut for fut, _ in self._pending.values()]
            self._pending.clear()
            arena = self._arena
            self._arena = None
        for fut in pending:
            if not fut.done():
                fut.set_exception(StreamBrokenError("stream client closed"))
        # the outgoing generator notices "closed" on its next bounded get
        if arena is not None:
            arena.close()
