"""Pack-integrity bookkeeping: screens, canary comparison, quarantine audit.

The corruption-defense subsystem (docs/integrity.md) has four detection
layers — wire checksums and the session-generation guard live in
``solver/service.py``; this module owns the two HOST-side layers plus the
shared accounting every layer reports into:

- :func:`screen_result` — a cheap NaN/bounds screen over every accelerated
  pack result (µs against a >1ms decode): a checksummed frame proves the
  BYTES survived the wire, not that the device computed them correctly —
  an SDC-afflicted chip produces plausible-shaped garbage that only
  content checks can catch.
- :func:`compare_results` — the canary cross-check's comparator. The native
  C++ packer is bit-identical to the device kernel by contract
  (tests/test_native_pack.py), so a canary re-solve that disagrees with the
  served pack is evidence of corruption, not of tie-breaking drift.
- :func:`snapshot` — the ``integrity`` flight-recorder state panel: when a
  slow/failed solve is recorded, the incident file says what the
  corruption counters believed at that moment.

Counters are process-global (one scheduler per worker, many workers per
process) and mirrored to Prometheus; the in-memory copy exists so bench
legs and the flight recorder can read them without scraping.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

_mu = threading.Lock()
_counts: Dict[str, Dict[str, int]] = {
    "checksum_failures": {},
    "session_mismatches": {},
    "canary_solves": {},
    "canary_mismatches": {},
    "screen_failures": {},
    "quarantines": {},
}  # guarded-by: _mu
_quarantine_log: List[dict] = []  # guarded-by: _mu (last N quarantine events)
_QUARANTINE_LOG_MAX = 32


def _bump(kind: str, address: str) -> None:
    key = address or "local"
    with _mu:
        table = _counts[kind]
        table[key] = table.get(key, 0) + 1


def _metric(name: str, address: str) -> None:
    try:
        from karpenter_tpu import metrics

        getattr(metrics, name).labels(address=address or "local").inc()
    except Exception:
        pass  # trimmed registries


def record_checksum_failure(address: str) -> None:
    _bump("checksum_failures", address)
    _metric("SOLVER_INTEGRITY_CHECKSUM_FAILURES", address)


def record_session_mismatch(address: str) -> None:
    _bump("session_mismatches", address)
    _metric("SOLVER_INTEGRITY_SESSION_MISMATCHES", address)


def record_canary(address: str, mismatch: bool) -> None:
    _bump("canary_solves", address)
    _metric("SOLVER_INTEGRITY_CANARY_SOLVES", address)
    if mismatch:
        _bump("canary_mismatches", address)
        _metric("SOLVER_INTEGRITY_CANARY_MISMATCHES", address)


def record_screen_failure(address: str) -> None:
    _bump("screen_failures", address)
    _metric("SOLVER_INTEGRITY_SCREEN_FAILURES", address)


def record_quarantine(address: str, reason: str, detail: str = "") -> None:
    _bump("quarantines", address)
    _metric("SOLVER_INTEGRITY_QUARANTINES", address)
    with _mu:
        _quarantine_log.append({
            "address": address or "local",
            "reason": reason,
            "detail": detail[:200],
            "t": time.time(),
        })
        del _quarantine_log[:-_QUARANTINE_LOG_MAX]


def snapshot() -> dict:
    """The ``integrity`` flight-recorder panel / bench accounting view."""
    with _mu:
        return {
            **{k: dict(v) for k, v in _counts.items()},
            "recent_quarantines": list(_quarantine_log[-8:]),
        }


def totals() -> Dict[str, int]:
    """Per-kind totals summed over addresses (bench acceptance numbers)."""
    with _mu:
        return {k: sum(v.values()) for k, v in _counts.items()}


def reset() -> None:
    """Test/bench isolation: zero the in-memory copy (Prometheus counters
    are monotonic by design and stay)."""
    with _mu:
        for table in _counts.values():
            table.clear()
        del _quarantine_log[:]


# ---------------------------------------------------------------------------
# host-side content checks
# ---------------------------------------------------------------------------


def screen_result(result, n_pods: int) -> Optional[str]:
    """NaN/bounds screen over a host-side PackResult. Returns a description
    of the first violation, or None.

    Deliberately about REPRESENTATION, not semantics: semantics (capacity,
    double placement) is `_validate_pack`'s decoded-plan job. This catches
    what decode would silently launder into the plan — non-finite node
    requests, assignments pointing outside the node table, an impossible
    node count — the shapes device SDC and NaN injection actually take."""
    assignment, node_sig, node_host, node_req, n_nodes_arr = result
    n_max = int(np.asarray(node_sig).shape[0])
    n_nodes = np.asarray(n_nodes_arr).reshape(-1)[0]
    if not np.isfinite(float(n_nodes)):
        return "n_nodes is not finite"
    n_nodes = int(n_nodes)
    if not 0 <= n_nodes <= n_max:
        return f"n_nodes {n_nodes} outside [0, {n_max}]"
    a = np.asarray(assignment)[:n_pods]
    if a.size and (int(a.max(initial=-1)) >= n_nodes or int(a.min(initial=0)) < -1):
        return (
            f"assignment outside [-1, {n_nodes}) "
            f"(min {int(a.min())}, max {int(a.max())})"
        )
    req = np.asarray(node_req)[:max(n_nodes, 0)]
    if req.size and not np.isfinite(req).all():
        return "node_req contains non-finite values"
    if req.size and float(req.min(initial=0.0)) < 0:
        return "node_req contains negative totals"
    host = np.asarray(node_host)[:max(n_nodes, 0)]
    if host.size and not np.isfinite(host.astype(np.float64)).all():
        return "node_host contains non-finite values"
    return None


def compare_results(served, reference, n_pods: int) -> Optional[str]:
    """Canary comparator: the served pack vs the native re-solve of the
    SAME encoded batch at the SAME node-table size. Native/device parity is
    bit-identical by contract, so any divergence is a finding. Returns the
    first difference, or None."""
    s_assign, s_sig, s_host, s_req, s_n = served
    r_assign, r_sig, r_host, r_req, r_n = reference
    sn, rn = (
        int(np.asarray(s_n).reshape(-1)[0]),
        int(np.asarray(r_n).reshape(-1)[0]),
    )
    if sn != rn:
        return f"n_nodes differs (served {sn}, native {rn})"
    if not np.array_equal(
        np.asarray(s_assign)[:n_pods], np.asarray(r_assign)[:n_pods]
    ):
        return "assignment differs"
    if not np.array_equal(np.asarray(s_sig)[:sn], np.asarray(r_sig)[:sn]):
        return "node signatures differ"
    if not np.array_equal(np.asarray(s_host)[:sn], np.asarray(r_host)[:sn]):
        return "node hostnames differ"
    if not np.allclose(
        np.asarray(s_req)[:sn], np.asarray(r_req)[:sn],
        rtol=1e-5, atol=1e-5, equal_nan=False,
    ):
        return "node request totals differ"
    return None
