"""Tensorize one solve: pods × instance types × constraints → dense arrays.

Host-side preparation for the packing kernel:

1. canonicalize every pod into a (core, hostname) pair and intern cores;
2. build the signature closure (base ⊕ cores under join) with the exact
   requirements algebra (``signature.py``);
3. emit dense arrays — join table ``[S, C]``, capacity frontiers
   ``[S, F, R]``, per-pod core/hostname/request vectors — padded to bucketed
   shapes so XLA compiles once per shape bucket.

Complement-set semantics never reach the device: they are fully resolved into
the join table and frontiers here.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Pod
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.solver.signature import (
    Core,
    SignatureOverflow,
    SignatureTable,
    pod_core_and_hostname,
)
from karpenter_tpu.utils import resources as res

# Frontier rows are padded with this; requests are non-negative and include a
# pods count ≥ 1, so a padded row can never satisfy a fit test.
FRONTIER_PAD = -1.0

# closure results retained per table (one per recently seen core vocabulary)
CLOSURE_MEMO_MAX = 8


def _bucket(n: int, minimum: int = 64) -> int:
    """Shape bucket ≥ n: powers of two up to 2048, then multiples of 2048 —
    the scan cost is linear in the padded pod count, so pure pow2 buckets
    waste up to 2× of it at large batches (10k pods → 16384). The 2048-step
    ladder keeps the jit cache small; its padding overhead shrinks with
    batch size (≤ 20% from ~10k pods up, larger below)."""
    b = minimum
    while b < n and b < 2048:
        b *= 2
    if n <= b:
        return b
    return ((n + 2047) // 2048) * 2048


@dataclass
class EncodedBatch:
    """Everything the kernel needs, plus the host-side context to decode."""

    pods: List[Pod]  # solve order (FFD-sorted)
    n_pods: int
    # device arrays (padded to p_pad)
    pod_valid: np.ndarray  # [P] bool
    pod_open_sig: np.ndarray  # [P] i32 — signature of a fresh node for this pod
    pod_core: np.ndarray  # [P] i32
    pod_host: np.ndarray  # [P] i32, -1 = no hostname requirement
    pod_host_in_base: np.ndarray  # [P] bool
    pod_open_host: np.ndarray  # [P] i32 node hostname state when opened (-1/h/-2)
    pod_req: np.ndarray  # [P, R] f32
    join_table: np.ndarray  # [S, C] i32, -1 = incompatible
    frontiers: np.ndarray  # [S, F, R] f32
    daemon: np.ndarray  # [R] f32
    # host context
    table: SignatureTable
    signatures: List  # local (batch-scoped) Signature list; kernel sig ids index it
    cores: List[Core]
    hostnames: List[str]
    axes: List[str]
    usable: np.ndarray  # [T, R]
    # compact transfer form: pod_req row i == uniq_req[pod_req_id[i]]; the
    # fused TPU dispatch ships only the unique vectors + per-pod ids (a 10k
    # batch has dozens of distinct request shapes, not 10k). The final
    # uniq_req row is all-zero and backs the padding pods.
    pod_req_id: np.ndarray = None  # [P] i32
    uniq_req: np.ndarray = None  # [U+1, R] f32
    # the TRIMMED axis names matching the emitted arrays' R (inactive
    # resource axes are dropped at emission); decode maps totals back
    # through these, not RESOURCE_AXES + axes
    axis_names: list = None
    # per-core fresh-node signatures + whether the base constraints carry a
    # hostname requirement — the fused dispatch derives pod_open_sig and
    # pod_open_host ON DEVICE from these instead of shipping two more
    # per-pod rows
    open_sig_by_core: np.ndarray = None  # [C] i32
    base_has_hostname: bool = False

    def type_mask_matrix(self) -> np.ndarray:
        """[S_local, T] stacked signature→type masks for THIS batch's
        signature space (what the kernel's sig ids index)."""
        m = getattr(self, "_mask_matrix", None)
        if m is None:
            m = self._mask_matrix = np.stack([s.type_mask for s in self.signatures])
        return m

    def pack_args(self) -> tuple:
        """The canonical positional argument order of ``kernel.pack`` — the
        single definition of the wire/call contract (backend, sidecar warmup,
        and the driver entry all build this tuple)."""
        return (
            self.pod_valid,
            self.pod_open_sig,
            self.pod_core,
            self.pod_host,
            self.pod_host_in_base,
            self.pod_open_host,
            self.pod_req,
            self.join_table,
            self.frontiers,
            self.daemon,
        )


def usable_capacity(
    instance_types: Sequence[InstanceType], extra_axes: Sequence[str]
) -> np.ndarray:
    """[T, R] allocatable minus overhead — what requests compare against
    (reference: requirements.go:68-80 merges requests+overhead vs capacity;
    subtracting overhead once per type is the same inequality). Scaled to the
    exact-integer device units (resources.AXIS_SCALES)."""
    out = np.zeros((len(instance_types), res.NUM_RESOURCE_AXES + len(extra_axes)), np.float32)
    for i, it in enumerate(instance_types):
        out[i] = res.to_scaled_vector(it.resources, extra_axes) - res.to_scaled_vector(
            it.overhead, extra_axes
        )
    return out


class EncodeCache:
    """Per-scheduler reuse of solve-invariant encode state.

    The signature table (type masks, Pareto frontiers, join closure) and the
    usable-capacity matrix depend only on (hostname-free constraints,
    catalog, resource axes) — stable across a provisioner's batches until
    the catalog changes — yet round 1 rebuilt them every solve (~40ms of the
    10k-pod latency budget). Keyed by a semantic catalog fingerprint, NOT
    object identity (providers build fresh InstanceType objects per
    get_instance_types call), with small-LRU eviction so a drifting catalog
    cannot grow the cache unboundedly. Owned by one scheduler (one worker
    thread), not shared.

    Hit/miss traffic is counted (``solver_encode_cache_{hits,misses}_total``)
    so a thrashing cache — e.g. a provider whose catalog fingerprint churns
    every refresh — is visible on the scrape instead of only as an
    unattributed ~40ms p99 regression."""

    MAX_ENTRIES = 4

    def __init__(self, max_entries: int = MAX_ENTRIES):
        self.max_entries = max_entries
        self.tables: "OrderedDict[Tuple, Tuple[np.ndarray, SignatureTable]]" = OrderedDict()

    def get(self, key: Tuple):
        from karpenter_tpu import metrics

        hit = self.tables.get(key)
        if hit is not None:
            self.tables.move_to_end(key)
            metrics.SOLVER_ENCODE_CACHE_HITS.inc()
        else:
            metrics.SOLVER_ENCODE_CACHE_MISSES.inc()
        return hit

    def put(self, key: Tuple, value) -> None:
        self.tables[key] = value
        self.tables.move_to_end(key)
        while len(self.tables) > self.max_entries:
            self.tables.popitem(last=False)

    def clear(self) -> None:
        self.tables.clear()


# fingerprint memo keyed by the catalog's object identities: providers
# recreate InstanceType objects per get_instance_types() call, but within a
# worker the same objects recur for many solves, and re-deriving the
# semantic fingerprint walked 400 types every solve. Holding the catalog
# tuple in the value keeps the ids valid for the entry's lifetime.
# Lock-protected: catalog_fingerprint runs from concurrent per-provisioner
# solve workers, and an unlocked popitem can race a sibling's move_to_end
# into a KeyError (same contract as requirements._catreq_cache).
_fp_cache: "OrderedDict[Tuple, Tuple]" = OrderedDict()  # guarded-by: _fp_lock
_fp_lock = threading.Lock()
_FP_CACHE_MAX = 8


def catalog_fingerprint(instance_types: Sequence[InstanceType]) -> Tuple:
    """Order-sensitive semantic identity of a catalog — every field that
    feeds type compatibility or the usable-capacity matrix."""
    id_key = tuple(map(id, instance_types))
    with _fp_lock:
        hit = _fp_cache.get(id_key)
        if hit is not None:
            _fp_cache.move_to_end(id_key)
            return hit[1]
    fp = _catalog_fingerprint(instance_types)
    with _fp_lock:
        _fp_cache[id_key] = (tuple(instance_types), fp)
        while len(_fp_cache) > _FP_CACHE_MAX:
            _fp_cache.popitem(last=False)
    return fp


def _catalog_fingerprint(instance_types: Sequence[InstanceType]) -> Tuple:
    return tuple(
        (
            it.name,
            it.architecture,
            tuple(sorted(it.operating_systems)),
            tuple(sorted((o.capacity_type, o.zone) for o in it.offerings)),
            tuple(sorted(it.resources.items())),
            tuple(sorted(it.overhead.items())),
            it.price,
            tuple(sorted(it.labels.items())),
        )
        for it in instance_types
    )


def _table_key(constraints: Constraints, instance_types, axes) -> Tuple:
    reqs = tuple(
        (r.key, r.operator, tuple(r.values))
        for r in constraints.requirements.requirements
        if r.key != lbl.HOSTNAME
    )
    return (reqs, catalog_fingerprint(instance_types), tuple(axes))


def encode(
    constraints: Constraints,
    instance_types: Sequence[InstanceType],
    pods: Sequence[Pod],
    daemon: Dict[str, float],
    cache: Optional[EncodeCache] = None,
    plan=None,
) -> EncodedBatch:
    """Build the dense solve request. ``instance_types`` must already be
    price-sorted and ``pods`` FFD-sorted. Raises SignatureOverflow when
    constraint diversity exceeds the closure cap (caller falls back to FFD).

    Two input modes: with ``plan`` (a ``topology.DomainPlan``), topology
    decisions are overlaid from the plan onto each pod's memoized statics —
    zero pod mutation, the hot path. Without it, decisions must already be
    materialized into the pods' nodeSelectors (legacy callers re-parse each
    pod's spec).
    """
    from karpenter_tpu.scheduling.statics import merged_core, statics

    # resource axes: reserved + any extended resources in play
    if plan is not None:
        # inject_plan already paid the statics pass over this exact list
        if plan.sts is not None and plan._pods is pods:
            sts = plan.sts
        else:
            sts = [statics(p) for p in pods]
        import operator

        pod_extras = frozenset().union(
            *map(operator.attrgetter("extra_res"), sts)
        ) if sts else set()
        extras = sorted(
            pod_extras
            | set(
                res.collect_extra_axes(
                    [it.resources for it in instance_types]
                    + [it.overhead for it in instance_types]
                    + [daemon]
                )
            )
        )
        pod_requests = None
    else:
        sts = None
        pod_requests = [res.requests_for_pods(p) for p in pods]
        extras = res.collect_extra_axes(
            [it.resources for it in instance_types]
            + [it.overhead for it in instance_types]
            + pod_requests
            + [daemon]
        )
    axes = extras  # extra axis names appended after the reserved block
    key = _table_key(constraints, instance_types, axes) if cache is not None else None
    cached = cache.get(key) if cache is not None else None
    if cached is not None:
        usable, table = cached
        table.set_base(constraints)
    else:
        usable = usable_capacity(instance_types, axes)
        table = SignatureTable(constraints, instance_types, usable, axes)
        if cache is not None:
            cache.put(key, (usable, table))

    # canonicalize pods; intern cores + hostnames + request vectors.
    # Plain python lists + one np.array at the end: 10k individual ndarray
    # element stores were a measurable slice of encode.
    cores: List[Core] = []
    core_ids: Dict[Core, int] = {}
    hostnames: List[str] = []
    host_ids: Dict[str, int] = {}
    host_in_base_by_id: List[bool] = []
    req_ids: Dict[Tuple, int] = {}
    uniq_vecs: List[np.ndarray] = []

    n = len(pods)
    core_l = [0] * n
    host_l = [-1] * n
    hib_l = [False] * n
    openh_l = [-1] * n
    reqid_l = [0] * n
    base_has_hostname = constraints.requirements.has(lbl.HOSTNAME)

    # template collapse: pods sharing (selector/affinity template, injected
    # non-hostname decisions, request template) resolve (core id, base
    # hostname, request id) through ONE identity-keyed dict hit; injected
    # hostnames resolve through one more
    tmpl_cache: Dict[Tuple, Tuple] = {}
    if plan is not None:
        tmpl_get = tmpl_cache.get
        host_ids_get = host_ids.get
        EMPTY = ()
        # ztokens/hostdecs ARE the plan storage: gather both columns in two
        # C-level map passes instead of per-pod method calls in the loop
        pids = list(map(id, pods))
        ztoks = [t if t is not None else EMPTY for t in map(plan.ztokens.get, pids)]
        dhs = list(map(plan.hostdecs.get, pids))
        for i, st in enumerate(sts):
            ztok = ztoks[i]
            dh = dhs[i]
            k2 = (id(st.merge_tid), id(ztok), id(st.req_tid))
            hit = tmpl_get(k2)
            if hit is None:
                if ztok:
                    core, base_host = merged_core(st, ztok)
                else:
                    core, base_host = st.core0, st.hostname0
                cid = core_ids.get(core)
                if cid is None:
                    cid = len(cores)
                    core_ids[core] = cid
                    cores.append(core)
                rid = req_ids.get(st.req_key)
                if rid is None:
                    rid = len(uniq_vecs)
                    req_ids[st.req_key] = rid
                    uniq_vecs.append(res.to_scaled_vector(st.req, axes))
                hit = tmpl_cache[k2] = (cid, base_host, rid)
            cid, base_host, rid = hit
            core_l[i] = cid
            reqid_l[i] = rid
            # hostname precedence mirrors the selector-merge order: folded
            # affinity > injected decision > the pod's own selector
            hostname = (
                base_host if (dh is None or st.aff_hostname is not None) else dh
            )
            if hostname is None:
                continue
            hid = host_ids_get(hostname)
            if hid is None:
                hid = len(hostnames)
                host_ids[hostname] = hid
                hostnames.append(hostname)
                host_in_base_by_id.append(table.hostname_in_base(hostname))
            host_l[i] = hid
            in_base = host_in_base_by_id[hid]
            hib_l[i] = in_base
            openh_l[i] = hid if (in_base or not base_has_hostname) else -2
    for i, pod in enumerate(pods if plan is None else ()):
        core, hostname = pod_core_and_hostname(pod)
        requests = pod_requests[i]
        rkey = tuple(sorted(requests.items()))
        cid = core_ids.get(core)
        if cid is None:
            cid = len(cores)
            core_ids[core] = cid
            cores.append(core)
        core_l[i] = cid
        if hostname is not None:
            hid = host_ids.get(hostname)
            if hid is None:
                hid = len(hostnames)
                host_ids[hostname] = hid
                hostnames.append(hostname)
                host_in_base_by_id.append(table.hostname_in_base(hostname))
            host_l[i] = hid
            in_base = host_in_base_by_id[hid]
            hib_l[i] = in_base
            # node hostname state if this pod opens a node: joinable (h) when
            # the merged hostname set stays non-empty ({h}), poisoned (-2)
            # when the base domains exclude h (set intersects to ∅ — later
            # hostname pods can never match, reference requirements.go:175)
            openh_l[i] = hid if (in_base or not base_has_hostname) else -2
        rid = req_ids.get(rkey)
        if rid is None:
            rid = len(uniq_vecs)
            req_ids[rkey] = rid
            uniq_vecs.append(res.to_scaled_vector(requests, axes))
        reqid_l[i] = rid

    return finish_encode(
        table, usable, axes, daemon, pods,
        np.array(core_l, np.int32),
        np.array(host_l, np.int32),
        np.array(hib_l, bool),
        np.array(openh_l, np.int32),
        np.array(reqid_l, np.int32),
        cores, hostnames, uniq_vecs, base_has_hostname,
    )


def finish_encode(
    table: SignatureTable,
    usable: np.ndarray,
    axes: Sequence[str],
    daemon: Dict[str, float],
    pods: Sequence[Pod],
    pod_core: np.ndarray,
    pod_host: np.ndarray,
    pod_host_in_base: np.ndarray,
    pod_open_host: np.ndarray,
    pod_req_id_core: np.ndarray,
    cores: List[Core],
    hostnames: List[str],
    uniq_vecs: List[np.ndarray],
    base_has_hostname: bool,
) -> EncodedBatch:
    """The shared tail of ``encode``: batch-local vocab arrays → signature
    closure → axis trim → pod padding → EncodedBatch. ``delta.py``'s
    resident path reconstructs the vocab arrays from cached per-pod rows and
    calls this directly, so a delta-built batch is bit-exact against a full
    re-encode by construction — both run the identical closure/trim/pad
    code on identical inputs."""
    n = len(pods)
    R = usable.shape[1]
    # final row = zeros, backing the padding pods
    uniq_req = np.vstack(uniq_vecs + [np.zeros(R, np.float32)]).astype(np.float32)
    pod_req = uniq_req[pod_req_id_core]

    # signature closure over THIS batch's cores, scoped to the reachable
    # set and re-indexed densely: a cached table accumulates signatures and
    # joins from earlier batches, and emitting arrays sized (or indexed) by
    # the accumulated closure would both crash on foreign cores and grow
    # the kernel input without bound.
    #
    # The closure is a pure function of (table base+catalog, cores
    # vocabulary) and the table accumulates monotonically, so consecutive
    # batches with the same core vocabulary — the steady state — reuse the
    # memoized (signatures, join_table, frontiers, open sigs) instead of
    # re-sweeping S×C joins (the encode hot spot at high diversity:
    # S=C=201 is 40k join lookups per solve). Memoized ON the table: the
    # EncodeCache key already pins base constraints, catalog, and axes.
    cores_key = tuple(cores)
    closure_memo = table._closure_memo
    hit = closure_memo.get(cores_key)
    if hit is not None:
        closure_memo.move_to_end(cores_key)
        signatures, join_table, frontiers, open_sig_by_core = hit
    else:
        open_sig_global = [table.open_signature(c) for c in cores]
        order: List[int] = []
        local: Dict[int, int] = {}

        def visit(sid: int) -> None:
            if sid >= 0 and sid not in local:
                local[sid] = len(order)
                order.append(sid)

        visit(0)
        for sid in open_sig_global:
            visit(sid)
        i = 0
        while i < len(order):
            sid = order[i]
            i += 1
            for core in cores:
                visit(table.join(sid, core))

        signatures = [table.signatures[sid] for sid in order]
        S = len(signatures)
        C = max(len(cores), 1)  # gathers need a non-empty core axis
        join_table = np.full((S, C), -1, np.int32)
        for li, sid in enumerate(order):
            for cid, core in enumerate(cores):
                out = table._join_cache.get((sid, core), -1)
                if out >= 0:
                    join_table[li, cid] = local[out]

        f_max = max((len(s.frontier) for s in signatures), default=1) or 1
        frontiers = np.full((S, f_max, R), FRONTIER_PAD, np.float32)
        for li, s in enumerate(signatures):
            if len(s.frontier):
                frontiers[li, : len(s.frontier)] = s.frontier

        open_sig_by_core = np.array([local[s] for s in open_sig_global] or [0], np.int32)
        # downstream consumers never mutate these arrays (device_put,
        # np.stack copies); freeze to make sharing safe by construction
        join_table.setflags(write=False)
        frontiers.setflags(write=False)
        open_sig_by_core.setflags(write=False)
        closure_memo[cores_key] = (signatures, join_table, frontiers, open_sig_by_core)
        while len(closure_memo) > CLOSURE_MEMO_MAX:
            closure_memo.popitem(last=False)

    daemon_vec = res.to_scaled_vector(daemon, axes)

    # Trim inactive resource axes from the EMITTED arrays: kernel time and
    # transfer bytes scale with R, and a typical batch exercises 3 of the
    # 8+ reserved axes (cpu/memory/pods). An axis must stay when any pod
    # requests it, the daemon overhead uses it, or some type's usable
    # capacity is NEGATIVE there (overhead > capacity — trimming that axis
    # would stop the fit test from rejecting such types). Fit semantics on
    # a trimmed axis are vacuous (0 ≤ usable), and the frontier PAD rows
    # still fail on the kept axes, so assignments are unchanged (the wide
    # parity sweep pins this). NOTE: stacked multi-solves must encode
    # same-shaped batches — same pod-axis usage, like the existing same-S
    # requirement.
    full_names = res.RESOURCE_AXES + list(axes)
    active = (uniq_req != 0).any(axis=0) | (daemon_vec != 0) | (usable < 0).any(axis=0)
    if not active.any():
        active[0] = True  # keep at least one axis (kernels need R >= 1)
    # The trimmed CATALOG-SIDE arrays (frontiers, daemon, usable) are
    # memoized on the table per (closure, daemon content, active mask):
    # steady-state solves must return identity-STABLE objects, because the
    # session transport fingerprints the catalog side by array id
    # (RemoteSolver._catalog_key) — a fresh slice per solve would re-pay
    # blake2b over the full tensors under the solve lock every batch. The
    # pod-side slices (pod_req, uniq_req) stay per-batch.
    trim_key = (cores_key, daemon_vec.tobytes(), active.tobytes())
    trim_memo = table._trim_memo
    thit = trim_memo.get(trim_key)
    if thit is not None:
        trim_memo.move_to_end(trim_key)
        frontiers, daemon_vec, usable_out, axis_names, keep = thit
    else:
        if not active.all():
            keep = np.flatnonzero(active)
            frontiers = np.ascontiguousarray(frontiers[:, :, keep])
            daemon_vec = daemon_vec[keep]
            usable_out = usable[:, keep]
            axis_names = [full_names[i] for i in keep]
        else:
            keep = None
            usable_out = usable
            axis_names = full_names
        # downstream consumers never mutate these; freeze so the memoized
        # sharing is safe by construction (closure-memo arrays already are)
        frontiers.setflags(write=False)
        daemon_vec.setflags(write=False)
        trim_memo[trim_key] = (frontiers, daemon_vec, usable_out, axis_names, keep)
        while len(trim_memo) > CLOSURE_MEMO_MAX:
            trim_memo.popitem(last=False)
    if keep is not None:
        pod_req = pod_req[:, keep]
        uniq_req = uniq_req[:, keep]

    # pad pods to bucket
    p_pad = _bucket(max(n, 1))
    pad = p_pad - n

    def pad1(a, fill):
        return np.concatenate([a, np.full((pad,) + a.shape[1:], fill, a.dtype)]) if pad else a

    return EncodedBatch(
        pods=list(pods),
        n_pods=n,
        pod_valid=pad1(np.ones(n, bool), False),
        pod_open_sig=pad1(open_sig_by_core[pod_core], 0),
        pod_core=pad1(pod_core, 0),
        pod_host=pad1(pod_host, -1),
        pod_host_in_base=pad1(pod_host_in_base, False),
        pod_open_host=pad1(pod_open_host, -1),
        pod_req=pad1(pod_req, 0.0),
        join_table=join_table,
        frontiers=frontiers,
        daemon=daemon_vec,
        table=table,
        signatures=signatures,
        cores=cores,
        hostnames=hostnames,
        axes=axes,
        usable=usable_out,
        axis_names=axis_names,
        # padding pods point at uniq_req's final all-zero row
        pod_req_id=pad1(pod_req_id_core, len(uniq_vecs)),
        uniq_req=uniq_req,
        open_sig_by_core=open_sig_by_core,
        base_has_hostname=base_has_hostname,
    )
