"""Tensorize one solve: pods × instance types × constraints → dense arrays.

Host-side preparation for the packing kernel:

1. canonicalize every pod into a (core, hostname) pair and intern cores;
2. build the signature closure (base ⊕ cores under join) with the exact
   requirements algebra (``signature.py``);
3. emit dense arrays — join table ``[S, C]``, capacity frontiers
   ``[S, F, R]``, per-pod core/hostname/request vectors — padded to bucketed
   shapes so XLA compiles once per shape bucket.

Complement-set semantics never reach the device: they are fully resolved into
the join table and frontiers here.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Pod
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.solver.signature import (
    Core,
    SignatureOverflow,
    SignatureTable,
    pod_core_and_hostname,
)
from karpenter_tpu.utils import resources as res

# Frontier rows are padded with this; requests are non-negative and include a
# pods count ≥ 1, so a padded row can never satisfy a fit test.
FRONTIER_PAD = -1.0


def _bucket(n: int, minimum: int = 64) -> int:
    """Shape bucket ≥ n: powers of two up to 2048, then multiples of 2048 —
    the scan cost is linear in the padded pod count, so pure pow2 buckets
    waste up to 2× of it at large batches (10k pods → 16384). The 2048-step
    ladder keeps the jit cache small; its padding overhead shrinks with
    batch size (≤ 20% from ~10k pods up, larger below)."""
    b = minimum
    while b < n and b < 2048:
        b *= 2
    if n <= b:
        return b
    return ((n + 2047) // 2048) * 2048


@dataclass
class EncodedBatch:
    """Everything the kernel needs, plus the host-side context to decode."""

    pods: List[Pod]  # solve order (FFD-sorted)
    n_pods: int
    # device arrays (padded to p_pad)
    pod_valid: np.ndarray  # [P] bool
    pod_open_sig: np.ndarray  # [P] i32 — signature of a fresh node for this pod
    pod_core: np.ndarray  # [P] i32
    pod_host: np.ndarray  # [P] i32, -1 = no hostname requirement
    pod_host_in_base: np.ndarray  # [P] bool
    pod_open_host: np.ndarray  # [P] i32 node hostname state when opened (-1/h/-2)
    pod_req: np.ndarray  # [P, R] f32
    join_table: np.ndarray  # [S, C] i32, -1 = incompatible
    frontiers: np.ndarray  # [S, F, R] f32
    daemon: np.ndarray  # [R] f32
    # host context
    table: SignatureTable
    cores: List[Core]
    hostnames: List[str]
    axes: List[str]
    usable: np.ndarray  # [T, R]

    def pack_args(self) -> tuple:
        """The canonical positional argument order of ``kernel.pack`` — the
        single definition of the wire/call contract (backend, sidecar warmup,
        and the driver entry all build this tuple)."""
        return (
            self.pod_valid,
            self.pod_open_sig,
            self.pod_core,
            self.pod_host,
            self.pod_host_in_base,
            self.pod_open_host,
            self.pod_req,
            self.join_table,
            self.frontiers,
            self.daemon,
        )


def usable_capacity(
    instance_types: Sequence[InstanceType], extra_axes: Sequence[str]
) -> np.ndarray:
    """[T, R] allocatable minus overhead — what requests compare against
    (reference: requirements.go:68-80 merges requests+overhead vs capacity;
    subtracting overhead once per type is the same inequality). Scaled to the
    exact-integer device units (resources.AXIS_SCALES)."""
    out = np.zeros((len(instance_types), res.NUM_RESOURCE_AXES + len(extra_axes)), np.float32)
    for i, it in enumerate(instance_types):
        out[i] = res.to_scaled_vector(it.resources, extra_axes) - res.to_scaled_vector(
            it.overhead, extra_axes
        )
    return out


def encode(
    constraints: Constraints,
    instance_types: Sequence[InstanceType],
    pods: Sequence[Pod],
    daemon: Dict[str, float],
) -> EncodedBatch:
    """Build the dense solve request. ``instance_types`` must already be
    price-sorted and ``pods`` FFD-sorted; topology decisions must already be
    injected (both shared with the FFD path). Raises SignatureOverflow when
    constraint diversity exceeds the closure cap (caller falls back to FFD).
    """
    # resource axes: reserved + any extended resources in play (pod requests
    # via the memoized accessor — a fresh resource_requests() per pod was a
    # measurable slice of encode at 10k pods)
    extras = res.collect_extra_axes(
        [it.resources for it in instance_types]
        + [it.overhead for it in instance_types]
        + [res.requests_for_pods(p) for p in pods]
        + [daemon]
    )
    axes = extras  # extra axis names appended after the reserved block
    usable = usable_capacity(instance_types, axes)
    table = SignatureTable(constraints, instance_types, usable, axes)

    # canonicalize pods; intern cores + hostnames
    cores: List[Core] = []
    core_ids: Dict[Core, int] = {}
    hostnames: List[str] = []
    host_ids: Dict[str, int] = {}

    n = len(pods)
    pod_core = np.zeros(n, np.int32)
    pod_host = np.full(n, -1, np.int32)
    pod_host_in_base = np.zeros(n, bool)
    pod_open_host = np.full(n, -1, np.int32)
    pod_req = np.zeros((n, usable.shape[1]), np.float32)
    base_has_hostname = constraints.requirements.has(lbl.HOSTNAME)

    req_cache: Dict[Tuple, np.ndarray] = {}
    for i, pod in enumerate(pods):
        core, hostname = pod_core_and_hostname(pod)
        cid = core_ids.get(core)
        if cid is None:
            cid = len(cores)
            core_ids[core] = cid
            cores.append(core)
        pod_core[i] = cid
        if hostname is not None:
            hid = host_ids.get(hostname)
            if hid is None:
                hid = len(hostnames)
                host_ids[hostname] = hid
                hostnames.append(hostname)
            pod_host[i] = hid
            in_base = table.hostname_in_base(hostname)
            pod_host_in_base[i] = in_base
            # node hostname state if this pod opens a node: joinable (h) when
            # the merged hostname set stays non-empty ({h}), poisoned (-2)
            # when the base domains exclude h (set intersects to ∅ — later
            # hostname pods can never match, reference requirements.go:175)
            pod_open_host[i] = hid if (in_base or not base_has_hostname) else -2
        requests = res.requests_for_pods(pod)
        rkey = tuple(sorted(requests.items()))
        vec = req_cache.get(rkey)
        if vec is None:
            vec = res.to_scaled_vector(requests, axes)
            req_cache[rkey] = vec
        pod_req[i] = vec

    # signature closure: process every signature against every core until no
    # new signatures appear (table.join interns joined signatures, growing
    # table.signatures; raises SignatureOverflow past the cap)
    open_sig_by_core = np.array([table.open_signature(c) for c in cores], np.int32)
    processed = 0
    while processed < len(table.signatures):
        sid = processed
        processed += 1
        for core in cores:
            table.join(sid, core)

    S = len(table.signatures)
    C = max(len(cores), 1)  # gathers need a non-empty core axis
    join_table = np.full((S, C), -1, np.int32)
    for (sid, core), out in table._join_cache.items():
        join_table[sid, core_ids[core]] = out

    f_max = max((len(s.frontier) for s in table.signatures), default=1) or 1
    R = usable.shape[1]
    frontiers = np.full((S, f_max, R), FRONTIER_PAD, np.float32)
    for s in table.signatures:
        if len(s.frontier):
            frontiers[s.sig_id, : len(s.frontier)] = s.frontier

    daemon_vec = res.to_scaled_vector(daemon, axes)

    # pad pods to bucket
    p_pad = _bucket(max(n, 1))
    pad = p_pad - n

    def pad1(a, fill):
        return np.concatenate([a, np.full((pad,) + a.shape[1:], fill, a.dtype)]) if pad else a

    return EncodedBatch(
        pods=list(pods),
        n_pods=n,
        pod_valid=pad1(np.ones(n, bool), False),
        pod_open_sig=pad1(open_sig_by_core[pod_core], 0),
        pod_core=pad1(pod_core, 0),
        pod_host=pad1(pod_host, -1),
        pod_host_in_base=pad1(pod_host_in_base, False),
        pod_open_host=pad1(pod_open_host, -1),
        pod_req=pad1(pod_req, 0.0),
        join_table=join_table,
        frontiers=frontiers,
        daemon=daemon_vec,
        table=table,
        cores=cores,
        hostnames=hostnames,
        axes=axes,
        usable=usable,
    )
