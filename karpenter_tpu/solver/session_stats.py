"""Process-wide catalog-residency accounting for the solver transport.

The v3 transport makes catalog-side tensors *resident* on the device side —
pinned by the sidecar per session (``service.SolverService``) or by the
in-process invariants cache (``fused.DeviceInvariants``). Both funnel their
hit/miss/upload/eviction events through this module so one gauge answers the
question the BENCH acceptance bar asks: *does the steady-state solve ship
catalog bytes, or only pod deltas?*

Semantics:

- a **hit** = a solve served against already-resident catalog tensors (no
  catalog bytes crossed the wire/PCIe for it);
- a **miss** = the solve found its catalog non-resident (fingerprint unknown,
  evicted, or a restarted sidecar) and an upload had to happen;
- ``solver_session_catalog_hit_rate`` = hits / (hits + misses) since process
  start (or the last ``reset()`` — bench resets after warmup so the reported
  rate is the steady-state one).

Counters are process-global because the sidecar and the in-process fused
path never run in the same solve: a configured sidecar owns the device
(``backend._fused_route`` yields to it), so the stream of events is one
transport's story at a time.
"""

from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_hits = 0  # guarded-by: _lock
_misses = 0  # guarded-by: _lock


def record(hit: bool) -> None:
    """One solve consulted the resident catalog: hit (tensors already on
    device) or miss (an upload had to happen first)."""
    global _hits, _misses
    from karpenter_tpu import metrics

    with _lock:
        if hit:
            _hits += 1
        else:
            _misses += 1
        # the gauge is set under the lock so two racing records cannot
        # publish their snapshots out of order and leave a stale value
        metrics.SOLVER_SESSION_HIT_RATE.set(_hits / (_hits + _misses))
    # the online SLO engine judges `session.catalog_hit_rate` from the
    # same event stream (outside the lock: the engine has its own)
    from karpenter_tpu import obs

    eng = obs.slo_engine()
    if eng is not None:
        eng.record_ratio("session.catalog_hit_rate", hit)


def record_upload() -> None:
    """Catalog-side tensors crossed to the device (OpenSession upload or a
    DeviceInvariants device_put)."""
    from karpenter_tpu import metrics

    metrics.SOLVER_SESSION_UPLOADS.inc()


def record_eviction(n: int = 1) -> None:
    """Resident catalog entries dropped (LRU pressure or TTL expiry)."""
    from karpenter_tpu import metrics

    metrics.SOLVER_SESSION_EVICTIONS.inc(n)


def snapshot() -> Dict[str, float]:
    """Bench surface: the counters plus the derived hit rate."""
    with _lock:
        hits, misses = _hits, _misses
    total = hits + misses
    return {
        "hits": hits,
        "misses": misses,
        "hit_rate": (hits / total) if total else None,
    }


def reset() -> None:
    """Bench/tests: restart the window (e.g. after warmup, so the reported
    rate is the steady state's, not the cold start's)."""
    global _hits, _misses
    with _lock:
        _hits = 0
        _misses = 0
