"""The jitted packing kernel.

Exact first-fit in FFD order as a ``lax.scan`` over pods. Per-node carry
state is {signature id, hostname id, resource total}; the accept test per
(pod, node) is:

    join_table[node_sig, pod_core] ≥ 0          (requirements compatibility)
  ∧ hostname fields agree                       (single-value hostname join)
  ∧ ∃ frontier row f: total + pod_req ≤ f       (∃ surviving type that fits)

which is the tensorized form of ``scheduling/node.go:46-66``. ``argmax`` over
the ok-mask picks the *first* fitting node, preserving first-fit semantics.

Shapes are static per (P, S, C, F, R) bucket; no data-dependent control flow
— unschedulable pods are masked, not branched on.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


# pods unrolled per scan step (amortizes per-step dispatch latency)
CHUNK = 8


class PackResult(NamedTuple):
    assignment: jnp.ndarray  # [P] i32 node index, -1 = unschedulable/padding
    node_sig: jnp.ndarray  # [N] i32 final signature per node, -1 = unopened
    node_host: jnp.ndarray  # [N] i32
    node_req: jnp.ndarray  # [N, R] f32 total requests (incl. daemon)
    n_nodes: jnp.ndarray  # scalar i32


@partial(jax.jit, static_argnames=("n_max",))
def pack(
    pod_valid,  # [P] bool
    pod_open_sig,  # [P] i32
    pod_core,  # [P] i32
    pod_host,  # [P] i32, -1 = no hostname requirement
    pod_host_in_base,  # [P] bool — hostname ∈ base constraint domains
    pod_open_host,  # [P] i32 — node hostname state when opened by this pod
    #   (-1 none, h ≥ 0 joinable, -2 poisoned: hostname set became empty)
    pod_req,  # [P, R] f32
    join_table,  # [S, C] i32
    frontiers,  # [S, F, R] f32
    daemon,  # [R] f32
    n_max: int,
) -> PackResult:
    P, R = pod_req.shape

    node_sig0 = jnp.full((n_max,), -1, jnp.int32)
    node_host0 = jnp.full((n_max,), -1, jnp.int32)
    node_req0 = jnp.zeros((n_max, R), jnp.float32)
    count0 = jnp.zeros((), jnp.int32)

    def step(carry, x):
        node_sig, node_host, node_req, count = carry
        valid, open_sig, core, host, host_in_base, open_host, req = x

        is_open = node_sig >= 0
        j = join_table[jnp.clip(node_sig, 0), core]  # [N]
        ok_sig = (j >= 0) & is_open
        # hostname join: pods without a hostname requirement always pass; a
        # hostname pod joins a node whose hostname is unset only if its value
        # is in the base domains (otherwise the intersection with the node's
        # current hostname set would be empty with no escape hatch)
        ok_host = (host < 0) | ((node_host == -1) & host_in_base) | (node_host == host)
        new_req = node_req + req[None, :]  # [N, R]
        fr = frontiers[jnp.clip(j, 0)]  # [N, F, R] gather from small table
        fits = jnp.any(jnp.all(new_req[:, None, :] <= fr, axis=-1), axis=-1)
        ok = ok_sig & ok_host & fits

        any_ok = jnp.any(ok)
        first_ok = jnp.argmax(ok)  # first open node that accepts → first-fit

        open_req = daemon + req
        open_fits = jnp.any(jnp.all(open_req[None, :] <= frontiers[open_sig], axis=-1))

        # node table full → cannot open; the caller detects saturation
        # (n_nodes == n_max with unscheduled pods) and retries with a larger
        # table, so a conservative n_max stays sound
        can_open = open_fits & (count < node_sig.shape[0])
        schedulable = valid & (any_ok | can_open)
        target = jnp.where(any_ok, first_ok, count)

        upd_sig = jnp.where(any_ok, j[first_ok], open_sig)
        upd_host = jnp.where(
            any_ok,
            jnp.where(host >= 0, host, node_host[first_ok]),
            open_host,
        )
        upd_req = jnp.where(any_ok, new_req[first_ok], open_req)

        # masked scatter: write target slot only when the pod schedules
        node_sig = node_sig.at[target].set(jnp.where(schedulable, upd_sig, node_sig[target]))
        node_host = node_host.at[target].set(jnp.where(schedulable, upd_host, node_host[target]))
        node_req = node_req.at[target].set(jnp.where(schedulable, upd_req, node_req[target]))
        count = count + jnp.where(schedulable & ~any_ok, 1, 0).astype(jnp.int32)

        assignment = jnp.where(schedulable, target, -1).astype(jnp.int32)
        return (node_sig, node_host, node_req, count), assignment

    # Chunked scan: the per-step body is tiny, so a 10k-pod scan is dominated
    # by per-step dispatch latency. Unrolling CHUNK pods inside each step
    # (still strictly sequential — XLA fuses the unrolled bodies into one
    # kernel per step) cuts the step count CHUNK×. P is always a multiple of
    # CHUNK because encode buckets P to powers of two ≥ 64.
    xs = (pod_valid, pod_open_sig, pod_core, pod_host, pod_host_in_base,
          pod_open_host, pod_req)
    if P % CHUNK == 0 and P >= CHUNK:
        xs_chunked = tuple(a.reshape((P // CHUNK, CHUNK) + a.shape[1:]) for a in xs)

        def chunk_step(carry, chunk):
            outs = []
            for k in range(CHUNK):
                carry, out = step(carry, tuple(a[k] for a in chunk))
                outs.append(out)
            return carry, jnp.stack(outs)

        (node_sig, node_host, node_req, count), assignment = lax.scan(
            chunk_step, (node_sig0, node_host0, node_req0, count0), xs_chunked
        )
        assignment = assignment.reshape(P)
    else:
        (node_sig, node_host, node_req, count), assignment = lax.scan(
            step, (node_sig0, node_host0, node_req0, count0), xs
        )
    return PackResult(assignment, node_sig, node_host, node_req, count)


@jax.jit
def fuse_result(result: PackResult) -> jnp.ndarray:
    """Flatten the PackResult into ONE i32 buffer on device (f32 totals are
    bitcast, not converted) so the host needs a single transfer — per-array
    fetches each pay full round-trip latency on a tunneled TPU."""
    parts = [
        result.assignment.reshape(-1),
        result.node_sig.reshape(-1),
        result.node_host.reshape(-1),
        lax.bitcast_convert_type(result.node_req, jnp.int32).reshape(-1),
        result.n_nodes.reshape(-1).astype(jnp.int32),
    ]
    return jnp.concatenate(parts)


def split_result(buf, p: int, n: int, r: int) -> PackResult:
    """Host-side inverse of ``fuse_result`` (numpy): ``p`` pods scanned,
    ``n`` node slots, ``r`` resource axes."""
    import numpy as np

    buf = np.asarray(buf)
    assignment = buf[:p]
    node_sig = buf[p : p + n]
    node_host = buf[p + n : p + 2 * n]
    node_req = buf[p + 2 * n : p + 2 * n + n * r].view(np.float32).reshape(n, r)
    n_nodes = buf[p + 2 * n + n * r]
    return PackResult(assignment, node_sig, node_host, node_req, n_nodes)


@partial(jax.jit, static_argnames=())
def cheapest_fitting_type(
    node_req,  # [N, R]
    node_sig,  # [N]
    sig_type_mask,  # [S, T] bool
    usable,  # [T, R]
):
    """Post-pack, one shot: for every node, the index of the cheapest
    instance type that survives its signature and fits its total. Types are
    price-sorted, so "cheapest" = first True. Returns [N] i32, -1 for
    unopened nodes."""
    mask = sig_type_mask[jnp.clip(node_sig, 0)]  # [N, T]
    fits = jnp.all(node_req[:, None, :] <= usable[None, :, :], axis=-1)  # [N, T]
    ok = mask & fits
    idx = jnp.argmax(ok, axis=-1)
    has = jnp.any(ok, axis=-1) & (node_sig >= 0)
    return jnp.where(has, idx, -1).astype(jnp.int32)
