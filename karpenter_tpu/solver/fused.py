"""Single-dispatch device solve: compact upload → pack → typemask → one buffer.

The r2 benchmark showed the non-RTT device cost of a 10k-pod solve was
dominated by *transfers*, not compute: ten float/int arrays (~620KB) shipped
per solve over a ~30MB/s tunnel, two separate jit dispatches, and a
multi-array fetch. This module collapses the device round trip to:

- ONE compact per-solve upload: a ``[6, P] int16`` pod table (ids fit i16 by
  construction — see ``ids_fit``) plus the ``[U, R] float32`` unique request
  vectors (a 10k-pod batch has dozens of distinct request shapes, not 10k);
- solve-invariant arrays (join table, frontiers, daemon, signature→type
  masks, usable capacities) kept DEVICE-RESIDENT across batches in a small
  content-keyed cache (``DeviceInvariants``);
- ONE jitted dispatch that unpacks, gathers ``pod_req = uniq_req[req_id]``
  on device, runs the packing kernel (Pallas on TPU, lax.scan elsewhere),
  computes each node's surviving-instance-type bitmask (the old host-side
  ``[N, T, R]`` broadcast in decode), and flattens everything — including
  the f32 totals, bitcast — into ONE int32 buffer for a single fetch.

Saturation retry (node table full with unscheduled pods) stays host-driven
exactly as in ``backend._pack_device`` (the re-dispatch runs in the finish
phase, off the solve lock — docs/solver-transport.md).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import numpy as np

# pod scalar rows in the packed [4, P] i16 table. open_sig and open_host
# are DERIVED on device: open_sig = open_sig_by_core[core] (a tiny [C]
# array shipped alongside), open_host = host when joinable (host in base
# domains, or no base hostname requirement) else the poison value -2 —
# exactly encode's host-side formulas.
ROW_FLAGS = 0  # bit0 = valid, bit1 = host_in_base
ROW_CORE = 1
ROW_HOST = 2
ROW_REQ_ID = 3

I16_MAX = 32766


def ids_fit(batch) -> bool:
    """All interned ids fit int16 (hostname ids are the only axis that can
    realistically approach the cap, at 32k+ distinct hostnames in one
    batch — the caller falls back to the uncompacted path)."""
    return (
        len(batch.hostnames) < I16_MAX
        and len(batch.cores) < I16_MAX
        and batch.uniq_req is not None
        and batch.uniq_req.shape[0] < I16_MAX
        and len(batch.signatures) < I16_MAX
    )


def pad_uniq_req(uniq: np.ndarray) -> np.ndarray:
    """Pad the unique-request matrix to a power-of-two row count (min 16)
    so a drifting unique-request count doesn't recompile the fused
    dispatch. The padding rows are zeros, like the batch's own final
    all-zero row backing the padding pods."""
    u_pad = 16
    while u_pad < uniq.shape[0]:
        u_pad *= 2
    if u_pad != uniq.shape[0]:
        uniq = np.vstack(
            [uniq, np.zeros((u_pad - uniq.shape[0], uniq.shape[1]), np.float32)]
        )
    return uniq


def pack_pod_table(batch):
    """The per-solve compact upload: ([4, P] i16 pod table,
    [C] i16 per-core open signatures, scalar base_has_hostname i32)."""
    flags = batch.pod_valid.astype(np.int16) | (
        batch.pod_host_in_base.astype(np.int16) << 1
    )
    tab = np.stack(
        [
            flags,
            batch.pod_core.astype(np.int16),
            batch.pod_host.astype(np.int16),
            batch.pod_req_id.astype(np.int16),
        ]
    )
    open_by_core = np.asarray(batch.open_sig_by_core).astype(np.int16)
    bhh = np.array([1 if batch.base_has_hostname else 0], np.int32)
    return tab, open_by_core, bhh


class DeviceInvariants:
    """Content-keyed LRU of device-resident solve invariants.

    A provisioner's consecutive batches share (signature table, closure,
    catalog) — re-uploading the join table, frontiers, type masks and usable
    capacities per solve wastes tunnel bandwidth on bytes that did not
    change. Keyed by content digest, so a changed catalog or closure simply
    misses. ``get_v2`` additionally holds the v2 kernel's per-core join
    tables (frontJ/compatJ/jvals — by far the largest arrays of a diverse
    solve) device-resident under the same digest."""

    MAX_ENTRIES = 4

    def __init__(self):
        import threading

        self._cache: "Dict[bytes, tuple]" = {}  # guarded-by: self._lock
        self._cache_v2: "Dict[bytes, tuple]" = {}  # guarded-by: self._lock
        self._order: list = []  # guarded-by: self._lock
        # the router's device shadow probe calls get()/get_v2() from its
        # own thread while a production solve may be cold-starting the
        # device path concurrently — the LRU list mutation must not race
        self._lock = threading.Lock()

    def _digest(self, batch) -> bytes:
        import hashlib

        mask = batch.type_mask_matrix()
        h = hashlib.blake2b(digest_size=16)
        h.update(np.ascontiguousarray(batch.join_table).tobytes())
        h.update(np.ascontiguousarray(batch.frontiers).tobytes())
        h.update(np.ascontiguousarray(batch.daemon).tobytes())
        h.update(np.ascontiguousarray(mask).tobytes())
        h.update(np.ascontiguousarray(batch.usable).tobytes())
        return h.digest()

    def _touch_locked(self, key: bytes) -> None:
        from karpenter_tpu.solver import session_stats

        # LRU, not FIFO: interleaving invariant sets (several provisioners
        # on one scheduler) must not evict the hot entry
        if key in self._order:
            self._order.remove(key)
        self._order.append(key)
        while len(self._order) > self.MAX_ENTRIES:
            dead = self._order.pop(0)
            self._cache.pop(dead, None)
            self._cache_v2.pop(dead, None)
            session_stats.record_eviction()

    def get(self, batch, record: bool = True):
        """``record=False`` keeps this lookup out of the session-residency
        stats — shadow probes and saturation re-dispatches are not solves,
        and counting them would inflate the hit rate the bench's ≥0.95
        acceptance bar reads."""
        from karpenter_tpu.solver import session_stats

        key = self._digest(batch)
        with self._lock:
            hit = self._cache.get(key)
        if record:
            session_stats.record(hit is not None)
        if hit is None:
            session_stats.record_upload()  # a real transfer, whoever asked
            hit = tuple(
                jax.device_put(a)
                for a in (
                    batch.join_table.astype(np.int32),
                    batch.frontiers.astype(np.float32),
                    batch.daemon.astype(np.float32),
                    batch.type_mask_matrix().astype(bool),
                    batch.usable.astype(np.float32),
                )
            )
        with self._lock:
            self._cache[key] = hit
            self._touch_locked(key)
        return hit

    def get_v2(self, batch, record: bool = True):
        """(front_j, compat_j, jvals, frontiers, daemon, mask, usable) on
        device — the v2 route's per-core tables computed once per closure.
        ``record`` as in :meth:`get`."""
        from karpenter_tpu.solver import session_stats

        key = self._digest(batch)
        with self._lock:
            hit = self._cache_v2.get(key)
        if record:
            session_stats.record(hit is not None)
        if hit is None:
            session_stats.record_upload()  # a real transfer, whoever asked
            from karpenter_tpu.solver.pallas_kernel_v2 import _precompute

            front_j, compat_j, jvals, _ = _precompute(
                np.asarray(batch.join_table), np.asarray(batch.frontiers, np.float32)
            )
            hit = tuple(
                jax.device_put(a)
                for a in (
                    front_j, compat_j, jvals,
                    batch.frontiers.astype(np.float32),
                    batch.daemon.astype(np.float32),
                    batch.type_mask_matrix().astype(bool),
                    batch.usable.astype(np.float32),
                )
            )
        with self._lock:
            self._cache_v2[key] = hit
            self._touch_locked(key)
        return hit


class PodResidency:
    """Device-resident pod-side upload (docs/delta-encoding.md § device).

    ``DeviceInvariants`` already pins the catalog side; this is its
    pod-side twin for delta rounds. The host ``ResidentEncoder`` returns
    the SAME ``EncodedBatch`` object on a no-churn round, so object
    identity is the residency key: the entry holds the batch ref (pinning
    the id) plus the device buffers of its compact upload, and a
    steady-state round skips ``pack_pod_table`` AND the transfer entirely.
    A churn round whose pod-table shape survived patches the resident
    table in place — the donated buffer lets XLA reuse the allocation
    instead of materializing a second [4, P] table (SNIPPETS.md
    ``donate_argnums`` idiom; a no-op on backends without donation, where
    it degrades to copy-and-patch).

    One entry, not an LRU: interleaving provisioners churn the batch
    identity every round anyway, and a stale entry costs exactly one
    re-upload — the miss path IS the pre-delta behavior."""

    # past a quarter of the columns the full upload is barely bigger
    PATCH_MAX_COL_FRACTION = 4

    def __init__(self):
        import threading

        self._entry = None  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.stats = {"reused": 0, "patched": 0, "uploaded": 0}  # guarded-by: self._lock
        # donation only where the backend implements it — the CPU rig
        # would warn per compile and copy anyway
        donate = (0,) if jax.default_backend() != "cpu" else ()
        self._patch_cols = jax.jit(
            lambda tab, idx, cols: tab.at[:, idx].set(cols),
            donate_argnums=donate,
        )

    def _count(self, what: str) -> None:
        with self._lock:
            self.stats[what] += 1
        if what != "uploaded":
            try:
                from karpenter_tpu import metrics

                metrics.SOLVER_DELTA_APPLIED.labels(path="device").inc()
            except Exception:
                pass  # trimmed registries

    def _publish_bytes(self, devs) -> None:
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_DELTA_RESIDENT_BYTES.labels(side="device").set(
                sum(int(getattr(a, "nbytes", 0) or 0) for a in devs)
            )
        except Exception:
            pass  # trimmed registries

    def get(self, batch):
        """``(pod_tab, open_by_core, bhh, uniq)`` as device arrays,
        reusing or patching the resident upload when ``batch`` allows."""
        with self._lock:
            entry = self._entry
        if entry is not None and entry[0] is batch:
            self._count("reused")
            return entry[1]
        tab, open_by_core, bhh = pack_pod_table(batch)
        uniq = pad_uniq_req(batch.uniq_req)
        host = (tab, open_by_core, bhh, uniq)
        devs = None
        if entry is not None:
            _, (tab_d, obc_d, bhh_d, uniq_d), prev = entry
            ptab, pobc, pbhh, puniq = prev
            if ptab.shape == tab.shape:
                changed = np.flatnonzero((ptab != tab).any(axis=0)).astype(np.int32)
                if (
                    0 < changed.size
                    <= max(1, tab.shape[1] // self.PATCH_MAX_COL_FRACTION)
                ):
                    # in-place column patch; the donated prior-round table
                    # is dead after this (the entry swap below retires it)
                    tab_d = self._patch_cols(tab_d, changed, tab[:, changed])
                elif changed.size:
                    tab_d = jax.device_put(tab)
                side_ok = (
                    np.array_equal(pobc, open_by_core)
                    and np.array_equal(pbhh, bhh)
                    and np.array_equal(puniq, uniq)
                )
                devs = (
                    tab_d,
                    obc_d if side_ok else jax.device_put(open_by_core),
                    bhh_d if side_ok else jax.device_put(bhh),
                    uniq_d if side_ok else jax.device_put(uniq),
                )
                self._count("patched" if changed.size else "reused")
        if devs is None:
            devs = tuple(jax.device_put(a) for a in host)
            self._count("uploaded")
        with self._lock:
            self._entry = (batch, devs, host)
        self._publish_bytes(devs)
        return devs


def _pack_typebits(ok, T32):
    """[N, T] bool → [N, T32] i32 bit-packed (bit t%32 of word t//32)."""
    import jax.numpy as jnp

    N = ok.shape[0]
    okp = ok.astype(jnp.int32).reshape(N, T32, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)).astype(jnp.uint32)
    return (
        (okp.astype(jnp.uint32) * weights[None, None, :])
        .sum(axis=-1, dtype=jnp.uint32)
        .astype(jnp.int32)
    )


def _unpack_pods(pod_tab, open_by_core, bhh, uniq_req):
    """In-jit inverse of ``pack_pod_table``: the per-pod kernel inputs from
    the compact i16 upload (encode's host-side formulas, on device)."""
    import jax.numpy as jnp

    tab = pod_tab.astype(jnp.int32)
    pod_valid = (tab[ROW_FLAGS] & 1) != 0
    pod_host_in_base = (tab[ROW_FLAGS] & 2) != 0
    pod_core = tab[ROW_CORE]
    pod_host = tab[ROW_HOST]
    pod_open_sig = open_by_core.astype(jnp.int32)[pod_core]
    # joinable hostname state when the merged hostname set stays non-empty,
    # poisoned (-2) otherwise
    joinable = pod_host_in_base | (bhh[0] == 0)
    pod_open_host = jnp.where(
        pod_host >= 0, jnp.where(joinable, pod_host, -2), -1
    ).astype(jnp.int32)
    pod_req = uniq_req[tab[ROW_REQ_ID]]  # [P, R] gather on device
    return (
        pod_valid, pod_open_sig, pod_core, pod_host, pod_host_in_base,
        pod_open_host, pod_req,
    )


def _finalize(result, sig_type_mask, usable):
    """Surviving-type bitmask per node (decode's old host-side [N, T, R]
    broadcast) + everything flattened into ONE int32 buffer for one fetch."""
    import jax.numpy as jnp
    from jax import lax

    T = usable.shape[0]
    T32 = (T + 31) // 32
    pad_t = T32 * 32 - T
    mask = sig_type_mask[jnp.clip(result.node_sig, 0)]  # [N, T]
    fits = jnp.all(result.node_req[:, None, :] <= usable[None, :, :], axis=-1)
    ok = mask & fits & (result.node_sig >= 0)[:, None]
    if pad_t:
        ok = jnp.pad(ok, ((0, 0), (0, pad_t)))
    typebits = _pack_typebits(ok, T32)  # [N, T32] i32

    parts = [
        result.assignment.reshape(-1),
        result.node_sig.reshape(-1),
        result.node_host.reshape(-1),
        lax.bitcast_convert_type(result.node_req, jnp.int32).reshape(-1),
        typebits.reshape(-1),
        result.n_nodes.reshape(-1).astype(jnp.int32),
    ]
    return jnp.concatenate(parts)


@partial(jax.jit, static_argnames=("n_max", "kernel"))
def fused_solve(
    pod_tab,  # [4, P] i16
    open_by_core,  # [C] i16 — per-core fresh-node signatures
    bhh,  # [1] i32 — base constraints carry a hostname requirement
    uniq_req,  # [U, R] f32 (last row zeros = padding pods)
    join_table,  # [S, C] i32 (device-resident)
    frontiers,  # [S, F, R] f32 (device-resident)
    daemon,  # [R] f32 (device-resident)
    sig_type_mask,  # [S, T] bool (device-resident)
    usable,  # [T, R] f32 (device-resident)
    n_max: int,
    kernel: str,  # "pallas" | "scan"
):
    from karpenter_tpu.solver import kernel as _k

    unpacked = _unpack_pods(pod_tab, open_by_core, bhh, uniq_req)
    args = unpacked + (join_table, frontiers, daemon)
    if kernel == "pallas":
        from karpenter_tpu.solver.pallas_kernel import pack_pallas

        result = pack_pallas(*args, n_max=n_max)
    else:
        result = _k.pack(*args, n_max=n_max)
    return _finalize(result, sig_type_mask, usable)


@partial(jax.jit, static_argnames=("n_max", "F", "R"))
def fused_solve_v2(
    pod_tab,  # [4, P] i16
    open_by_core,  # [C] i16
    bhh,  # [1] i32
    uniq_req,  # [U, R] f32
    front_j,  # [C, FRp, S_pad] f32 (device-resident; pallas_kernel_v2._precompute)
    compat_j,  # [C, 8, S_pad] f32 (device-resident)
    jvals,  # [C, 8, S_pad] f32 (device-resident)
    frontiers,  # [S, F, R] f32 (device-resident; open-fits derivation)
    daemon,  # [R] f32 (device-resident)
    sig_type_mask,  # [S, T] bool (device-resident)
    usable,  # [T, R] f32 (device-resident)
    n_max: int,
    F: int,
    R: int,
):
    """The fused dispatch through the v2 (matmul-gather) kernel: the route
    for constraint-diverse batches past the v1 unroll budget. Same one
    compact upload / one buffer back; the v2 host precompute
    (``_open_fits_host``) is derived ON DEVICE and the per-core join tables
    ride the invariants cache."""
    import jax.numpy as jnp

    from karpenter_tpu.solver import kernel as _k
    from karpenter_tpu.solver.pallas_kernel_v2 import _pack_v2_call

    (pod_valid, pod_open_sig, pod_core, pod_host, pod_host_in_base,
     pod_open_host, pod_req) = _unpack_pods(pod_tab, open_by_core, bhh, uniq_req)
    # _open_fits_host's formula, in-jit: daemon+req fits ANY frontier of
    # the pod's open signature (open sigs are always valid indices)
    need = pod_req + daemon[None, :]
    limits = frontiers[pod_open_sig]  # [P, F, R]
    open_fits = jnp.any(jnp.all(need[:, None, :] <= limits, axis=-1), axis=-1)
    pod_scal = jnp.stack([
        pod_valid.astype(jnp.int32), pod_open_sig, pod_core, pod_host,
        pod_host_in_base.astype(jnp.int32), pod_open_host,
    ])
    assignment, node_sig, node_host, node_req_t, count = _pack_v2_call(
        pod_scal,
        pod_req.T,
        front_j,
        compat_j,
        jvals,
        open_fits.reshape(1, -1).astype(jnp.int32),
        daemon.reshape(R, 1),
        n_max=n_max,
        F=F,
        R=R,
    )
    result = _k.PackResult(
        assignment=assignment[0],
        node_sig=node_sig[0, :n_max],
        node_host=node_host[0, :n_max],
        node_req=node_req_t[:, :n_max].T,
        n_nodes=count[0, 0],
    )
    return _finalize(result, sig_type_mask, usable)


def split_fused(buf, p: int, n: int, r: int, t: int):
    """Host-side inverse of ``fused_solve``'s flat buffer. Returns
    (PackResult, typemask[N, T] bool)."""
    from karpenter_tpu.solver.kernel import PackResult

    buf = np.asarray(buf)
    t32 = (t + 31) // 32
    o = 0
    assignment = buf[o : o + p]; o += p
    node_sig = buf[o : o + n]; o += n
    node_host = buf[o : o + n]; o += n
    node_req = buf[o : o + n * r].view(np.float32).reshape(n, r); o += n * r
    typebits = buf[o : o + n * t32].view(np.uint32).reshape(n, t32); o += n * t32
    n_nodes = buf[o]
    # unpack bits → [N, T] bool
    shifts = np.arange(32, dtype=np.uint32)
    bits = (typebits[:, :, None] >> shifts[None, None, :]) & 1
    typemask = bits.reshape(n, t32 * 32)[:, :t].astype(bool)
    return (
        PackResult(assignment, node_sig, node_host, node_req, n_nodes),
        typemask,
    )
