"""Minimal-move matching + disruption cost for whole-cluster re-pack.

The consolidation controller already solves the *entire* candidate set
through the normal solver routes (native/device/pool/streamed — the
proposal inherits bit-exact route parity from the scheduler, so no route
logic lives here). What the raw proposal lacks is the robustness
objective: the solver prices CAPACITY, not CHURN. A proposed packing that
reshuffles every pod to save one node is a worse wave than one that
leaves most nodes untouched — every move is an eviction, a recreation,
and a window where the workload runs below replicas.

This module turns a priced proposal into a minimal-move wave:

- :func:`minimal_move_match` pairs proposed virtual nodes with existing
  candidate nodes that already hold exactly that packing. A matched node
  is KEPT (zero moves — it is its own replacement); only the unmatched
  remainder is retired and launched. The match key is (chosen instance
  type, resident pod set), so correctness does not depend on solver
  ordering.

- :func:`disruption_cost` scores each retired node so waves drain the
  cheapest disruption first: scale by the node's hourly price, discount
  capacity the cloud is likely to reclaim anyway (the
  ``poll_disruptions``-fed interruption risk — a spot node under active
  reclaim pressure is nearly free to retire voluntarily), and charge per
  resident pod for the moves themselves.

Everything here is deterministic host-side arithmetic over the solver's
output — it runs identically whichever route produced the proposal.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, Pod
from karpenter_tpu.scheduling.ffd import VirtualNode

# Each pod move costs this many $/hr-equivalents in the disruption score:
# enough that a node with many pods outranks a slightly pricier empty one,
# small enough that price still dominates across instance-type tiers.
MOVE_COST = 0.01


@dataclass
class RepackMatch:
    """The minimal-move view of one proposal: ``keep`` nodes already hold
    their proposed packing verbatim; ``retire`` nodes drain (their pods
    are the ``moves``); ``launch`` virtual nodes are the capacity that
    must actually be built."""

    keep: List[Node] = field(default_factory=list)
    retire: List[Node] = field(default_factory=list)
    launch: List[VirtualNode] = field(default_factory=list)
    moves: List[Pod] = field(default_factory=list)


def _pod_key(p: Pod) -> Tuple[str, str]:
    return (p.metadata.namespace, p.metadata.name)


def _vnode_signature(v: VirtualNode) -> Tuple[str, frozenset]:
    itype = v.instance_type_options[0].name if v.instance_type_options else ""
    return (itype, frozenset(_pod_key(p) for p in v.pods))


def minimal_move_match(
    nodes: List[Node],
    node_pods: Dict[str, List[Pod]],
    proposed: List[VirtualNode],
) -> RepackMatch:
    """Pair proposed virtual nodes with existing candidates that already
    hold exactly that packing (same chosen instance type, same resident
    pod set). ``node_pods`` maps node name -> that node's reschedulable
    pods (the same set the plan fed the solver). Matching is greedy over
    a signature index — O(nodes + proposed) — and deterministic: ties
    between identical nodes break by node name."""
    match = RepackMatch()
    # signature -> existing nodes holding it, name-ordered for determinism
    by_sig: Dict[Tuple[str, frozenset], List[Node]] = {}
    for n in sorted(nodes, key=lambda n: n.metadata.name):
        sig = (
            n.metadata.labels.get(lbl.INSTANCE_TYPE, ""),
            frozenset(_pod_key(p) for p in node_pods.get(n.metadata.name, [])),
        )
        by_sig.setdefault(sig, []).append(n)
    for v in proposed:
        pool = by_sig.get(_vnode_signature(v))
        if pool:
            match.keep.append(pool.pop(0))
        else:
            match.launch.append(v)
    kept = {n.metadata.name for n in match.keep}
    for n in nodes:
        if n.metadata.name not in kept:
            match.retire.append(n)
            match.moves.extend(node_pods.get(n.metadata.name, []))
    return match


def disruption_cost(
    node: Node, node_pods: List[Pod], price: float, risk: float
) -> float:
    """The per-node disruption-cost dimension: what retiring this node
    costs in availability terms. Lower = retire first. ``risk`` is the
    interruption-risk score in [0, 1] for the node's (capacity_type,
    zone) — high-risk capacity is discounted because the cloud was going
    to take it anyway, so the voluntary wave should spend its budget
    there."""
    risk = min(max(risk, 0.0), 1.0)
    return max(price, 0.0) * (1.0 - risk) + MOVE_COST * len(node_pods)


def order_retirement(
    retire: List[Node],
    node_pods: Dict[str, List[Pod]],
    price_by_type: Dict[str, float],
    risk_fn,
) -> List[Node]:
    """Retired nodes ordered cheapest-disruption-first (ties by name for
    determinism). ``risk_fn(capacity_type, zone) -> float`` is normally
    ``InterruptionRiskTracker.risk``."""

    def cost(n: Node) -> Tuple[float, str]:
        labels = n.metadata.labels
        price = price_by_type.get(labels.get(lbl.INSTANCE_TYPE, ""), 0.0)
        risk = risk_fn(
            labels.get(lbl.CAPACITY_TYPE, ""), labels.get(lbl.TOPOLOGY_ZONE, "")
        )
        return (
            disruption_cost(n, node_pods.get(n.metadata.name, []), price, risk),
            n.metadata.name,
        )

    return sorted(retire, key=cost)
