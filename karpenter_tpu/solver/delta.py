"""Resident cluster encoding: per-round deltas over the host encode path.

Steady-state rounds re-see mostly the same pods against the same catalog,
yet ``encode.encode`` rebuilt every pod-side tensor from Python objects each
solve — sort + inject + encode was ~26ms of the 10k-pod budget after the
wire floor fell (BENCH_r06). ``ResidentEncoder`` does to the pod side what
PR 4's sessions did to the catalog side: it keeps the encoded batch
resident across rounds and patches it from per-pod cached rows, guarded by
a content-keyed **epoch** so staleness fails loud into a full re-encode,
never a stale-tensor solve (docs/delta-encoding.md).

Three round shapes, cheapest first:

1. **reuse** — same sorted pod identities, same epoch: the previous
   ``EncodedBatch`` is returned as-is (and, because object identity is
   stable, the device/session transports skip their own re-uploads too).
2. **delta** — pods arrived/bound/deleted under an unchanged epoch: cached
   per-pod rows (stable-vocab core/host/request ids) are gathered in the
   new sorted order, renumbered to batch-local first-seen ids with
   vectorized numpy, and handed to ``encode.finish_encode`` — the SAME tail
   the full path runs, so delta-built tensors are bit-exact against a full
   re-encode by construction (the parity fuzz in tests/test_delta.py pins
   this with float-hex equality).
3. **full** — cold start, epoch change (constraints/catalog/axes/daemon
   drift), or an evicted table: delegate to ``encode.encode`` and adopt its
   batch-local vocabulary as the new resident state.

Topology batches ride the resident path through **plan reuse** rather than
row deltas: ``inject_plan`` is cluster- and rng-dependent, so a resident
overlay of its per-pod decisions would be guesswork — but the whole
injected round (post-inject constraints, ``DomainPlan``, daemon overhead)
is a deterministic function of (sorted batch, pre-inject constraints
content, cluster state). When none of those moved — same ``sts`` object
from the sort cache, equal requirements tuple, same ``Cluster.version()``
— the cached plan is reused and the encode lands on the zero-churn reuse
rung. Any input moving (a bind bumps the cluster version) falls back to a
counted full inject+re-encode; the per-pod row delta stays reserved for
topology-free batches, whose injected plan is empty by construction.

Threading: owned by one scheduler and called under its solve lock (the
``EncodeCache`` contract); no internal locking.
"""

from __future__ import annotations

import hashlib
import operator
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Pod
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.scheduling.topology import DomainPlan
from karpenter_tpu.solver import encode as enc
from karpenter_tpu.utils import resources as res

# catalog-extras memo entries retained (keyed by catalog fingerprint +
# daemon content — one per recently seen catalog)
_EXTRAS_MEMO_MAX = 4


def _first_seen(stable: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Renumber stable vocab ids to batch-local ids in FIRST-OCCURRENCE
    order — exactly the ids the full encode's interning loop would have
    assigned scanning the same pods in the same order. Returns (local ids
    [n] i32, stable ids indexed by local id)."""
    uniq, first, inv = np.unique(stable, return_index=True, return_inverse=True)
    order = np.argsort(first, kind="stable")
    rank = np.empty(len(uniq), np.int64)
    rank[order] = np.arange(len(uniq))
    return rank[inv.reshape(-1)].astype(np.int32), uniq[order]


class ResidentEncoder:
    """Per-scheduler resident encode state (see module docstring)."""

    def __init__(self, cache: enc.EncodeCache):
        self._cache = cache
        # host epoch: blake2b-16 over every input the encoded tensors are a
        # function of besides the pods themselves — full requirements tuple
        # (hostname included: it feeds hostname_in_base/open-host), catalog
        # fingerprint, resource axes, daemon overhead content
        self._epoch: Optional[bytes] = None
        self._table = None
        self._usable: Optional[np.ndarray] = None
        self._axes: Optional[tuple] = None
        # stable vocabularies (epoch-scoped; reset on every adoption)
        self._cores: list = []
        self._core_ids: dict = {}
        self._hosts: List[str] = []
        self._host_ids: Dict[str, int] = {}
        self._host_hib: List[bool] = []
        self._req_ids: dict = {}  # id(st.req_tid) -> stable rid
        self._req_vecs: List[Optional[np.ndarray]] = []  # UNTRIMMED [R] f32
        # per-pod rows: id(pod) -> (pod, stable_cid, stable_hid, stable_rid,
        # hib). Holds the pod strongly so the id cannot be recycled; pruned
        # to the current round's pods on every delta/adopt.
        self._rows: dict = {}
        # sort cache: pod ids of the last input → its output. Keyed on pod
        # identity alone — the same contract the reuse rung already holds
        # (see sort()); _sorted_pods pins every pod so no id can recycle.
        self._sort_key: Optional[list] = None
        self._sorted_pods: Optional[List[Pod]] = None
        self._sorted_sts: Optional[list] = None
        self._topo_any: bool = True
        # zero-churn reuse: sorted pod ids + batch of the last encode.
        # _last_pods_obj is the sorted list OBJECT (stable across sort-cache
        # hits), so the steady-state reuse check is one identity test
        # instead of a 10k-element id-list build+compare.
        self._last_pids: Optional[list] = None
        self._last_pods_obj: Optional[list] = None
        self._last_batch: Optional[enc.EncodedBatch] = None
        # whether the resident rows were adopted from a topology round:
        # those rows embed the injected plan's decisions, so the per-pod
        # row delta must never rebuild tensors from them
        self._topo_resident: bool = False
        self._extras_memo: dict = {}
        # pod-extras memo: the O(n) extra_res union, keyed on the sts list
        # object (held strongly; sort-cache hits return the same object)
        self._pod_extras_sts: Optional[list] = None
        self._pod_extras: frozenset = frozenset()
        # plan reuse (topology batches): the cached injected round — the
        # post-inject constraints clone, the DomainPlan, and the daemon
        # overhead — valid while (sts object, pre-inject requirements
        # content, cluster version) all match
        self._plan_key: Optional[tuple] = None
        self._plan_sts: Optional[list] = None
        self._plan: Optional[DomainPlan] = None
        self._plan_constraints: Optional[Constraints] = None
        self._plan_daemon: Optional[Dict[str, float]] = None
        # epoch-digest memo: the repr of a catalog-merged requirements
        # tuple is ~MBs of string per round; Requirements is
        # immutable-by-convention and catalog_fingerprint returns a
        # memoized (identity-stable) object, so identity of both plus the
        # small axes/daemon content stands in for the full serialization
        self._digest_memo: Optional[tuple] = None

    # -- sort ----------------------------------------------------------------

    def sort(self, pods: Sequence[Pod]) -> Tuple[List[Pod], list, bool]:
        """``sort_pods_ffd_with_statics`` with a resident fast path: when
        the input's pod identities match the previous round's, the cached
        sorted output is returned without re-sorting. Bit-exact either way:
        the slow branch IS the ffd sort.

        The hit key is pod identity alone — the contract the reuse rung in
        ``encode`` already holds (its ``spids == _last_pids`` guard never
        consults statics either): nothing in this codebase mutates a pod's
        spec in place — selector writes REPLACE the pod (watch updates) or
        go through materialize/restore, which swaps the identical original
        dict back — so an unchanged pod object proves an unchanged spec.
        Running the 10k-call statics pass per hit just to re-prove that
        cost ~10ms alone, the whole steady-state host budget."""
        from karpenter_tpu.scheduling.statics import statics

        n = len(pods)
        key = list(map(id, pods))
        if key == self._sort_key:
            return self._sorted_pods, self._sorted_sts, True
        sts = [statics(p) for p in pods]
        if n < 256:
            order = sorted(range(n), key=lambda i: (-sts[i].cpu, -sts[i].mem))
            spods = [pods[i] for i in order]
            ssts = [sts[i] for i in order]
        else:
            cpu = np.fromiter(
                map(operator.attrgetter("cpu"), sts), dtype=np.float64, count=n
            )
            mem = np.fromiter(
                map(operator.attrgetter("mem"), sts), dtype=np.float64, count=n
            )
            order = np.lexsort((-mem, -cpu)).tolist()
            getter = operator.itemgetter(*order)
            spods, ssts = list(getter(pods)), list(getter(sts))
        self._sort_key = key
        self._sorted_pods = spods
        self._sorted_sts = ssts
        self._topo_any = any(st.topo_any for st in ssts)
        return spods, ssts, False

    # -- inject --------------------------------------------------------------

    def eligible(self, sts: list) -> bool:
        """Topology-free batches only: with no affinity/spread/host-port
        pod, ``inject_plan`` provably returns an empty plan and leaves the
        constraints unmutated, so the resident path can skip its per-pod
        discovery sweep entirely."""
        if self._sorted_sts is sts:
            return not self._topo_any
        return not any(st.topo_any for st in sts)

    @staticmethod
    def empty_plan(pods: List[Pod], sts: list) -> DomainPlan:
        """The plan ``inject_plan`` would build for a topology-free batch:
        no decisions, statics attached for encode's shared-pass fast path."""
        plan = DomainPlan(pods)
        plan.sts = sts
        return plan

    # -- plan reuse (topology batches) ---------------------------------------

    @staticmethod
    def plan_key(constraints: Constraints, cluster_version: int) -> tuple:
        """Everything the injected round is a function of besides the
        sorted batch itself: the PRE-inject requirements content (inject
        mutates its constraints clone, so content — not identity — is the
        stable part) and the cluster version (affinity/spread domains read
        existing cluster pods and nodes; every store mutation bumps it)."""
        reqs = tuple(
            (r.key, r.operator, tuple(r.values))
            for r in constraints.requirements.requirements
        )
        return (cluster_version, reqs)

    def plan_reuse(self, key: tuple, sts: list) -> Optional[tuple]:
        """The cached injected round, or None. Requires the sts OBJECT from
        the sort cache (identity pins pods + order + statics; the strongly
        held ref means the id cannot have been recycled) and an equal plan
        key. Returns (constraints, plan, daemon) — the constraints a fresh
        clone of the cached post-inject clone and the daemon a dict copy,
        so a downstream consumer mutating either cannot poison the cache."""
        if self._plan_sts is not sts or key != self._plan_key:
            return None
        return (
            self._plan_constraints.clone(),
            self._plan,
            dict(self._plan_daemon),
        )

    def remember_plan(
        self, key: tuple, sts: list, constraints: Constraints,
        plan: DomainPlan, daemon: Dict[str, float],
    ) -> None:
        """Cache a freshly injected topology round for reuse. `constraints`
        is the POST-inject clone; the key holds the pre-inject content."""
        self._plan_key = key
        self._plan_sts = sts
        self._plan_constraints = constraints.clone()
        self._plan = plan
        self._plan_daemon = dict(daemon)

    # -- epoch ---------------------------------------------------------------

    def _axes_for(self, sts: list, instance_types, daemon: Dict[str, float]) -> tuple:
        """The resource-axis tuple ``encode`` would derive in plan mode —
        pod extras unioned with the (memoized) catalog+daemon extras."""
        if sts is self._pod_extras_sts:
            # sort-cache hits hand back the same sts object; the union over
            # 10k frozensets is O(n) Python and identical by construction
            pod_extras = self._pod_extras
        else:
            pod_extras = (
                frozenset().union(*map(operator.attrgetter("extra_res"), sts))
                if sts else frozenset()
            )
            self._pod_extras_sts = sts
            self._pod_extras = pod_extras
        fp = enc.catalog_fingerprint(instance_types)
        dk = tuple(sorted(daemon.items()))
        hit = self._extras_memo.get((id(fp), dk))
        if hit is None:
            hit = set(
                res.collect_extra_axes(
                    [it.resources for it in instance_types]
                    + [it.overhead for it in instance_types]
                    + [daemon]
                )
            )
            if len(self._extras_memo) >= _EXTRAS_MEMO_MAX:
                self._extras_memo.clear()
            # the fingerprint tuple rides in the value so its id stays valid
            self._extras_memo[(id(fp), dk)] = (hit, fp)
        cat_extras = hit[0] if isinstance(hit, tuple) else hit
        return tuple(sorted(pod_extras | cat_extras))

    def epoch_digest(
        self, constraints: Constraints, instance_types, axes: tuple,
        daemon: Dict[str, float],
    ) -> bytes:
        """Content key of everything but the pods: a change in any input
        the resident tensors were built from mints a new epoch and forces a
        counted full re-encode — the fail-loud ladder's first rung.

        Memoized on (requirements identity, fingerprint identity, axes,
        daemon content): Requirements mutators return new objects and the
        catalog fingerprint is identity-stable, so an unchanged pair proves
        an unchanged serialization without re-repr'ing the catalog-merged
        requirements tuple every round."""
        fp = enc.catalog_fingerprint(instance_types)
        dk = tuple(sorted(daemon.items()))
        memo = self._digest_memo
        if (
            memo is not None
            and memo[0] is constraints.requirements
            and memo[1] is fp
            and memo[2] == axes
            and memo[3] == dk
        ):
            return memo[4]
        reqs = tuple(
            (r.key, r.operator, tuple(r.values))
            for r in constraints.requirements.requirements
        )
        payload = repr((reqs, fp, axes, dk))
        digest = hashlib.blake2b(payload.encode(), digest_size=16).digest()
        self._digest_memo = (constraints.requirements, fp, axes, dk, digest)
        return digest

    # -- encode --------------------------------------------------------------

    def encode(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        pods: List[Pod],
        sts: list,
        daemon: Dict[str, float],
        plan: DomainPlan,
        *,
        topo: bool = False,
        plan_reused: bool = False,
    ) -> Tuple[enc.EncodedBatch, str]:
        """Encode an already-sorted batch through the resident path.
        Returns ``(batch, kind)`` with kind one of ``"reuse"`` / ``"delta"``
        / ``"full"``; the batch is bit-exact against ``encode.encode`` on
        the same inputs in every case.

        Topology batches (``topo=True``) only ever hit the reuse rung, and
        only when the backend reused the cached injected plan
        (``plan_reused``) — the epoch digest does not cover cluster state,
        and the resident rows of a topology round embed the plan's per-pod
        decisions, so both the zero-churn shortcut and the row delta would
        otherwise trust inputs the guard never checked. Everything else
        falls to a counted ``full("topology")``."""
        from karpenter_tpu import metrics

        axes = self._axes_for(sts, instance_types, daemon)
        epoch = self.epoch_digest(constraints, instance_types, axes, daemon)
        key = enc._table_key(constraints, instance_types, list(axes))
        if epoch != self._epoch:
            reason = "cold" if self._epoch is None else "epoch"
            return self._full(
                constraints, instance_types, pods, sts, daemon, plan,
                epoch, key, axes, reason, topo=topo,
            ), "full"
        # same epoch: the resident table must still BE the cache's table
        # (eviction under catalog churn re-mints equal-content objects whose
        # memoized closures this path's vocab ids don't belong to)
        hit = self._cache.tables.get(key)
        if hit is None or hit[1] is not self._table:
            return self._full(
                constraints, instance_types, pods, sts, daemon, plan,
                epoch, key, axes, "table", topo=topo,
            ), "full"
        # zero-churn reuse: list-object identity first (sort-cache hits
        # return the same sorted list, making steady state O(1)), the
        # id-list compare as the fresh-sort-same-pods fallback
        spids: Optional[list] = None
        if not topo or plan_reused:
            if pods is not self._last_pods_obj:
                spids = list(map(id, pods))
            if spids is None or spids == self._last_pids:
                metrics.SOLVER_DELTA_APPLIED.labels(path="host").inc()
                return self._last_batch, "reuse"
        if topo or self._topo_resident:
            return self._full(
                constraints, instance_types, pods, sts, daemon, plan,
                epoch, key, axes, "topology", topo=topo,
            ), "full"
        if spids is None:
            spids = list(map(id, pods))
        batch = self._delta(pods, sts, spids, constraints, daemon)
        metrics.SOLVER_DELTA_APPLIED.labels(path="host").inc()
        self._last_pids = spids
        self._last_pods_obj = pods
        self._last_batch = batch
        self._publish_resident_bytes(batch)
        return batch, "delta"

    def reset(self) -> None:
        """Drop all resident state (epoch, vocab, rows, cached batch) —
        the overflow-retry path's companion to ``EncodeCache.clear``."""
        self._epoch = None
        self._table = None
        self._usable = None
        self._axes = None
        self._cores = []
        self._core_ids = {}
        self._hosts = []
        self._host_ids = {}
        self._host_hib = []
        self._req_ids = {}
        self._req_vecs = []
        self._rows = {}
        self._last_pids = None
        self._last_pods_obj = None
        self._last_batch = None
        self._topo_resident = False
        self._pod_extras_sts = None
        self._pod_extras = frozenset()
        self._plan_key = None
        self._plan_sts = None
        self._plan = None
        self._plan_constraints = None
        self._plan_daemon = None
        self._digest_memo = None

    def force_full(self, reason: str) -> None:
        """Count an out-of-band full re-encode (e.g. a topology-bearing
        round routed around the resident path by the backend)."""
        from karpenter_tpu import metrics

        metrics.SOLVER_DELTA_FULL_REENCODES.labels(reason=reason).inc()

    # -- internals -----------------------------------------------------------

    def _full(
        self, constraints, instance_types, pods, sts, daemon, plan,
        epoch: bytes, key, axes: tuple, reason: str, *, topo: bool = False,
    ) -> enc.EncodedBatch:
        from karpenter_tpu import metrics

        metrics.SOLVER_DELTA_FULL_REENCODES.labels(reason=reason).inc()
        batch = enc.encode(
            constraints, instance_types, pods, daemon,
            cache=self._cache, plan=plan,
        )
        self._adopt(batch, pods, sts, epoch, key, axes, topo=topo)
        return batch

    def _adopt(
        self, batch: enc.EncodedBatch, pods: List[Pod], sts: list,
        epoch: bytes, key, axes: tuple, *, topo: bool = False,
    ) -> None:
        """Adopt a full encode's batch-local vocabulary as the resident
        stable vocabulary (stable id == batch-local id for this round) and
        cache one row per pod."""
        hit = self._cache.tables.get(key)
        if hit is None:
            # the table never landed (cache disabled edge): no residency
            self._epoch = None
            self._rows = {}
            self._last_pids = None
            self._last_pods_obj = None
            self._last_batch = None
            return
        self._usable, self._table = hit
        self._epoch = epoch
        self._axes = axes
        self._topo_resident = topo
        self._cores = list(batch.cores)
        self._core_ids = {c: i for i, c in enumerate(self._cores)}
        self._hosts = list(batch.hostnames)
        self._host_ids = {h: i for i, h in enumerate(self._hosts)}
        self._host_hib = [self._table.hostname_in_base(h) for h in self._hosts]
        n = batch.n_pods
        pc = batch.pod_core[:n].tolist()
        ph = batch.pod_host[:n].tolist()
        pr = batch.pod_req_id[:n].tolist()
        hb = batch.pod_host_in_base[:n].tolist()
        self._req_ids = {}
        self._req_vecs = [None] * (len(batch.uniq_req) - 1)
        rows = {}
        req_vecs = self._req_vecs
        req_ids = self._req_ids
        for i, pod in enumerate(pods):
            st = sts[i]
            rid = pr[i]
            if req_vecs[rid] is None:
                # UNTRIMMED vector, re-derived exactly as encode interned it
                req_vecs[rid] = res.to_scaled_vector(st.req, list(axes))
                req_ids[id(st.req_tid)] = rid
            rows[id(pod)] = (pod, pc[i], ph[i], rid, hb[i])
        self._rows = rows
        self._last_pids = list(map(id, pods))
        self._last_pods_obj = pods
        self._last_batch = batch
        self._publish_resident_bytes(batch)

    def _add_row(self, pod: Pod, st) -> tuple:
        """Intern one NEW pod into the stable vocabulary — the per-pod cost
        of an arrival, paid once. Topology-free by eligibility, so the core
        and hostname are the statics' undecorated ones (exactly what the
        full encode's plan-mode loop resolves with an empty ztoken and no
        hostname decision)."""
        core, hostname = st.core0, st.hostname0
        cid = self._core_ids.get(core)
        if cid is None:
            cid = len(self._cores)
            self._core_ids[core] = cid
            self._cores.append(core)
        if hostname is None:
            hid, hib = -1, False
        else:
            hid = self._host_ids.get(hostname)
            if hid is None:
                hid = len(self._hosts)
                self._host_ids[hostname] = hid
                self._hosts.append(hostname)
                self._host_hib.append(self._table.hostname_in_base(hostname))
            hib = self._host_hib[hid]
        rid = self._req_ids.get(id(st.req_tid))
        if rid is None:
            rid = len(self._req_vecs)
            self._req_ids[id(st.req_tid)] = rid
            self._req_vecs.append(res.to_scaled_vector(st.req, list(self._axes)))
        row = (pod, cid, hid, rid, hib)
        self._rows[id(pod)] = row
        return row

    def _delta(
        self, pods: List[Pod], sts: list, spids: list,
        constraints: Constraints, daemon: Dict[str, float],
    ) -> enc.EncodedBatch:
        """Churn round: gather cached rows in the new sorted order (new
        arrivals interned on the way), renumber the stable ids to
        batch-local first-seen ids with vectorized numpy, and run the
        shared ``finish_encode`` tail."""
        n = len(pods)
        rows_get = self._rows.get
        cid_l = [0] * n
        hid_l = [0] * n
        rid_l = [0] * n
        hib_l = [False] * n
        rows = {}
        for i, pid in enumerate(spids):
            row = rows_get(pid)
            if row is None:
                row = self._add_row(pods[i], sts[i])
            rows[pid] = row
            _, cid_l[i], hid_l[i], rid_l[i], hib_l[i] = row
        # prune to the current round: bound memory and keep only live pods
        # pinned (a bound/deleted pod's id must not alias a future arrival)
        self._rows = rows

        stable_cid = np.array(cid_l, np.int64)
        stable_hid = np.array(hid_l, np.int64)
        stable_rid = np.array(rid_l, np.int64)
        hib_arr = np.array(hib_l, bool)

        local_cid, core_sel = _first_seen(stable_cid)
        cores = [self._cores[s] for s in core_sel.tolist()]
        local_rid, req_sel = _first_seen(stable_rid)
        uniq_vecs = [self._req_vecs[s] for s in req_sel.tolist()]

        local_hid = np.full(n, -1, np.int32)
        mask = stable_hid >= 0
        hostnames: List[str] = []
        openh = np.full(n, -1, np.int32)
        base_has_hostname = constraints.requirements.has(lbl.HOSTNAME)
        if mask.any():
            loc, host_sel = _first_seen(stable_hid[mask])
            local_hid[mask] = loc
            hostnames = [self._hosts[s] for s in host_sel.tolist()]
            # node hostname state if the pod opens a node: joinable (h) or
            # poisoned (-2) when the base domains exclude it — the same
            # expression the full encode evaluates per pod
            openh[mask] = np.where(
                hib_arr[mask] | (not base_has_hostname), loc, -2
            )
        hib_out = hib_arr & mask

        return enc.finish_encode(
            self._table, self._usable, list(self._axes), daemon, pods,
            local_cid, local_hid, hib_out, openh, local_rid,
            cores, hostnames, uniq_vecs, base_has_hostname,
        )

    def _publish_resident_bytes(self, batch: enc.EncodedBatch) -> None:
        from karpenter_tpu import metrics

        total = sum(
            a.nbytes for a in batch.pack_args() if isinstance(a, np.ndarray)
        )
        metrics.SOLVER_DELTA_RESIDENT_BYTES.labels(side="host").set(total)
