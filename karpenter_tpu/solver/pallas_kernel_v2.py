"""Pallas packing kernel v2: signature gathers as MXU matmuls.

The v1 kernel (pallas_kernel.py) unrolls the S×F signature/frontier loops
per pod step, so Mosaic compile time scales with S·F (measured ~2.5× per S
doubling; ~2min at the S=512 closure cap) — constraint-diverse batches fall
back to lax.scan and pay ~500ms at 8k pods.

Here compile size is O(F), independent of S. The trick: keep each node's
signature as a ONE-HOT column of a ``[S, N]`` f32 state matrix, and
precompute per-core join tables outside the kernel:

- ``frontJ[c, f·R+r, s]  = frontiers[join[s, c], f, r]`` (``BIG`` where the
  join is incompatible) — so the joined-signature fit limits for every node
  are one matmul: ``limits = frontJ[core] @ onehot_sig`` → ``[F·R, N]``;
- ``compatJ[c, s] = join[s, c] >= 0`` — joinability is
  ``compatJ[core] @ onehot_sig`` → ``[1, N]``;
- ``jvals[c, s] = join[s, c]`` — the joined signature id, extracted only at
  the chosen target node.

Per pod the body is three small matmuls (MXU), vector compares (VPU), and
masked state writes — no dynamic VMEM indexing, no S-unrolled selects.
``frontJ[core]`` is a dynamic *leading-axis* read of a tile-aligned
``[F·R, S]`` slice, which Mosaic supports.

Semantics are assignment-identical to ``kernel.pack`` (parity-tested on
chip). VMEM sizing: the one-hot state is ``S_pad × N_pad`` f32 — the caller
gates on an estimate (``v2_vmem_ok``).
"""

from __future__ import annotations

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from karpenter_tpu.solver.kernel import PackResult
from karpenter_tpu.solver.pallas_kernel import (  # shared contract with v1
    _CORE,
    _HOST,
    _HOST_IN_BASE,
    _OPEN_HOST,
    _OPEN_SIG,
    _VALID,
    BIG,
    BLOCK,
)

logger = logging.getLogger("karpenter.solver")

NEG = -1e30  # "incompatible" frontier limit: nothing fits


def _pad_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pack_kernel_v2(
    pod_scal_ref,   # [6, P] i32
    pod_req_ref,    # [R, P] f32
    front_j_ref,    # [C, FR, S_pad] f32 — joined-frontier limits per core
    compat_j_ref,   # [C, 8, S_pad] f32 — row 0: join[s,c] >= 0 (1.0/0.0)
    jvals_ref,      # [C, 8, S_pad] f32 — row 0: join[s,c] (as f32)
    open_fits_ref,  # [1, P] i32 — precomputed: daemon+req fits open_sig's frontier
    daemon_ref,     # [R, 1] f32
    assignment_ref, # [1, P] i32 out
    node_sig_ref,   # [1, N] i32 out
    node_host_ref,  # [1, N] i32 out
    node_req_ref,   # [R, N] f32 out
    count_ref,      # [1, 1] i32 out (SMEM)
    sig_onehot_ref, # [S_pad, N] f32 scratch — node signature one-hot state
    *,
    n_cap: int,
    F: int,
    R: int,
):
    P = pod_scal_ref.shape[1]
    N = node_sig_ref.shape[1]
    S_pad = sig_onehot_ref.shape[0]
    FR = F * R

    node_sig_ref[:] = jnp.full((1, N), -1, jnp.int32)
    node_host_ref[:] = jnp.full((1, N), -1, jnp.int32)
    node_req_ref[:] = jnp.zeros((R, N), jnp.float32)
    sig_onehot_ref[:] = jnp.zeros((S_pad, N), jnp.float32)

    node_lane = lax.broadcasted_iota(jnp.int32, (1, N), 1)
    blk_lane = lax.broadcasted_iota(jnp.int32, (1, BLOCK), 1)
    sig_iota = lax.broadcasted_iota(jnp.int32, (S_pad, 1), 0)
    daemon = daemon_ref[:]  # [R, 1]

    def block_body(b, count):
        start = pl.multiple_of(b * BLOCK, BLOCK)
        scal_blk = pod_scal_ref[:, pl.ds(start, BLOCK)]  # [6, BLOCK]
        req_blk = pod_req_ref[:, pl.ds(start, BLOCK)]    # [R, BLOCK]
        openfit_blk = open_fits_ref[:, pl.ds(start, BLOCK)]  # [1, BLOCK]

        def pod_body(k, carry):
            count, assign_vec = carry
            at_k = blk_lane == k

            def pick(row):
                return jnp.sum(jnp.where(at_k, scal_blk[row : row + 1, :], 0))

            valid = pick(_VALID) != 0
            open_sig = pick(_OPEN_SIG)
            core = pick(_CORE)
            host = pick(_HOST)
            host_in_base = pick(_HOST_IN_BASE) != 0
            open_host = pick(_OPEN_HOST)
            open_ok = jnp.sum(jnp.where(at_k, openfit_blk, 0)) != 0
            req = jnp.sum(jnp.where(at_k, req_blk, 0.0), axis=1, keepdims=True)  # [R,1]

            node_sig = node_sig_ref[:]
            node_host = node_host_ref[:]
            node_req = node_req_ref[:]
            onehot = sig_onehot_ref[:]  # [S_pad, N]
            is_open = node_sig >= 0
            new_req = node_req + req  # [R, N]

            # per-core tables for THIS pod's core (dynamic leading index of
            # tile-aligned slices)
            front_c = front_j_ref[core]    # [FR, S_pad]
            compat_c = compat_j_ref[core]  # [8, S_pad]
            jvals_c = jvals_ref[core]      # [8, S_pad]

            # joined-frontier limits for every node: [FR, N]. HIGHEST
            # precision is load-bearing: the TPU MXU's default bf16 passes
            # would round the gathered limits and flip fit comparisons.
            limits_join = jnp.dot(
                front_c, onehot, preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST,
            )
            ok_row = jnp.dot(
                compat_c[0:1, :], onehot, preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST,
            )
            j_row = jnp.dot(
                jvals_c[0:1, :], onehot, preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST,
            )

            # fits = ∃f ∀r: new_req[r] ≤ limits_join[f·R+r]
            fits = jnp.zeros((1, N), jnp.bool_)
            for f in range(F):
                fit_f = jnp.ones((1, N), jnp.bool_)
                for r in range(R):
                    fit_f = fit_f & (new_req[r : r + 1, :] <= limits_join[f * R + r : f * R + r + 1, :])
                fits = fits | fit_f

            ok_host = (host < 0) | ((node_host == -1) & host_in_base) | (node_host == host)
            ok = (ok_row > 0.5) & is_open & ok_host & fits

            any_ok = jnp.any(ok)
            first_ok = jnp.min(jnp.where(ok, node_lane, BIG))
            can_open = open_ok & (count < n_cap)
            schedulable = valid & (any_ok | can_open)
            target = jnp.where(any_ok, first_ok, count)
            at_target = node_lane == target  # [1, N]

            def extract(vec):
                return jnp.sum(jnp.where(at_target, vec, 0))

            def extractf(vec):
                return jnp.sum(jnp.where(at_target, vec, 0.0))

            j_target = jnp.round(extractf(j_row)).astype(jnp.int32)
            upd_sig = jnp.where(any_ok, j_target, open_sig)
            upd_host = jnp.where(
                any_ok, jnp.where(host >= 0, host, extract(node_host)), open_host
            )
            open_req = daemon + req
            req_target = jnp.sum(jnp.where(at_target, new_req, 0.0), axis=1, keepdims=True)
            upd_req = jnp.where(any_ok, req_target, open_req)  # [R, 1]

            # the node's NEW signature as a one-hot column
            upd_onehot = (sig_iota == upd_sig).astype(jnp.float32)  # [S_pad, 1]

            write = schedulable & at_target
            node_sig_ref[:] = jnp.where(write, upd_sig, node_sig)
            node_host_ref[:] = jnp.where(write, upd_host, node_host)
            node_req_ref[:] = jnp.where(write, upd_req, node_req)
            sig_onehot_ref[:] = jnp.where(write, upd_onehot, onehot)

            assign_vec = jnp.where(at_k, jnp.where(schedulable, target, -1), assign_vec)
            count = count + jnp.where(schedulable & ~any_ok, 1, 0).astype(jnp.int32)
            return count, assign_vec

        count, assign_vec = lax.fori_loop(
            0, BLOCK, pod_body, (count, jnp.full((1, BLOCK), -1, jnp.int32))
        )
        assignment_ref[:, pl.ds(start, BLOCK)] = assign_vec
        return count

    count = lax.fori_loop(0, P // BLOCK, block_body, jnp.zeros((), jnp.int32))
    count_ref[0, 0] = count


def _precompute(join_table: np.ndarray, frontiers: np.ndarray):
    """Host-side per-core tables. join_table [S, C] i32; frontiers [S, F, R]."""
    S, C = join_table.shape
    F, R = frontiers.shape[1], frontiers.shape[2]
    FR = F * R
    S_pad = _pad_to(max(S, 8), 128)  # lane axis of the per-core tables
    C_pad = max(C, 1)

    flat = frontiers.reshape(S, FR).astype(np.float32)

    front_j = np.full((C_pad, _pad_to(FR, 8), S_pad), NEG, np.float32)
    compat_j = np.zeros((C_pad, 8, S_pad), np.float32)
    jvals = np.zeros((C_pad, 8, S_pad), np.float32)
    for c in range(C):
        j = join_table[:, c]  # [S]
        ok = j >= 0
        compat_j[c, 0, :S] = ok.astype(np.float32)
        jvals[c, 0, :S] = np.where(ok, j, 0).astype(np.float32)
        gathered = np.where(ok[:, None], flat[np.clip(j, 0, S - 1)], NEG)  # [S, FR]
        front_j[c, :FR, :S] = gathered.T
    return front_j, compat_j, jvals, S_pad


def _open_fits_host(pod_open_sig, pod_req, frontiers, daemon):
    """[P] precomputed: does daemon+req fit ANY frontier of the pod's open
    signature? (Independent of node state — hoisted out of the kernel.)"""
    need = pod_req.astype(np.float32) + daemon.astype(np.float32)[None, :]  # [P, R]
    limits = frontiers[np.asarray(pod_open_sig)]  # [P, F, R]
    return np.any(np.all(need[:, None, :] <= limits, axis=-1), axis=-1)


@partial(jax.jit, static_argnames=("n_max", "F", "R"))
def _pack_v2_call(
    pod_scal, pod_req_t, front_j, compat_j, jvals, open_fits,
    daemon, n_max: int, F: int, R: int,
):
    P = pod_scal.shape[1]
    S_pad = front_j.shape[2]
    n = max(BLOCK, _pad_to(n_max, BLOCK))
    return pl.pallas_call(
        partial(_pack_kernel_v2, n_cap=n_max, F=F, R=R),
        out_shape=(
            jax.ShapeDtypeStruct((1, P), jnp.int32),
            jax.ShapeDtypeStruct((1, n), jnp.int32),
            jax.ShapeDtypeStruct((1, n), jnp.int32),
            jax.ShapeDtypeStruct((R, n), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 7,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((S_pad, n), jnp.float32),
        ],
    )(pod_scal, pod_req_t, front_j, compat_j, jvals, open_fits, daemon)


def v2_vmem_ok(S: int, n_max: int, C: int, FR: int) -> bool:
    """Rough VMEM budget: one-hot state + per-core tables must fit.

    Threshold calibrated on a v5e: (S=256, n=512) ≈ 1.8MB compiles in ~7s
    and runs at the transport floor; (S=512, n=2048) ≈ 7.1MB consistently
    fails remote compile. 6MB keeps the proven region with headroom (the
    runtime fallback memoizes any residual failure per shape)."""
    S_pad = _pad_to(max(S, 8), 128)
    n = max(BLOCK, _pad_to(n_max, BLOCK))
    state = S_pad * n * 4  # sig one-hot
    tables = C * (_pad_to(FR, 8) + 16) * S_pad * 4
    return state + tables < 6 * 1024 * 1024


def pack_pallas_v2(
    pod_valid, pod_open_sig, pod_core, pod_host, pod_host_in_base,
    pod_open_host, pod_req, join_table, frontiers, daemon, n_max: int,
) -> PackResult:
    """Same contract as ``kernel.pack``; compile cost independent of S."""
    pod_req = np.asarray(pod_req, np.float32)
    join_table = np.asarray(join_table)
    frontiers = np.asarray(frontiers, np.float32)
    daemon_np = np.asarray(daemon, np.float32)
    P, R = pod_req.shape
    F = frontiers.shape[1]
    if P % BLOCK != 0:
        raise ValueError(f"pallas v2 needs P % {BLOCK} == 0, got {P}")
    front_j, compat_j, jvals, S_pad = _precompute(join_table, frontiers)
    open_fits = _open_fits_host(pod_open_sig, pod_req, frontiers, daemon_np)
    pod_scal = np.stack(
        [
            np.asarray(pod_valid).astype(np.int32),
            np.asarray(pod_open_sig).astype(np.int32),
            np.asarray(pod_core).astype(np.int32),
            np.asarray(pod_host).astype(np.int32),
            np.asarray(pod_host_in_base).astype(np.int32),
            np.asarray(pod_open_host).astype(np.int32),
        ]
    )
    assignment, node_sig, node_host, node_req_t, count = _pack_v2_call(
        pod_scal,
        pod_req.T,
        front_j,
        compat_j,
        jvals,
        open_fits.reshape(1, P).astype(np.int32),
        daemon_np.reshape(R, 1),
        n_max=n_max,
        F=F,
        R=R,
    )
    return PackResult(
        assignment=assignment[0],
        node_sig=node_sig[0, :n_max],
        node_host=node_host[0, :n_max],
        node_req=node_req_t[:, :n_max].T,
        n_nodes=count[0, 0],
    )
