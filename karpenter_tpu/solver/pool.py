"""Failover-aware solver sidecar pool (docs/fleet.md).

``RemoteSolver`` talks to ONE sidecar; at fleet scale the controller fronts
a POOL of them. Routing is a consistent-hash ring keyed on the PR-4
``catalog_session_key`` — a catalog generation's pinned tensors live in
exactly one member's HBM, so the steady state stays a delta solve against
a resident session and members don't each burn HBM on every catalog.

Failure handling is per member: each address gets its own circuit breaker
(window 1 / min_volume 1, same any-failure-trips contract as the old
single-address breaker in ``solver/backend.py``), and a dead or
breaker-open member reroutes the solve to the next ring member — where the
member's own ``RemoteSolver`` transparently re-uploads the catalog through
the NEEDS_CATALOG path. Only when EVERY member refuses does the pool raise,
which the scheduler's outer remote breaker turns into the in-process kernel
and ultimately the FFD floor — the degradation ladder keeps its shape, the
pool just adds rungs above it.

The failover cost is attributed: each reroute increments
``karpenter_solver_pool_failovers_total{address=<failed member>}`` and runs
under a ``solver.pool.failover`` span carrying from/to, so a PR-5 trace of
a slow solve shows exactly which member died and what the detour cost.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from bisect import bisect_right
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from karpenter_tpu import metrics
from karpenter_tpu.resilience.integrity import IntegrityError
from karpenter_tpu.resilience.overload import (
    DeadlineExceededError,
    OverloadedError,
)
from karpenter_tpu.solver import integrity
from karpenter_tpu.solver.service import (
    N_POD_ARRAYS,
    CatalogKeyMemo,
    RemoteSolver,
)

logger = logging.getLogger("karpenter.solver.pool")

# per-member breaker: any failure sidelines the member (one bounded stall,
# not one per solve), half-open probes re-admit it once it answers again
MEMBER_BREAKER_SECONDS = 15.0

# virtual nodes per member: enough that an 8-member pool's key space splits
# within a few percent of even, cheap enough to rebuild on membership change
RING_VNODES = 64


class PoolExhausted(RuntimeError):
    """Every pool member was dead or breaker-open for this solve."""


class HashRing:
    """Consistent-hash ring over member addresses. ``ordered(key)`` yields
    every member exactly once, starting from the key's ring successor —
    the failover ladder's member order."""

    def __init__(self, members: Sequence[str], vnodes: int = RING_VNODES):
        if not members:
            raise ValueError("hash ring needs at least one member")
        self.members = list(dict.fromkeys(members))  # stable order, deduped
        points: List[Tuple[int, str]] = []
        for member in self.members:
            for i in range(vnodes):
                digest = hashlib.blake2b(
                    f"{member}#{i}".encode(), digest_size=8
                ).digest()
                points.append((int.from_bytes(digest, "big"), member))
        points.sort()
        self._points = points
        self._hashes = [h for h, _ in points]

    @staticmethod
    def _key_point(key: bytes) -> int:
        return int.from_bytes(
            hashlib.blake2b(key, digest_size=8).digest(), "big"
        )

    def route(self, key: bytes) -> str:
        return self.ordered(key)[0]

    def ordered(self, key: bytes) -> List[str]:
        start = bisect_right(self._hashes, self._key_point(key))
        seen: "OrderedDict[str, None]" = OrderedDict()
        n = len(self._points)
        for i in range(n):
            _, member = self._points[(start + i) % n]
            if member not in seen:
                seen[member] = None
                if len(seen) == len(self.members):
                    break
        return list(seen)


class SolverPool:
    """Drop-in for :class:`RemoteSolver` over N sidecar addresses: same
    ``pack_begin(...) -> wait()`` / ``pack`` / ``health`` surface, so
    ``TpuScheduler`` treats a pool and a single sidecar identically."""

    KEY_MEMO_MAX = 8

    def __init__(
        self,
        addresses: Sequence[str],
        timeout: float = 30.0,
        cold_timeout: float = 180.0,
        breaker_open_seconds: float = MEMBER_BREAKER_SECONDS,
        client_factory: Optional[Callable[[str], RemoteSolver]] = None,
        clock: Callable[[], float] = time.monotonic,
        checksum: bool = False,
        stream: bool = False,
        shm_dir: str = "",
        delta: bool = False,
    ):
        addresses = [a.strip() for a in addresses if a.strip()]
        self._clock = clock
        self.ring = HashRing(addresses)
        self.addresses = self.ring.members
        self._timeout = timeout
        self._cold_timeout = cold_timeout
        # streaming transport (docs/solver-transport.md § Streaming): each
        # member client keeps ONE persistent multiplexed stream; credit
        # exhaustion surfaces as OverloadedError(kind="credits"), which
        # the soft-backoff path below consumes exactly like an admission
        # refusal — backpressure is never a breaker-worthy failure
        self._client_factory = client_factory or (
            lambda addr: RemoteSolver(
                addr, timeout=timeout, cold_timeout=cold_timeout,
                checksum=checksum, stream=stream, shm_dir=shm_dir,
                delta=delta,
            )
        )
        from karpenter_tpu.resilience import BreakerBoard

        # one breaker per member address; the board handles lazy creation.
        # The pool's clock is the breakers' clock — an injected test clock
        # must drive the cool-off too, or half-open recovery is untestable.
        self._breakers = BreakerBoard(
            clock=clock,
            window=1, min_volume=1, failure_rate=0.5,
            open_seconds=breaker_open_seconds,
        )
        self._clients: dict = {}  # guarded-by: self._mu
        self._key_memo = CatalogKeyMemo(self.KEY_MEMO_MAX)
        self.failovers = 0  # guarded-by: self._mu
        # soft breaker (docs/overload.md): a member answering
        # STATUS_OVERLOADED sits out its retry-after window and is simply
        # routed around — overload is backpressure, not failure, so its
        # REAL circuit breaker (and its half-open probe traffic) is never
        # touched by a shed
        self._backoff_until: Dict[str, float] = {}  # guarded-by: self._mu
        self.overload_skips = 0  # guarded-by: self._mu
        self._mu = threading.Lock()
        # integrity-quarantine hook (reason, address, detail): the owning
        # scheduler points this at its cluster-event emitter so every
        # quarantine lands as a Warning event, not only a log line
        self.on_quarantine: Optional[Callable[[str, str, str], None]] = None

    # -- members ------------------------------------------------------------
    def _client(self, address: str) -> RemoteSolver:
        with self._mu:
            client = self._clients.get(address)
            if client is None:
                client = self._clients[address] = self._client_factory(address)
            return client

    def _breaker(self, address: str):
        return self._breakers.get(f"solver-pool:{address}")

    def _member_failure(self, address: str, exc: Exception) -> None:
        tripped = self._breaker(address).record_failure()
        metrics.SOLVER_BREAKER_OPEN.labels(address=address).set(1)
        if tripped:
            metrics.SOLVER_BREAKER_TRIPS.labels(address=address).inc()
        logger.error(
            "solver pool member %s failed (%s); rerouting", address, exc
        )
        self._publish_available()

    def _member_success(self, address: str) -> None:
        self._breaker(address).record_success()
        metrics.SOLVER_BREAKER_OPEN.labels(address=address).set(0)
        self._publish_available()

    def quarantine(self, address: str, reason: str, detail: str = "") -> None:
        """Integrity quarantine (docs/integrity.md): force the member's
        breaker OPEN immediately — ``trip()``, the correctness edge, not
        the windowed availability path — because the member produced
        CORRUPT data (checksum failure, canary mismatch, screen failure,
        stale-session replay). Half-open probes re-admit it after the
        cool-off exactly like an availability trip; a member that is still
        corrupting re-quarantines on its first probe-served solve."""
        self._breaker(address).trip()
        metrics.SOLVER_BREAKER_OPEN.labels(address=address).set(1)
        metrics.SOLVER_BREAKER_TRIPS.labels(address=address).inc()
        integrity.record_quarantine(address, reason, detail)
        logger.error(
            "solver pool member %s QUARANTINED (%s): %s",
            address, reason, detail,
        )
        hook = self.on_quarantine
        if hook is not None:
            try:
                hook(reason, address, detail)
            except Exception:
                logger.debug("quarantine hook failed", exc_info=True)
        self._publish_available()

    def _member_corrupt(self, address: str, exc: IntegrityError) -> None:
        """An integrity verdict attributed to this member: quarantine and
        (the caller) reroutes — never a retry on the same member."""
        self.quarantine(address, exc.kind, str(exc))

    def _member_overloaded(self, address: str, retry_after: float) -> None:
        """Soft breaker: sit the member out for its own retry-after hint.
        No breaker state is touched — the member is healthy, just full."""
        with self._mu:
            self._backoff_until[address] = self._clock() + max(retry_after, 0.0)
        logger.info(
            "solver pool member %s overloaded; sitting it out %.2fs",
            address, max(retry_after, 0.0),
        )

    def _soft_backing_off(self, address: str) -> bool:
        with self._mu:
            until = self._backoff_until.get(address)
            if until is None:
                return False
            if self._clock() >= until:
                del self._backoff_until[address]
                return False
            return True

    def _backoff_remaining(self, address: str) -> float:
        with self._mu:
            until = self._backoff_until.get(address)
            if until is None:
                return 0.0
            return max(until - self._clock(), 0.0)

    def _count_overload_skip(self, address: str) -> None:
        metrics.SOLVER_POOL_OVERLOAD_SKIPS.labels(address=address).inc()
        with self._mu:
            self.overload_skips += 1

    def _publish_available(self) -> None:
        metrics.SOLVER_POOL_MEMBERS.set(len(self.available_members()))

    def available_members(self) -> List[str]:
        """Members currently admitting solves (breaker closed/probe-ready)."""
        return [a for a in self.addresses if self._breaker(a).available()]

    def health(self, timeout: float = 2.0) -> bool:
        """True when ANY member reports SERVING."""
        return any(
            self._client(a).health(timeout=timeout) for a in self.addresses
        )

    # -- routing ------------------------------------------------------------
    def _catalog_key(self, catalog_side: Tuple) -> bytes:
        """Identity-memoized catalog fingerprint (shared
        ``CatalogKeyMemo`` implementation) — the ring key must be the SAME
        content key the member pins its session under."""
        return self._key_memo.key(catalog_side)

    # -- solves -------------------------------------------------------------
    def pack_begin(
        self, *inputs, n_max: int, prof: Optional[dict] = None, record: bool = True
    ):
        """Route by session affinity, dispatch on the first admitting
        member, and return ``wait()``. A dispatch failure tries the next
        ring member immediately; a FETCH failure (discovered inside
        ``wait``) fails over synchronously — the overlap is already lost,
        correctness wins."""
        catalog_side = inputs[N_POD_ARRAYS:]
        key = self._catalog_key(catalog_side)
        order = self.ring.ordered(key)
        last_exc: Optional[Exception] = None
        hints: List[float] = []
        for i, address in enumerate(order):
            if self._soft_backing_off(address):
                # soft breaker (docs/overload.md): the member said
                # STATUS_OVERLOADED within its retry-after window — route
                # around it without an RPC and WITHOUT touching its real
                # breaker (overload is backpressure, not failure)
                self._count_overload_skip(address)
                hints.append(self._backoff_remaining(address))
                continue
            breaker = self._breaker(address)
            if not breaker.allow():
                # rerouted off a breaker-open member: the solve lands on a
                # non-affine member, so it counts as a failover (the
                # session re-homes there until the breaker re-admits)
                self._count_failover(address)
                continue
            client = self._client(address)
            try:
                pending = client.pack_begin(
                    *inputs, n_max=n_max, prof=prof, record=record
                )
            except DeadlineExceededError:
                # OUR deadline, not the member's health: no breaker, no
                # failover — the round already degraded to its FFD floor
                raise
            except OverloadedError as e:
                self._member_overloaded(address, e.retry_after)
                self._count_overload_skip(address)
                hints.append(e.retry_after)
                continue
            except IntegrityError as e:
                # corrupt frame at dispatch/open time: quarantine THIS
                # member (trip, not windowed failure) and try the next —
                # non-retryable on the same member by construction
                last_exc = e
                self._member_corrupt(address, e)
                self._count_failover(address)
                continue
            except Exception as e:
                last_exc = e
                self._member_failure(address, e)
                self._count_failover(address)
                continue
            return self._wrap_wait(
                pending, address, order[i + 1:], inputs, n_max, prof, record
            )
        if hints:
            # the pool is FULL, not broken: a typed verdict so the
            # scheduler's outer remote breaker never trips on pure overload;
            # the soonest member to free sets the caller's hint
            raise OverloadedError(
                f"every solver pool member overloaded (tried {order})",
                retry_after=min(hints),
            )
        raise PoolExhausted(
            f"no solver pool member available (tried {order}): {last_exc}"
        )

    def _count_failover(self, failed: str) -> None:
        metrics.SOLVER_POOL_FAILOVERS.labels(address=failed).inc()
        with self._mu:
            self.failovers += 1

    def _wrap_wait(
        self, pending, address: str, remaining: List[str],
        inputs, n_max: int, prof: Optional[dict], record: bool,
    ):
        def wait():
            try:
                out = pending()
            except DeadlineExceededError:
                # the propagated round budget expired: not this member's
                # fault, and no surviving member could make the deadline
                # either — straight up to the caller's FFD floor
                raise
            except OverloadedError as e:
                # shed mid-flight: sit the member out for its hint window
                # (no breaker state touched) and fail over to the rest of
                # the ring — another member may have headroom
                self._member_overloaded(address, e.retry_after)
                self._count_overload_skip(address)
                return self._failover(
                    address, remaining, inputs, n_max, prof, record, e,
                    failed_is_overloaded=True,
                )
            except IntegrityError as e:
                # corruption discovered at FETCH time (checksum/session
                # guard fired inside the member's wait): quarantine the
                # member and re-solve synchronously on the rest of the ring
                self._member_corrupt(address, e)
                return self._failover(
                    address, remaining, inputs, n_max, prof, record, e
                )
            except Exception as e:
                self._member_failure(address, e)
                return self._failover(
                    address, remaining, inputs, n_max, prof, record, e
                )
            self._member_success(address)
            return out

        return wait

    def _failover(
        self, failed: str, remaining: List[str],
        inputs, n_max: int, prof: Optional[dict], record: bool,
        cause: Exception,
        failed_is_overloaded: bool = False,
    ):
        from karpenter_tpu import obs

        last_exc: Exception = cause
        hints: List[float] = (
            [cause.retry_after] if isinstance(cause, OverloadedError) else []
        )
        for address in remaining:
            if self._soft_backing_off(address):
                self._count_overload_skip(address)
                hints.append(self._backoff_remaining(address))
                continue
            breaker = self._breaker(address)
            if not breaker.allow():
                continue
            # a reroute off a FAILED member is a failover; a reroute off a
            # merely-full one is a soft skip (already counted by the caller)
            if not failed_is_overloaded:
                self._count_failover(failed)
            # synchronous on the surviving member: its RemoteSolver's
            # NEEDS_CATALOG path re-uploads the session transparently
            with obs.tracer().span(
                "solver.pool.failover",
                attrs={"from": failed, "to": address},
            ):
                client = self._client(address)
                try:
                    out = client.pack_begin(
                        *inputs, n_max=n_max, prof=prof, record=record
                    )()
                except DeadlineExceededError:
                    raise  # the WORK's deadline: no member can outrun it
                except OverloadedError as e:
                    self._member_overloaded(address, e.retry_after)
                    self._count_overload_skip(address)
                    hints.append(e.retry_after)
                    failed, failed_is_overloaded = address, True
                    continue
                except IntegrityError as e:
                    last_exc = e
                    self._member_corrupt(address, e)
                    failed, failed_is_overloaded = address, False
                    continue
                except Exception as e:
                    last_exc = e
                    self._member_failure(address, e)
                    failed, failed_is_overloaded = address, False
                    continue
            self._member_success(address)
            return out
        if isinstance(cause, OverloadedError) and last_exc is cause:
            # nothing actually FAILED — the original verdict AND every
            # member visited since was backpressure (a real failure
            # anywhere would have replaced last_exc)
            raise OverloadedError(
                "every solver pool member overloaded during failover",
                retry_after=min(hints),
            )
        raise PoolExhausted(
            f"solver pool exhausted after failover (last member error: {last_exc})"
        )

    def pack(self, *inputs, n_max: int):
        """Synchronous convenience wrapper over ``pack_begin``."""
        return self.pack_begin(*inputs, n_max=n_max)()

    def close(self) -> None:
        with self._mu:
            clients = list(self._clients.values())
        for client in clients:
            try:
                client.close()
            except Exception:
                pass
