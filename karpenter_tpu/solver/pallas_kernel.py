"""Pallas TPU packing kernel: the whole first-fit scan in ONE kernel.

The ``lax.scan`` kernel (kernel.py) dispatches ~P sequential HLO steps; at
10k pods the per-step overhead dominates (hundreds of ms). Here the entire
scan runs inside a single Pallas kernel with the node table resident in
VMEM: the per-pod body is a handful of VPU ops over [*, N] tiles, and the
pod loop is a blocked ``fori_loop`` — no per-step dispatch, no HBM round
trips.

Same contract and assignment-exact semantics as ``kernel.pack`` (the parity
test runs both). TPU constraints shape the implementation:

- dynamic VMEM indexing must be 128-aligned, so pods are processed in
  128-wide blocks: the block loads once (aligned), per-pod values are
  extracted in registers via lane-mask + sum, and the block's assignment
  vector is stored once;
- ``join_table[s, core]`` needs a dynamic scalar read, so the join table
  lives in SMEM;
- ``frontiers[j]`` gathers unroll over the small static signature axis as
  masked selects;
- node-state updates are full-vector masked writes (cheaper than dynamic
  scatters on TPU).

Layouts are transposed so the large axis rides the 128-lane dimension:
pod scalars [6, P] i32, pod requests [R, P] f32, node requests [R, N] f32.
"""

from __future__ import annotations

import logging
import threading
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from karpenter_tpu.solver.kernel import PackResult

logger = logging.getLogger("karpenter.solver")

# pod scalar row indices in the packed [6, P] array
_VALID, _OPEN_SIG, _CORE, _HOST, _HOST_IN_BASE, _OPEN_HOST = range(6)

BIG = 2**30  # plain int: jnp constants would be captured tracers
BLOCK = 128  # lane width; dynamic VMEM indexing must be BLOCK-aligned


def _pack_kernel(
    pod_scal_ref,  # [6, P] i32 (VMEM)
    pod_req_ref,  # [R, P] f32 (VMEM)
    join_ref,  # [S, C] i32 (SMEM — dynamic scalar reads)
    frontiers_ref,  # [S, F, R] f32 (VMEM, static reads)
    daemon_ref,  # [R, 1] f32
    assignment_ref,  # [1, P] i32 out
    node_sig_ref,  # [1, N] i32 out
    node_host_ref,  # [1, N] i32 out
    node_req_ref,  # [R, N] f32 out
    count_ref,  # [1, 1] i32 out (SMEM)
    *,
    n_cap: int,  # logical node limit — N is lane-padded above it, and
    #   opening must stop at the CALLER'S n_max or the saturation-retry
    #   contract (n_nodes == n_max) breaks and assignments index past the
    #   sliced node arrays
):
    P = pod_scal_ref.shape[1]
    N = node_sig_ref.shape[1]
    R = pod_req_ref.shape[0]
    S = frontiers_ref.shape[0]
    F = frontiers_ref.shape[1]

    node_sig_ref[:] = jnp.full((1, N), -1, jnp.int32)
    node_host_ref[:] = jnp.full((1, N), -1, jnp.int32)
    node_req_ref[:] = jnp.zeros((R, N), jnp.float32)
    node_lane = lax.broadcasted_iota(jnp.int32, (1, N), 1)
    blk_lane = lax.broadcasted_iota(jnp.int32, (1, BLOCK), 1)
    daemon = daemon_ref[:]  # [R, 1]

    def block_body(b, count):
        start = pl.multiple_of(b * BLOCK, BLOCK)
        scal_blk = pod_scal_ref[:, pl.ds(start, BLOCK)]  # [6, BLOCK]
        req_blk = pod_req_ref[:, pl.ds(start, BLOCK)]  # [R, BLOCK]

        def pod_body(k, carry):
            count, assign_vec = carry
            at_k = blk_lane == k  # [1, BLOCK]

            def pick(row):  # scalar pod attribute from the loaded block
                return jnp.sum(jnp.where(at_k, scal_blk[row : row + 1, :], 0))

            valid = pick(_VALID) != 0
            open_sig = pick(_OPEN_SIG)
            core = pick(_CORE)
            host = pick(_HOST)
            host_in_base = pick(_HOST_IN_BASE) != 0
            open_host = pick(_OPEN_HOST)
            req = jnp.sum(jnp.where(at_k, req_blk, 0.0), axis=1, keepdims=True)  # [R,1]

            node_sig = node_sig_ref[:]  # [1, N]
            node_host = node_host_ref[:]
            node_req = node_req_ref[:]  # [R, N]
            is_open = node_sig >= 0
            new_req = node_req + req

            # j = join_table[node_sig, core]; fits = ∃f: new_req ≤ frontiers[j,f]
            j = jnp.full((1, N), -1, jnp.int32)
            for s in range(S):
                j = jnp.where(node_sig == s, join_ref[s, core], j)
            fits = jnp.zeros((1, N), jnp.bool_)
            open_fits = jnp.zeros((), jnp.bool_)
            open_req = daemon + req
            for s in range(S):
                fit_s = jnp.zeros((1, N), jnp.bool_)
                open_fit_s = jnp.zeros((), jnp.bool_)
                for f in range(F):
                    limit = frontiers_ref[s, f, :].reshape(R, 1)  # static index
                    fit_s = fit_s | jnp.all(new_req <= limit, axis=0, keepdims=True)
                    open_fit_s = open_fit_s | jnp.all(open_req <= limit)
                fits = fits | ((j == s) & fit_s)
                open_fits = open_fits | ((open_sig == s) & open_fit_s)

            ok_host = (host < 0) | ((node_host == -1) & host_in_base) | (node_host == host)
            ok = (j >= 0) & is_open & ok_host & fits  # [1, N]

            any_ok = jnp.any(ok)
            first_ok = jnp.min(jnp.where(ok, node_lane, BIG))

            can_open = open_fits & (count < n_cap)
            schedulable = valid & (any_ok | can_open)
            target = jnp.where(any_ok, first_ok, count)
            at_target = node_lane == target  # [1, N]

            def extract(vec):  # [1, N] → scalar at target
                return jnp.sum(jnp.where(at_target, vec, 0))

            upd_sig = jnp.where(any_ok, extract(j), open_sig)
            upd_host = jnp.where(
                any_ok, jnp.where(host >= 0, host, extract(node_host)), open_host
            )
            req_target = jnp.sum(jnp.where(at_target, new_req, 0.0), axis=1, keepdims=True)
            upd_req = jnp.where(any_ok, req_target, open_req)  # [R, 1]

            write = schedulable & at_target
            node_sig_ref[:] = jnp.where(write, upd_sig, node_sig)
            node_host_ref[:] = jnp.where(write, upd_host, node_host)
            node_req_ref[:] = jnp.where(write, upd_req, node_req)

            assign_vec = jnp.where(
                at_k, jnp.where(schedulable, target, -1), assign_vec
            )
            count = count + jnp.where(schedulable & ~any_ok, 1, 0).astype(jnp.int32)
            return count, assign_vec

        count, assign_vec = lax.fori_loop(
            0, BLOCK, pod_body, (count, jnp.full((1, BLOCK), -1, jnp.int32))
        )
        assignment_ref[:, pl.ds(start, BLOCK)] = assign_vec
        return count

    count = lax.fori_loop(0, P // BLOCK, block_body, jnp.zeros((), jnp.int32))
    count_ref[0, 0] = count


@partial(jax.jit, static_argnames=("n_max",))
def pack_pallas(
    pod_valid,
    pod_open_sig,
    pod_core,
    pod_host,
    pod_host_in_base,
    pod_open_host,
    pod_req,
    join_table,
    frontiers,
    daemon,
    n_max: int,
) -> PackResult:
    """Same signature/results as ``kernel.pack``, executed as one Pallas
    kernel. ``n_max`` is rounded up to a lane multiple internally; P must be
    a multiple of 128 (encode's buckets are)."""
    P, R = pod_req.shape
    if P % BLOCK != 0:
        raise ValueError(f"pallas pack needs P % {BLOCK} == 0, got {P}")
    n = max(BLOCK, ((n_max + BLOCK - 1) // BLOCK) * BLOCK)
    pod_scal = jnp.stack(
        [
            pod_valid.astype(jnp.int32),
            pod_open_sig.astype(jnp.int32),
            pod_core.astype(jnp.int32),
            pod_host.astype(jnp.int32),
            pod_host_in_base.astype(jnp.int32),
            pod_open_host.astype(jnp.int32),
        ]
    )  # [6, P]
    assignment, node_sig, node_host, node_req_t, count = pl.pallas_call(
        partial(_pack_kernel, n_cap=n_max),
        out_shape=(
            jax.ShapeDtypeStruct((1, P), jnp.int32),
            jax.ShapeDtypeStruct((1, n), jnp.int32),
            jax.ShapeDtypeStruct((1, n), jnp.int32),
            jax.ShapeDtypeStruct((R, n), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
    )(
        pod_scal,
        pod_req.T.astype(jnp.float32),  # [R, P]
        join_table.astype(jnp.int32),
        frontiers.astype(jnp.float32),
        daemon.astype(jnp.float32).reshape(R, 1),
    )
    return PackResult(
        assignment=assignment[0],
        node_sig=node_sig[0, :n_max],
        node_host=node_host[0, :n_max],
        node_req=node_req_t[:, :n_max].T,
        n_nodes=count[0, 0],
    )


def pallas_available() -> bool:
    """Pallas TPU kernels need a real TPU backend (tests run on CPU with the
    lax.scan kernel)."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


# shapes (P, n_max) whose pallas compile/run failed — only those fall back,
# one pathological batch must not degrade every other shape in the process.
# Solve threads and the router's shadow-probe thread write it concurrently
# with other solves' membership checks: mutate under the lock.
_failed_shapes_lock = threading.Lock()
_pallas_failed_shapes: set = set()  # guarded-by: _failed_shapes_lock

# The kernel unrolls the signature × frontier loops (S × F masked selects
# per pod step), so Mosaic compile time scales with S·F. Measured on a
# TPU v5e (P=1024, F=8): S=16 → 2.9s, S=64 → 6.1s, S=128 → 14.1s,
# S=256 → 38.3s — ~2.5× per doubling, extrapolating to ~2min at the
# S=512 closure cap. Beyond this budget the first solve of a new shape
# would blow the latency target on compile alone, so constraint-diverse
# batches take the lax.scan kernel (XLA gathers: compile-invariant in S).
PALLAS_UNROLL_BUDGET = 1024  # max S*F (≈14s one-time compile)


def pallas_shape_eligible(P: int, S: int, F: int) -> bool:
    """Whether a batch shape may take the v1 (unrolled) Pallas kernel —
    used by pack_best and the sharded multi-solve. Shapes past the unroll
    budget are served by the v2 matmul-gather kernel in pack_best; the
    sharded multi-solve keeps the vmapped lax.scan for them."""
    return P % BLOCK == 0 and S * F <= PALLAS_UNROLL_BUDGET and pallas_available()


def pack_best(*args, n_max: int) -> PackResult:
    """The fastest available packing kernel per platform: Pallas on TPU
    (≈4× the lax.scan kernel at 10k pods), the native C++ packer on CPU
    (the reference's in-process FFD loop over the tensor encoding), and
    lax.scan as the universal fallback. ``KARPENTER_PACKER`` forces a
    specific kernel (native | scan | pallas | auto) — benchmarking and
    incident escape hatch."""
    import os

    from karpenter_tpu.solver import kernel as _k

    forced = os.environ.get("KARPENTER_PACKER", "auto").lower()
    if forced == "native":
        from karpenter_tpu.solver import native

        native.native_available(wait=180)  # forced: block for the g++ build
        return native.pack_native(*args, n_max=n_max)
    if forced == "scan":
        return _k.pack(*args, n_max=n_max)
    if forced == "pallas":
        # forced means forced: no silent fallback — fail loudly if the
        # backend can't serve it (incident escape-hatch semantics)
        if not pallas_available():
            raise RuntimeError("KARPENTER_PACKER=pallas but no TPU backend")
        return pack_pallas(*args, n_max=n_max)

    P = args[6].shape[0]  # pod_req
    S, F = args[8].shape[0], args[8].shape[1]  # frontiers
    C = args[7].shape[1]  # join_table
    shape = (P, n_max)
    v1_tried = False
    if shape not in _pallas_failed_shapes and pallas_shape_eligible(P, S, F):
        v1_tried = True
        try:
            return pack_pallas(*args, n_max=n_max)
        except Exception:
            logger.exception(
                "pallas kernel failed for shape %s; trying alternatives", shape
            )
            with _failed_shapes_lock:
                _pallas_failed_shapes.add(shape)
    # when v1 is unavailable (unroll budget exceeded, or its compile failed
    # for this shape): the v2 kernel (signature gathers as MXU matmuls over
    # a one-hot state; compile O(F), independent of S) keeps the batch on
    # the TPU path
    v2_shape = ("v2", P, n_max)
    if (
        v2_shape not in _pallas_failed_shapes
        and not (v1_tried and shape not in _pallas_failed_shapes)
        and P % BLOCK == 0
        and pallas_available()
    ):
        from karpenter_tpu.solver import pallas_kernel_v2 as v2

        if v2.v2_vmem_ok(S, n_max, C, F * args[6].shape[1]):
            try:
                return v2.pack_pallas_v2(*args, n_max=n_max)
            except Exception:
                logger.exception(
                    "pallas v2 kernel failed for shape %s; lax.scan for this shape",
                    v2_shape,
                )
                with _failed_shapes_lock:
                    _pallas_failed_shapes.add(v2_shape)
    if not pallas_available():
        from karpenter_tpu.solver import native

        if native.native_available():
            try:
                return native.pack_native(*args, n_max=n_max)
            except Exception:
                logger.exception("native packer failed; lax.scan fallback")
    return _k.pack(*args, n_max=n_max)
