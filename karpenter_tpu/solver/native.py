"""ctypes binding for the native (C++) first-fit packer.

The shared library is compiled from ``native/ffd_pack.cpp`` on first use
(g++ is part of the toolchain; pybind11 is not, hence ctypes). Same contract
as ``kernel.pack``; used by ``pack_best`` when no TPU backend is present —
the in-process CPU path runs native instead of a 10k-step XLA scan.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from karpenter_tpu.solver.kernel import PackResult

logger = logging.getLogger("karpenter.solver.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "ffd_pack.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "libffd_pack.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None  # guarded-by: _lock
_load_failed = False  # guarded-by: _lock
_build_thread: Optional[threading.Thread] = None  # guarded-by: _lock


def _build_and_load() -> None:
    global _lib, _load_failed
    try:
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            # compile to a unique temp path and atomically rename: concurrent
            # processes sharing the checkout must never dlopen a half-written
            # library (last writer wins, every rename is a complete file)
            tmp = f"{_LIB}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, _LIB)
        lib = ctypes.CDLL(_LIB)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.ffd_pack.restype = ctypes.c_int32
        lib.ffd_pack.argtypes = [
            u8p, i32p, i32p, i32p, u8p, i32p, f32p, i32p, f32p, f32p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            i32p, i32p, i32p, f32p,
        ]
        with _lock:
            _lib = lib
    except Exception:
        logger.exception("native packer unavailable; using JAX kernel")
        with _lock:
            _load_failed = True


def _kick_build() -> None:
    """Start the (one-time) background build; never blocks the caller —
    a first solve must not wait out a g++ compile."""
    global _build_thread
    with _lock:
        if _lib is not None or _load_failed or (
            _build_thread is not None and _build_thread.is_alive()
        ):
            return
        _build_thread = threading.Thread(
            target=_build_and_load, daemon=True, name="ffd-pack-build"
        )
        _build_thread.start()


def native_available(wait: Optional[float] = None) -> bool:
    """Non-blocking by default: kicks the background build and reports
    whether the library is loaded NOW. Pass ``wait`` seconds to block for
    the build (tests do)."""
    _kick_build()
    if wait is not None:
        thread = _build_thread
        if thread is not None:
            thread.join(timeout=wait)
    with _lock:
        return _lib is not None


def _ensure_lib() -> Optional[ctypes.CDLL]:
    _kick_build()
    with _lock:
        return _lib


def pack_native(
    pod_valid,
    pod_open_sig,
    pod_core,
    pod_host,
    pod_host_in_base,
    pod_open_host,
    pod_req,
    join_table,
    frontiers,
    daemon,
    n_max: int,
) -> PackResult:
    """Same signature/results as ``kernel.pack``, on the CPU in native code."""
    lib = _ensure_lib()
    if lib is None:
        raise RuntimeError("native packer unavailable")

    def as_np(a, dtype):
        return np.ascontiguousarray(np.asarray(a), dtype=dtype)

    valid = as_np(pod_valid, np.uint8)
    open_sig = as_np(pod_open_sig, np.int32)
    core = as_np(pod_core, np.int32)
    host = as_np(pod_host, np.int32)
    host_in_base = as_np(pod_host_in_base, np.uint8)
    open_host = as_np(pod_open_host, np.int32)
    req = as_np(pod_req, np.float32)
    join = as_np(join_table, np.int32)
    fr = as_np(frontiers, np.float32)
    dm = as_np(daemon, np.float32)

    P, R = req.shape
    S, F, _ = fr.shape
    C = join.shape[1]
    assignment = np.empty(P, np.int32)
    node_sig = np.empty(n_max, np.int32)
    node_host = np.empty(n_max, np.int32)
    node_req = np.empty((n_max, R), np.float32)

    def ptr(a, ct):
        return a.ctypes.data_as(ctypes.POINTER(ct))

    count = lib.ffd_pack(
        ptr(valid, ctypes.c_uint8), ptr(open_sig, ctypes.c_int32),
        ptr(core, ctypes.c_int32), ptr(host, ctypes.c_int32),
        ptr(host_in_base, ctypes.c_uint8), ptr(open_host, ctypes.c_int32),
        ptr(req, ctypes.c_float), ptr(join, ctypes.c_int32),
        ptr(fr, ctypes.c_float), ptr(dm, ctypes.c_float),
        P, R, S, C, F, n_max,
        ptr(assignment, ctypes.c_int32), ptr(node_sig, ctypes.c_int32),
        ptr(node_host, ctypes.c_int32), ptr(node_req, ctypes.c_float),
    )
    if count < 0:
        raise RuntimeError(f"native packer error {count}")
    return PackResult(
        assignment=assignment,
        node_sig=node_sig,
        node_host=node_host,
        node_req=node_req,
        n_nodes=np.int32(count),
    )
