"""Measured-cost backend routing for the packing solve.

Round-4 finding (VERDICT r4 weak #3): ``auto`` routing preferred the device
path whenever a TPU was attached — by platform, never by cost — so
production deployments routed every solve onto a path the bench showed was
slower at those shapes. This router makes backend choice empirical: an EMA
of the measured end-to-end pack time per (backend, shape-class), with the
native C++ packer a first-class contender rather than a no-TPU fallback.

Semantics:

- **Cold start**: every candidate is tried once (in the caller's preference
  order) before any exploitation, so each backend owns a measurement. The
  device path is listed first so its one-time XLA compile lands in the
  worker's warmup solve, where the production runtime already pays it.
- **Exploit**: every solve routes to the backend with the lowest EMA for
  the shape class — ``choose`` never sacrifices a production solve to
  exploration, so the winner's latency distribution (and the p99 the bench
  publishes) is unpolluted by probe iterations.
- **Shadow re-probe**: ``should_probe`` fires every ``probe_every``-th
  solve of a shape class (64 by default: drift — tunnel weather, host
  load, chip attach — moves on a minutes timescale, while a device probe
  on a core-starved host can shadow a measured solve, so probes are kept
  rare), rising to every 8th while the class's EMAs are NEAR-TIED (within
  1.25×: a stale runner-up in a close race can silently drift into a real
  loss, and refreshing it costs nothing on the critical path). The caller
  re-measures the LOSER(s) on a daemon thread (a device probe's fetch
  wait releases the GIL; a losing native probe is slow precisely when it
  lost, so it never runs inline) so a drifting environment can re-win the
  route. EMA alpha 0.4 forgets a compile-poisoned first sample within a
  few probes.

The default router is PROCESS-SHARED (``default_router``): schedulers come
and go — worker hot-swap on spec change, consolidation's per-plan shadow
scheduler — but the cost landscape is a property of the machine, so a fresh
scheduler must not re-pay cold start on shapes the process already
measured. That sharing means ``choose``/``record`` are called from several
workers' solve threads and from shadow-probe threads concurrently; a small
internal lock keeps the counters and EMAs consistent (the operations are
dict reads/writes — the lock is uncontended and nanoseconds-cheap next to
any pack).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

EMA_ALPHA = 0.4
PROBE_EVERY = 64
# recorded instead of elapsed time when a backend RAISES: a fast-failing
# backend must lose the route, not win it with a microsecond "cost".
# Probes rehabilitate a fixed backend (alpha pulls the EMA back down).
FAILURE_PENALTY_S = 60.0


class CostRouter:
    def __init__(self, probe_every: int = PROBE_EVERY, alpha: float = EMA_ALPHA):
        self.probe_every = probe_every
        self.alpha = alpha
        self._ema: Dict[Tuple[str, tuple], float] = {}  # guarded-by: self._lock
        self._solves: Dict[tuple, int] = {}  # guarded-by: self._lock
        # brownout knobs (resilience/brownout.py): paused probes keep
        # exploration entirely off an overloaded machine, and a bias > 1
        # inflates every NON-native EMA at choose time so the host FFD/
        # native floor wins marginal races while the ladder is engaged —
        # the EMAs themselves stay unpolluted for recovery
        self._probes_paused = False  # guarded-by: self._lock
        self._brownout_bias = 1.0  # guarded-by: self._lock
        self._lock = threading.Lock()

    # EMAs within this factor are a NEAR-TIE: the run-to-run noise exceeds
    # the gap, so the nominal winner is a coin flip whose runner-up EMA
    # must not go stale (drift silently turns the tie into a real loss).
    # Ties raise the SHADOW-PROBE cadence — never the production route:
    # exploration stays off the critical path even when the race is close.
    NEAR_TIE = 1.25

    def choose(self, key: tuple, candidates: List[str]) -> str:
        """Pick the backend for this solve: first unmeasured candidate (in
        preference order) during cold start, then always the cheapest."""
        if len(candidates) == 1:
            return candidates[0]
        with self._lock:
            self._solves[key] = self._solves.get(key, 0) + 1
            for c in candidates:
                if (c, key) not in self._ema:
                    return c
            bias = self._brownout_bias
            return min(
                candidates,
                key=lambda c: self._ema[(c, key)] * (1.0 if c == "native" else bias),
            )

    def should_probe(self, key: tuple) -> bool:
        """True every ``probe_every``-th solve of this shape class — every
        ``probe_every // 8``-th while the key's EMAs are near-tied — so the
        caller re-measures the losing backend(s) off the critical path."""
        with self._lock:
            if self._probes_paused:
                return False
        n = self._solves.get(key, 0)
        if not self.probe_every or n == 0:
            return False
        cadence = self.probe_every
        with self._lock:
            emas = sorted(v for (b, k), v in self._ema.items() if k == key)
        if len(emas) > 1 and emas[1] <= self.NEAR_TIE * emas[0]:
            cadence = max(4, self.probe_every // 8)
        return n % cadence == 0

    def record(self, key: tuple, backend: str, seconds: float) -> None:
        k = (backend, key)
        with self._lock:
            prev = self._ema.get(k)
            self._ema[k] = (
                seconds if prev is None else prev + self.alpha * (seconds - prev)
            )

    def record_failure(self, key: tuple, backend: str) -> None:
        """A backend RAISED for this shape class: record the failure
        penalty, not the (tiny) elapsed time — a fast-failing backend must
        lose the route, not win it with a microsecond "cost". Shadow probes
        (and the caller's circuit breakers' half-open probes) rehabilitate
        a fixed backend: alpha pulls the EMA back down."""
        self.record(key, backend, FAILURE_PENALTY_S)

    def ema(self, key: tuple, backend: str) -> Optional[float]:
        with self._lock:
            return self._ema.get((backend, key))

    # -- brownout knobs (resilience/brownout.py) ----------------------------

    def set_probes_paused(self, paused: bool) -> None:
        """Brownout rung 1: shadow probes re-measure LOSING backends — pure
        exploration, the first work an overloaded machine sheds."""
        with self._lock:
            self._probes_paused = bool(paused)

    def probes_paused(self) -> bool:
        with self._lock:
            return self._probes_paused

    def set_brownout_bias(self, factor: float) -> None:
        """Brownout rung 3: inflate non-native EMAs by ``factor`` at choose
        time (1.0 = no bias) so marginal device-vs-native races route to
        the host path while the ladder is engaged. The stored EMAs are
        untouched: recovery is instant when the bias clears."""
        with self._lock:
            self._brownout_bias = max(float(factor), 1.0)

    def brownout_bias(self) -> float:
        with self._lock:
            return self._brownout_bias

    def report(self) -> Dict[str, float]:
        """Flat {backend@key: ema_seconds} snapshot (bench/metrics surface)."""
        with self._lock:
            items = list(self._ema.items())
        return {
            f"{backend}@{'x'.join(map(str, key))}": round(v, 6)
            for (backend, key), v in sorted(items)
        }


# Process-shared default: schedulers come and go (worker hot-swap on spec
# change, consolidation's per-plan shadow scheduler) but the cost landscape
# is a property of the machine — a fresh scheduler must not re-pay cold
# start on shapes the process has already measured. Several workers boot
# concurrently (provisioning Apply runs per-provisioner), so the lazy init
# must be locked — two racing initializations would hand different workers
# different routers and split the cost landscape they exist to share.
_default_lock = threading.Lock()
_default: Optional[CostRouter] = None  # guarded-by: _default_lock


def default_router() -> CostRouter:
    global _default
    with _default_lock:
        if _default is None:
            _default = CostRouter()
        return _default


def reset_default() -> None:
    """Tests isolate router learning with this."""
    global _default
    with _default_lock:
        _default = None
