"""The solve service: gRPC transport to a JAX solver sidecar.

SURVEY §5.8 / BASELINE north star: the reconcile loop ships the encoded solve
request to a sidecar owning the TPU (host↔TPU over PCIe/ICI being the analog
of the reference's in-process function call), selected per-process via
``--solver-service-address``; the in-process packer remains the fallback.

Wire format: **flat little-endian buffers, not protobuf message trees**
(SURVEY hard-part #6 — 10k pods × 512 types must round-trip well under
100ms). A message is::

    magic "KTPU" | u16 version | u16 array count
    per array: u8 dtype code | u8 ndim | u32 dims... | raw C-order bytes

The RPC surface is one unary method ``/karpenter.solver.v1.Solver/Pack``
registered through gRPC's generic handler with identity (bytes) serializers,
so no generated stubs are needed. Request = the 10 ``kernel.pack`` inputs
(+ n_max as a scalar array); response = ONE fused i32 buffer (see
``kernel.fuse_result``) the client splits back into a ``PackResult``.
"""

from __future__ import annotations

import logging
import struct
import threading
import time
from concurrent import futures
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("karpenter.solver.service")

MAGIC = b"KTPU"
# v2: response switched from 5 per-field arrays to one fused buffer — a
# version skew must fail loudly, not degrade into a silent parse error
VERSION = 2
METHOD = "/karpenter.solver.v1.Solver/Pack"
HEALTH_METHOD = "/karpenter.solver.v1.Solver/Health"
SERVING = b"SERVING"
NOT_SERVING = b"NOT_SERVING"

_DTYPES = {0: np.dtype(np.bool_), 1: np.dtype(np.int32), 2: np.dtype(np.float32)}
_DTYPE_CODES = {v: k for k, v in _DTYPES.items()}


# ---------------------------------------------------------------------------
# flat buffer codec
# ---------------------------------------------------------------------------


def pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    parts: List[bytes] = [MAGIC, struct.pack("<HH", VERSION, len(arrays))]
    for a in arrays:
        # NOT ascontiguousarray: it promotes 0-d scalars to 1-d
        a = np.asarray(a, order="C")
        code = _DTYPE_CODES.get(a.dtype)
        if code is None:
            # normalize off-spec dtypes (e.g. int64 scalars, float64)
            if np.issubdtype(a.dtype, np.floating):
                a = a.astype(np.float32)
            elif np.issubdtype(a.dtype, np.bool_):
                a = a.astype(np.bool_)
            else:
                a = a.astype(np.int32)
            code = _DTYPE_CODES[a.dtype]
        parts.append(struct.pack("<BB", code, a.ndim))
        parts.append(struct.pack(f"<{a.ndim}I", *a.shape))
        parts.append(a.tobytes())
    return b"".join(parts)


def unpack_arrays(data: bytes) -> List[np.ndarray]:
    if data[:4] != MAGIC:
        raise ValueError("bad magic")
    version, count = struct.unpack_from("<HH", data, 4)
    if version != VERSION:
        raise ValueError(f"unsupported version {version}")
    offset = 8
    out: List[np.ndarray] = []
    for _ in range(count):
        code, ndim = struct.unpack_from("<BB", data, offset)
        offset += 2
        shape = struct.unpack_from(f"<{ndim}I", data, offset)
        offset += 4 * ndim
        dtype = _DTYPES[code]
        n_items = int(np.prod(shape, dtype=np.int64))  # prod(()) == 1 → scalar
        n_bytes = n_items * dtype.itemsize
        arr = np.frombuffer(data, dtype=dtype, count=n_items, offset=offset).reshape(shape)
        offset += n_bytes
        out.append(arr)
    return out


# ---------------------------------------------------------------------------
# server (the JAX/TPU sidecar)
# ---------------------------------------------------------------------------


class SolverService:
    """Owns the jitted kernel; one Pack call = one batched solve.

    Readiness = the backend compiled and executed one tiny solve (warmup);
    liveness = the process responds at all. Round 1 shipped neither — a hung
    sidecar was only discovered via the 5s client deadline per batch
    (VERDICT weak #7)."""

    def __init__(self):
        self.ready = threading.Event()

    def warmup(self) -> None:
        """Compile + run a minimal solve so readiness implies a working
        backend, not just a bound port."""
        try:
            from karpenter_tpu.cloudprovider.fake import instance_types
            from karpenter_tpu.cloudprovider.requirements import catalog_requirements
            from karpenter_tpu.kube.client import Cluster
            from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
            from karpenter_tpu.scheduling.topology import Topology
            from karpenter_tpu.solver import encode as enc
            from karpenter_tpu.testing.factories import make_pod, make_provisioner

            catalog = instance_types(4)
            constraints = make_provisioner(solver="tpu").spec.constraints
            constraints.requirements = constraints.requirements.merge(
                catalog_requirements(catalog)
            )
            pods = sort_pods_ffd([make_pod(requests={"cpu": "0.1"}) for _ in range(4)])
            cluster = Cluster()
            Topology(cluster).inject(constraints, pods)
            batch = enc.encode(
                constraints, catalog, pods, daemon_overhead(cluster, constraints)
            )
            self.solve_bytes(
                pack_arrays(
                    [np.asarray(a) for a in batch.pack_args()]
                    + [np.asarray([len(batch.pod_valid)], np.int32)]
                )
            )
            logger.info("solver warmup complete")
        except Exception:
            logger.exception("solver warmup failed; staying unready")
            return
        self.ready.set()

    def warmup_loop(self, max_backoff: float = 60.0) -> None:
        """Retry warmup with capped decorrelated-jitter backoff until it
        succeeds — a transient failure (TPU not plumbed yet) must not leave
        the pod NOT_SERVING forever with a healthy liveness probe, and a
        fleet of sidecars restarting together must not re-warm in lockstep
        against a shared bottleneck (resilience/policy.py)."""
        from karpenter_tpu.resilience import decorrelated_jitter

        backoffs = decorrelated_jitter(1.0, cap=max_backoff)
        while not self.ready.is_set():
            self.warmup()
            if self.ready.is_set():
                return
            time.sleep(next(backoffs))

    def health_bytes(self, request: bytes) -> bytes:
        return SERVING if self.ready.is_set() else NOT_SERVING

    def solve_bytes(self, request: bytes) -> bytes:
        import jax

        from karpenter_tpu.solver import kernel

        from karpenter_tpu.solver.pallas_kernel import pack_best

        arrays = unpack_arrays(request)
        *inputs, n_max_arr = arrays
        n_max = int(n_max_arr.reshape(-1)[0])
        result = pack_best(*inputs, n_max=n_max)
        # one fused device→host transfer on the sidecar too — per-array
        # fetches each pay the full device round trip
        buf = jax.device_get(kernel.fuse_result(result))
        return pack_arrays([np.asarray(buf)])


def serve(
    address: str = "127.0.0.1:50051",
    max_workers: int = 4,
    health_port: int = 0,
    warmup: bool = False,
):
    """Start the sidecar server; returns the grpc server object.

    ``health_port`` > 0 additionally serves HTTP ``/healthz`` (liveness,
    always 200 once the process is up) and ``/readyz`` (503 until the warmup
    solve completes) for kubelet probes (deploy/solver.yaml). ``warmup``
    runs the compile-warming solve in the background; without it readiness
    is immediate (tests, in-process use)."""
    import grpc

    service = SolverService()

    def handler_fn(method_name, unused_handler_call_details=None):
        if method_name.method == METHOD:
            return grpc.unary_unary_rpc_method_handler(
                lambda request, ctx: service.solve_bytes(request),
                request_deserializer=None,  # raw bytes in
                response_serializer=None,  # raw bytes out
            )
        if method_name.method == HEALTH_METHOD:
            return grpc.unary_unary_rpc_method_handler(
                lambda request, ctx: service.health_bytes(request),
                request_deserializer=None,
                response_serializer=None,
            )
        return None

    class Handler(grpc.GenericRpcHandler):
        def service(self, handler_call_details):
            return handler_fn(handler_call_details)

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ("grpc.max_send_message_length", 256 * 1024 * 1024),
        ],
    )
    server.add_generic_rpc_handlers((Handler(),))
    server.add_insecure_port(address)
    server.start()
    if warmup:
        threading.Thread(target=service.warmup_loop, daemon=True).start()
    else:
        service.ready.set()
    if health_port:
        server.health_server = _serve_health(service, health_port)
    server.solver_service = service
    logger.info("solver service listening on %s", address)
    return server


def _serve_health(service: SolverService, port: int):
    """Plain-HTTP probe endpoints for kubelet."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Probe(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/healthz":
                code, body = 200, b"ok"
            elif self.path == "/readyz":
                if service.ready.is_set():
                    code, body = 200, b"ok"
                else:
                    code, body = 503, b"warming"
            else:
                code, body = 404, b"not found"
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    httpd = ThreadingHTTPServer(("0.0.0.0", port), Probe)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


# ---------------------------------------------------------------------------
# client (lives in the controller process)
# ---------------------------------------------------------------------------


class RemoteSolver:
    """Drop-in for ``kernel.pack``: ships the arrays to the sidecar and
    returns the PackResult tuple as host numpy arrays."""

    def __init__(self, address: str, timeout: float = 30.0, cold_timeout: float = 180.0):
        import grpc

        self.address = address
        self.timeout = timeout
        # first call per (P, n_max) shape must cover the sidecar's XLA
        # compile; later calls get the short deadline
        self.cold_timeout = cold_timeout
        self._warm_shapes = set()
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
            ],
        )
        self._call = self._channel.unary_unary(METHOD)
        self._health_call = self._channel.unary_unary(HEALTH_METHOD)

    def health(self, timeout: float = 2.0) -> bool:
        """True when the sidecar reports SERVING (warmup done)."""
        try:
            return self._health_call(b"", timeout=timeout) == SERVING
        except Exception:
            return False

    def pack(self, *inputs, n_max: int):
        from karpenter_tpu.solver.kernel import split_result

        request = pack_arrays(
            [np.asarray(a) for a in inputs] + [np.asarray([n_max], np.int32)]
        )
        p = len(inputs[0])
        shape = (p, n_max)
        timeout = self.timeout if shape in self._warm_shapes else self.cold_timeout
        response = self._call(request, timeout=timeout)
        self._warm_shapes.add(shape)
        (buf,) = unpack_arrays(response)
        r = inputs[6].shape[1]  # pod_req
        return split_result(buf, p, n_max, r)

    def close(self) -> None:
        self._channel.close()


def main(argv: Optional[List[str]] = None) -> None:
    """Sidecar entrypoint: ``python -m karpenter_tpu.solver.service``."""
    import argparse
    import time

    ap = argparse.ArgumentParser(prog="karpenter-solver-service")
    ap.add_argument("--address", default="127.0.0.1:50051")
    ap.add_argument("--max-workers", type=int, default=4)
    ap.add_argument("--health-port", type=int, default=8081)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = serve(args.address, args.max_workers, health_port=args.health_port, warmup=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop(grace=2)


if __name__ == "__main__":
    main()
