"""The solve service: gRPC transport to a JAX solver sidecar.

SURVEY §5.8 / BASELINE north star: the reconcile loop ships the encoded solve
request to a sidecar owning the TPU (host↔TPU over PCIe/ICI being the analog
of the reference's in-process function call), selected per-process via
``--solver-service-address``; the in-process packer remains the fallback.

Wire format: **flat little-endian buffers, not protobuf message trees**
(SURVEY hard-part #6 — 10k pods × 512 types must round-trip well under
100ms). A message is::

    magic "KTPU" | u16 version | u16 array count
    per array: u8 dtype code | u8 ndim | u32 dims... | raw C-order bytes

The RPC surface is served through gRPC's generic handler with identity
(bytes) serializers, so no generated stubs are needed.

**v3 — session-based transport** (BENCH_r05: the wire, not the kernel, gates
the <100ms target — ``transport_rtt_floor_ms=106`` and 114.9ms of the worst
iteration in ``pack_fetch``). The catalog-side tensors (join table,
frontiers, daemon vector) are solve-INVARIANT per catalog generation, yet v2
shipped them with every Pack. v3 makes the sidecar stateful per catalog
fingerprint:

- ``/Solver/OpenSession`` uploads the catalog-side tensors once, keyed by a
  content fingerprint (:func:`catalog_session_key` — the closure of
  ``encode.catalog_fingerprint`` materialized as arrays); the sidecar pins
  them on device (``jax.device_put``) in a bounded LRU with TTL eviction;
- each ``Pack`` carries the 16-byte session key plus ONLY the pod-side
  arrays — the steady-state payload excludes catalog bytes entirely;
- a fingerprint miss (LRU/TTL eviction, or a restarted sidecar whose store
  is empty) answers ``NEEDS_CATALOG`` and the client transparently re-opens
  and retries once;
- version skew fails LOUDLY, exactly as the v1→v2 bump did: a v2 frame hits
  ``unsupported version 2`` server-side, never a silent mis-parse.

Every response leads with an i32 status array (``STATUS_OK`` /
``STATUS_NEEDS_CATALOG``) so transport-level errors stay distinguishable
from in-band protocol state.

The client half (:class:`RemoteSolver`) splits dispatch from fetch
(``pack_begin`` → ``wait()``): the RPC goes out as a gRPC future, so the
scheduler can release its solve lock and encode batch i+1 while solve i is
in flight — only the fused-result fetch blocks (docs/solver-transport.md
has the pipeline diagram).

**Trace-context trailer** (docs/observability.md): when the client has an
active span, ``Pack``/``OpenSession`` requests carry one extra i32 array —
the 24-byte trace context (trace id + span id) — AFTER the protocol's
fixed arrays. A frame without it is a perfectly valid v3 frame (absent =
no trace), and the Pack trailer is CAPABILITY-gated for rolling upgrades:
the sidecar advertises ``PROTO_TRACE_TRAILER`` in its OpenSession response
payload (which old clients never read, over a frame old servers already
tolerate growing), and a client only appends the Pack trailer after seeing
the bit — old/new peers interop cleanly in either deploy order, while
actual version skew still fails loudly at the codec. A
traced ``Pack`` response appends an f32 ``[solve_s, fetch_s, serialize_s]``
trailer so the sidecar's half of the RTT becomes attributable client-side;
the sidecar also opens real child spans (``sidecar.pack`` →
``sidecar.solve``/``sidecar.fetch``/``sidecar.serialize``,
``sidecar.device_put`` on session open) into its OWN trace ring, served at
``GET /debug/traces`` on its health port.

**Overload control** (docs/overload.md): a bounded :class:`AdmissionGate`
fronts the solve executor (``--solver-max-inflight`` concurrent solves +
``--solver-queue-depth`` queued; past that ``STATUS_OVERLOADED`` with an
f32 retry-after hint, which ``SolverPool`` honors as a soft breaker — a
shed is backpressure, never a breaker-tripping failure). The round
``Budget``'s remaining seconds ride the Pack frame as a second optional
trailer (f32[1], gated on the ``PROTO_DEADLINE`` capability bit exactly
like the trace trailer), and the sidecar re-checks it after queueing so
already-doomed work sheds with ``STATUS_DEADLINE_EXCEEDED`` *before*
device dispatch — which the client treats as non-retryable, straight to
its FFD floor. New-session uploads are additionally refused under an HBM
headroom floor (``--hbm-floor-bytes``) while resident-session solves keep
flowing.

**End-to-end integrity** (docs/integrity.md): with ``--pack-checksum`` on
and the sidecar advertising ``PROTO_CHECKSUM``, every Pack exchange carries
a blake2b-64 frame checksum both ways (one more array in the ordinary
framing, digest over everything between the header and the trailer) and
the response echoes the catalog session key it was solved against. A
digest mismatch — either side — is a typed
:class:`~karpenter_tpu.resilience.integrity.IntegrityError`, never a
silently wrong array; a wrong-session echo is audited and recovered
through the NEEDS_CATALOG machinery (one forced re-open, then
IntegrityError). Both are NON-retryable on the same member: the pool
quarantines the member (``CircuitBreaker.trip`` — the correctness edge)
and fails the solve over through the ring.
"""

from __future__ import annotations

import hashlib
import logging
import math
import struct
import threading
import time
from collections import OrderedDict
from concurrent import futures
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

# stdlib-only submodule imports: the typed overload/integrity verdicts must
# exist in the sidecar's trimmed images too (resilience/__init__ would pull
# the metrics registry)
from karpenter_tpu.resilience.integrity import IntegrityError
from karpenter_tpu.resilience.overload import (
    DeadlineExceededError,
    OverloadedError,
)

logger = logging.getLogger("karpenter.solver.service")

MAGIC = b"KTPU"
# v2: response switched from 5 per-field arrays to one fused buffer.
# v3: stateful sessions — Pack carries a session key + pod-side arrays only,
# responses lead with a status word, OpenSession uploads the catalog side.
# A version skew must fail loudly, not degrade into a silent parse error.
VERSION = 3
METHOD = "/karpenter.solver.v1.Solver/Pack"
OPEN_SESSION_METHOD = "/karpenter.solver.v1.Solver/OpenSession"
# persistent bidirectional stream (solver/stream.py): every message wraps
# an UNCHANGED unary frame in a correlation-id envelope, so the unary and
# streamed transports share one codec, one capability set, one test corpus
STREAM_METHOD = "/karpenter.solver.v1.Solver/SolveStream"
HEALTH_METHOD = "/karpenter.solver.v1.Solver/Health"
SERVING = b"SERVING"
NOT_SERVING = b"NOT_SERVING"

# in-band response status (first i32 array of every v3 response).
# DEADLINE_EXCEEDED: the propagated round budget expired before device
# dispatch — non-retryable by construction (the client goes straight to
# its FFD floor, never a retry storm). OVERLOADED: the bounded admission
# queue (or HBM pressure) refused the work; the response payload carries
# an f32 retry-after hint the pool honors as a soft breaker. A status
# word neither side knows fails LOUD client-side, like version skew.
STATUS_OK = 0
STATUS_NEEDS_CATALOG = 1
STATUS_DEADLINE_EXCEEDED = 2
STATUS_OVERLOADED = 3
# INTEGRITY: the request frame failed its end-to-end checksum server-side —
# the bytes that arrived are not the bytes that were sent. Typed and
# non-retryable-on-the-SAME-member client-side (the pool quarantines the
# path and fails over; retrying corrupt transport would be a coin flip).
STATUS_INTEGRITY = 4
# NEEDS_DELTA_BASE: a delta-framed Pack referenced a resident pod base
# (by its 16-byte epoch digest) the sidecar does not hold — restart, LRU
# eviction, or a patch whose recomputed content digest disagreed with the
# epoch it claimed to produce. Retryable exactly like NEEDS_CATALOG: the
# client rebuilds a full ``DELTA_ESTABLISH`` frame and redispatches. A
# stale base NEVER solves — the digest recompute is the guard
# (docs/delta-encoding.md).
STATUS_NEEDS_DELTA_BASE = 5

# capability bits a sidecar advertises in its OpenSession RESPONSE payload
# (old clients never read that payload; old servers never send it — the one
# frame both sides already tolerate growing). A client may only append the
# Pack trace-context trailer after seeing this bit: an old sidecar's
# `*pod_arrays` unpack would swallow the trailer as an extra pod array and
# crash the solve mid-rolling-upgrade. PROTO_DEADLINE gates the f32
# remaining-budget trailer the same way (docs/overload.md).
PROTO_TRACE_TRAILER = 1
PROTO_DEADLINE = 2
# PROTO_CHECKSUM gates the integrity feature pair (docs/integrity.md): a
# per-frame blake2b-64 checksum trailer on Pack requests/responses, and the
# Pack response echoing the catalog session key it was solved against. Both
# would crash or silently confuse an old peer's positional parse, so the
# client engages them only after seeing this bit — the same rolling-upgrade
# contract as the trace/deadline trailers.
PROTO_CHECKSUM = 4
# PROTO_STREAM advertises the persistent multiplexed stream transport
# (docs/solver-transport.md § Streaming): a client only opens SolveStream
# after seeing the bit — an old sidecar never advertises it, a new sidecar
# keeps serving unary forever — so rolling upgrades interop in either
# order, exactly like the trailer capabilities.
PROTO_STREAM = 8
# PROTO_DELTA advertises the resident pod-side store (docs/delta-encoding.md):
# a client that saw the bit may frame Pack requests as per-round deltas
# against a pod base the sidecar keeps resident — establish / elide / patch,
# addressed by content-keyed epoch digests. An old sidecar never advertises
# it (the client keeps shipping full pod arrays); an old client never sets
# PACK_FLAG_DELTA (the server parses the classic positional layout) — the
# same either-order rolling-upgrade contract as every other bit.
PROTO_DELTA = 16
PROTO_FEATURES = (
    PROTO_TRACE_TRAILER | PROTO_DEADLINE | PROTO_CHECKSUM | PROTO_STREAM
    | PROTO_DELTA
)

# Pack-request flags (optional third word of the n_max array; old servers
# read words 0-1 and ignore the rest, and the client only sends it after
# the server advertised PROTO_CHECKSUM anyway): bit 0 asks the server to
# echo the session key the solve ran against — the client's stale-session /
# wrong-catalog-generation guard.
PACK_FLAG_ECHO_SESSION = 1
# bit 1 marks a delta-framed request (PROTO_DELTA peers only): the array
# after the vals word is the i32[10] delta header, and the pod arrays that
# follow depend on its kind — see the delta framing block below.
PACK_FLAG_DELTA = 2

# admission-control defaults (docs/overload.md): the executor admits
# max_inflight concurrent solves, queues queue_depth more, and refuses the
# rest with STATUS_OVERLOADED + the retry-after hint — queues bounded by
# decision, not by memory.
MAX_INFLIGHT = 4
QUEUE_DEPTH = 16
OVERLOAD_RETRY_AFTER_S = 1.0

# sidecar session store bounds: one entry per live catalog generation —
# a handful of provisioners each see one catalog at a time, so a small LRU
# holds the working set; TTL reclaims device memory for catalogs no client
# has touched in a while (a dropped controller never closes its session).
SESSION_MAX = 8
SESSION_TTL_S = 900.0

# ---------------------------------------------------------------------------
# device-memory telemetry (the resource-side half of the SLO story: the
# latency histograms can see a pack_fetch spike, only these gauges can say
# whether session churn was filling HBM at the time)
# ---------------------------------------------------------------------------


def _resident_nbytes(resident) -> int:
    """Bytes pinned on device by one session's catalog tensors."""
    return int(sum(int(getattr(a, "nbytes", 0) or 0) for a in resident))


def _session_label(key: bytes) -> str:
    return key.hex()[:12]


def _publish_session_hbm(key: bytes, nbytes: int) -> None:
    try:
        from karpenter_tpu import metrics

        metrics.SOLVER_SESSION_HBM.labels(session=_session_label(key)).set(nbytes)
    except Exception:
        pass  # the sidecar's trimmed images may lack the registry


def _drop_session_hbm(key: bytes) -> None:
    try:
        from karpenter_tpu import metrics

        metrics.SOLVER_SESSION_HBM.remove(_session_label(key))
    except Exception:
        pass  # never-published label or trimmed registry


def publish_device_headroom() -> Optional[int]:
    """Set the device-memory headroom gauge from the backend's
    memory_stats; returns the headroom (None when the backend does not
    report memory — the CPU test rig — in which case the gauge stays
    unset rather than lying with a zero)."""
    try:
        import jax

        device = jax.devices()[0]
        stats = device.memory_stats() or {}
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        in_use = stats.get("bytes_in_use")
        if not limit or in_use is None:
            return None
        headroom = max(int(limit) - int(in_use), 0)
        from karpenter_tpu import metrics

        metrics.SOLVER_HBM_HEADROOM.labels(device=str(device.id)).set(headroom)
        return headroom
    except Exception:
        return None


# ``kernel.pack`` takes 7 pod-side arrays then the 3 catalog-side ones
# (join_table, frontiers, daemon) — the split the session protocol is
# built around (see EncodedBatch.pack_args).
N_POD_ARRAYS = 7

# ---------------------------------------------------------------------------
# delta framing (docs/delta-encoding.md)
# ---------------------------------------------------------------------------
#
# With PACK_FLAG_DELTA set, the array right after the vals word is an
# i32[10] header — [kind, n_idx, base_epoch (4×i32 = 16 bytes), new_epoch
# (4×i32)] — shape-distinct from every other trailer (the trace context is
# i32[6], the session echo i32[4]), so shape/dtype-addressed parsers stay
# unambiguous. The epoch is a blake2b-16 content digest of the 7 pod-side
# arrays; what follows the header depends on kind:
#
# - ESTABLISH: the 7 full pod arrays. The sidecar verifies their digest IS
#   new_epoch (a claim that disagrees with the content is refused as
#   INTEGRITY, exactly like the session-key check) and pins them resident.
# - ELIDE: nothing — the pod side is byte-identical to the resident base
#   named by new_epoch. A miss answers NEEDS_DELTA_BASE.
# - PATCH: one i32[n_idx] row-index array, then the 7 arrays sliced to the
#   changed rows. The sidecar copies the base, applies the rows, and
#   RECOMPUTES the digest — disagreement with new_epoch answers
#   NEEDS_DELTA_BASE (epoch mismatch counted), never a stale-tensor solve.
DELTA_HEADER_WORDS = 10
DELTA_ESTABLISH = 0
DELTA_ELIDE = 1
DELTA_PATCH = 2
# arrays after the header, per kind (patch = idx + 7 row slices)
_DELTA_BODY_ARRAYS = {
    DELTA_ESTABLISH: N_POD_ARRAYS,
    DELTA_ELIDE: 0,
    DELTA_PATCH: N_POD_ARRAYS + 1,
}
# resident pod bases the sidecar retains (LRU): the steady state is ONE
# per client, advanced in place by each patch — the small cap only bounds
# a fleet of clients churning epochs faster than they solve
POD_STORE_MAX = 8

_DTYPES = {0: np.dtype(np.bool_), 1: np.dtype(np.int32), 2: np.dtype(np.float32)}
_DTYPE_CODES = {v: k for k, v in _DTYPES.items()}


def pod_epoch_key(pod_arrays) -> bytes:
    """16-byte content digest of the 7 pod-side arrays — the delta
    protocol's epoch. Content-addressed like :func:`catalog_session_key`
    (dtype+shape folded in) so identical pod sets converge on one resident
    base and any drift mints a new epoch."""
    h = hashlib.blake2b(digest_size=16)
    for a in pod_arrays:
        a = np.asarray(a)
        h.update(str((a.dtype.str, a.shape)).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.digest()


def delta_header(kind: int, n_idx: int, base: bytes, new: bytes) -> np.ndarray:
    """Build the i32[10] delta header array."""
    return np.frombuffer(
        struct.pack("<2i", kind, n_idx) + base + new, np.int32
    )


def _delta_span(arrays: Sequence[np.ndarray]) -> Optional[int]:
    """Arrays consumed by a delta frame starting at index 2 (header +
    kind-dependent body), or None when the header is malformed — the
    caller refuses with INTEGRITY instead of mis-slicing trailers."""
    if len(arrays) < 3:
        return None
    h = np.asarray(arrays[2]).reshape(-1)
    if h.dtype != np.int32 or h.size != DELTA_HEADER_WORDS:
        return None
    n_body = _DELTA_BODY_ARRAYS.get(int(h[0]))
    if n_body is None or len(arrays) < 3 + n_body:
        return None
    return 1 + n_body


# ---------------------------------------------------------------------------
# flat buffer codec
# ---------------------------------------------------------------------------


def pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    parts: List[bytes] = [MAGIC, struct.pack("<HH", VERSION, len(arrays))]
    for a in arrays:
        # NOT ascontiguousarray: it promotes 0-d scalars to 1-d
        a = np.asarray(a, order="C")
        code = _DTYPE_CODES.get(a.dtype)
        if code is None:
            # normalize off-spec dtypes (e.g. int64 scalars, float64)
            if np.issubdtype(a.dtype, np.floating):
                a = a.astype(np.float32)
            elif np.issubdtype(a.dtype, np.bool_):
                a = a.astype(np.bool_)
            else:
                a = a.astype(np.int32)
            code = _DTYPE_CODES[a.dtype]
        parts.append(struct.pack("<BB", code, a.ndim))
        parts.append(struct.pack(f"<{a.ndim}I", *a.shape))
        parts.append(a.tobytes())
    return b"".join(parts)


def unpack_arrays(data: bytes) -> List[np.ndarray]:
    if data[:4] != MAGIC:
        raise ValueError("bad magic")
    version, count = struct.unpack_from("<HH", data, 4)
    if version != VERSION:
        raise ValueError(f"unsupported version {version}")
    offset = 8
    out: List[np.ndarray] = []
    for _ in range(count):
        code, ndim = struct.unpack_from("<BB", data, offset)
        offset += 2
        shape = struct.unpack_from(f"<{ndim}I", data, offset)
        offset += 4 * ndim
        dtype = _DTYPES[code]
        n_items = math.prod(shape)  # prod(()) == 1 → scalar
        n_bytes = n_items * dtype.itemsize
        arr = np.frombuffer(data, dtype=dtype, count=n_items, offset=offset).reshape(shape)
        offset += n_bytes
        out.append(arr)
    return out


# ---------------------------------------------------------------------------
# frame checksums (docs/integrity.md)
# ---------------------------------------------------------------------------
#
# The integrity trailer is one more array in the ordinary v3 framing — an
# i32[3] whose first word is a magic marker and whose remaining 8 bytes are
# a blake2b-64 digest of everything BETWEEN the fixed header and the
# trailer's own header (frame[8:trailer]). Appending it only rewrites the
# count word at offset 6, which the digest deliberately excludes:
#
# - a flip in magic/version fails loudly at the codec already;
# - a flip anywhere in [8, trailer) changes digested bytes → mismatch;
# - a flip in the count word either breaks the parse (count grew past the
#   buffer) or drops the trailer from the parse (count shrank) — and a
#   frame that NEGOTIATED checksums but arrives without one is rejected as
#   "missing", so shrinking the count cannot smuggle a silent change;
# - a flip inside the trailer itself un-marks it (missing) or breaks the
#   digest (mismatch).
#
# Verification walks only the array HEADERS (no array materialization), so
# it is O(frame bytes) in the one blake2b pass.

CHECKSUM_MAGIC = 0x4B53554D  # "MUSK" little-endian; spells KSUM on the wire
CHECKSUM_WORDS = 3  # [magic, digest_lo, digest_hi]
_I32_CODE = _DTYPE_CODES[np.dtype(np.int32)]


def append_checksum(frame: bytes) -> bytes:
    """Return ``frame`` with the integrity trailer appended (count word
    bumped; every other byte of the original frame unchanged)."""
    digest = hashlib.blake2b(frame[8:], digest_size=8).digest()
    count = struct.unpack_from("<H", frame, 6)[0]
    trailer = (
        struct.pack("<BBI", _I32_CODE, 1, CHECKSUM_WORDS)
        + struct.pack("<i", CHECKSUM_MAGIC)
        + digest
    )
    return frame[:6] + struct.pack("<H", count + 1) + frame[8:] + trailer


def _checksum_span(frame: bytes) -> Tuple[Optional[int], Optional[bytes]]:
    """Walk the framing headers; ``(trailer_header_offset, digest)`` when
    the LAST declared array is an integrity trailer, else ``(None, None)``.
    Raises like :func:`unpack_arrays` on malformed framing — a frame too
    broken to walk is a loud codec error, never a quiet "missing"."""
    if frame[:4] != MAGIC:
        raise ValueError("bad magic")
    version, count = struct.unpack_from("<HH", frame, 4)
    if version != VERSION:
        raise ValueError(f"unsupported version {version}")
    offset = 8
    last = None
    for _ in range(count):
        header = offset
        code, ndim = struct.unpack_from("<BB", frame, offset)
        offset += 2
        shape = struct.unpack_from(f"<{ndim}I", frame, offset)
        offset += 4 * ndim
        dtype = _DTYPES[code]
        n_bytes = math.prod(shape) * dtype.itemsize
        payload = offset
        offset += n_bytes
        if offset > len(frame):
            raise ValueError("truncated frame")
        last = (header, code, shape, payload)
    if last is None:
        return None, None
    header, code, shape, payload = last
    if code == _I32_CODE and shape == (CHECKSUM_WORDS,):
        if struct.unpack_from("<i", frame, payload)[0] == CHECKSUM_MAGIC:
            return header, frame[payload + 4:payload + 12]
    return None, None


def verify_checksum(frame: bytes) -> str:
    """``"ok"`` / ``"missing"`` / ``"mismatch"``. Malformed framing raises
    (codec-level loudness); whether ``"missing"`` is acceptable is the
    caller's negotiation state — a peer that agreed to checksums and sends
    none is as rejected as one whose digest disagrees."""
    header, digest = _checksum_span(frame)
    if header is None:
        return "missing"
    computed = hashlib.blake2b(frame[8:header], digest_size=8).digest()
    return "ok" if computed == digest else "mismatch"


# the integrity trailer's on-wire size: BB header + one u32 dim + 12
# payload bytes (append_checksum and pack_arrays emit the identical form)
CHECKSUM_TRAILER_BYTES = 18


def verify_and_unpack(frame: bytes) -> Tuple[str, List[np.ndarray]]:
    """Single-walk verify + parse — the streamed transport's hot path
    (the unary handlers keep the two-walk ``verify_checksum`` →
    ``unpack_arrays`` sequence; semantics are identical, this just
    refuses to pay the header walk twice per message). Returns
    ``(verdict, arrays)`` with the trailer already stripped; raises
    exactly like :func:`unpack_arrays` on malformed framing."""
    arrays = unpack_arrays(frame)
    if not arrays or not is_checksum_array(arrays[-1]):
        return "missing", arrays
    digest = np.asarray(arrays[-1])[1:].tobytes()
    computed = hashlib.blake2b(
        frame[8:len(frame) - CHECKSUM_TRAILER_BYTES], digest_size=8
    ).digest()
    return ("ok" if computed == digest else "mismatch"), arrays[:-1]


def is_checksum_array(a: np.ndarray) -> bool:
    """True for the integrity trailer once it has been through the codec —
    how parsers strip it before positional payload interpretation."""
    a = np.asarray(a)
    return (
        a.dtype == np.int32
        and a.shape == (CHECKSUM_WORDS,)
        and int(a[0]) == CHECKSUM_MAGIC
    )


# ---------------------------------------------------------------------------
# session keys
# ---------------------------------------------------------------------------


def catalog_session_key(
    join_table: np.ndarray, frontiers: np.ndarray, daemon: np.ndarray
) -> bytes:
    """16-byte content fingerprint of the catalog-side tensors — the
    signature closure that ``encode.catalog_fingerprint``'s table produced,
    materialized. Content-addressed (not identity-addressed) so two clients
    of one sidecar converge on one resident copy, and a catalog-generation
    flip simply mints a new key."""
    h = hashlib.blake2b(digest_size=16)
    for a in (join_table, frontiers, daemon):
        a = np.asarray(a)
        h.update(str((a.dtype.str, a.shape)).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.digest()


def _key_array(key: bytes) -> np.ndarray:
    return np.frombuffer(key, np.int32)


class CatalogKeyMemo:
    """Identity-memoized :func:`catalog_session_key`: the encode closure
    memo freezes and reuses the catalog-side arrays across solves, so the
    steady state never re-hashes the multi-MB join table. Entries hold a
    strong ref to the arrays so the memo ids stay valid for each entry's
    lifetime. Shared by :class:`RemoteSolver` (per member) and the sidecar
    pool's ring router (solver/pool.py) — one implementation, one drift
    surface."""

    def __init__(self, max_entries: int = 8):
        self.max_entries = max_entries
        self._memo: "OrderedDict[tuple, tuple]" = OrderedDict()  # guarded-by: self._lock
        self._lock = threading.Lock()

    def key(self, catalog_side: Tuple) -> bytes:
        id_key = tuple(map(id, catalog_side))
        with self._lock:
            hit = self._memo.get(id_key)
            if hit is not None:
                self._memo.move_to_end(id_key)
                return hit[1]
        key = catalog_session_key(*[np.asarray(a) for a in catalog_side])
        with self._lock:
            self._memo[id_key] = (tuple(catalog_side), key)
            while len(self._memo) > self.max_entries:
                self._memo.popitem(last=False)
        return key


def _status_response(status: int, payload: Sequence[np.ndarray] = ()) -> bytes:
    return pack_arrays([np.array([status], np.int32), *payload])


# ---------------------------------------------------------------------------
# trace-context trailer (optional on Pack/OpenSession requests)
# ---------------------------------------------------------------------------

# 16-byte trace id + 8-byte span id as six little-endian i32 words
TRACE_CTX_WORDS = 6


def _trace_ctx_array(ctx) -> np.ndarray:
    """SpanContext → the 6-word i32 trailer array."""
    raw = bytes.fromhex(ctx.trace_id) + bytes.fromhex(ctx.span_id)
    return np.frombuffer(raw, np.int32)


def _ctx_from_array(arr: np.ndarray):
    """Trailer array → SpanContext, or None on anything off-shape — a
    malformed trailer degrades to an untraced solve, never an error."""
    from karpenter_tpu.obs import SpanContext

    a = np.asarray(arr).reshape(-1)
    if a.dtype != np.int32 or a.size != TRACE_CTX_WORDS:
        return None
    raw = a.tobytes()
    return SpanContext(raw[:16].hex(), raw[16:24].hex())


def _parse_trailers(trailer: Sequence[np.ndarray]):
    """Optional Pack trailers → ``(SpanContext|None, deadline_s|None)``.

    Trailers are distinguished by shape+dtype, not position — the trace
    context is i32[6], the deadline an f32[1] of REMAINING budget seconds
    (relative, because client and sidecar clocks never agree). Anything
    unrecognized is ignored, so a future trailer degrades old servers to
    "feature absent", never to a mis-parse."""
    ctx = None
    deadline_s = None
    for arr in trailer:
        a = np.asarray(arr).reshape(-1)
        if a.dtype == np.int32 and a.size == TRACE_CTX_WORDS:
            ctx = _ctx_from_array(arr)
        elif a.dtype == np.float32 and a.size == 1:
            deadline_s = float(a[0])
    return ctx, deadline_s


# ---------------------------------------------------------------------------
# admission control (the sidecar's half of overload control)
# ---------------------------------------------------------------------------


class AdmissionGate:
    """Bounded admission in front of the solve executor: at most
    ``max_inflight`` concurrent solves, at most ``queue_depth`` callers
    parked behind them, everyone else refused immediately — the queue is
    bounded by decision (STATUS_OVERLOADED + a retry hint), not by gRPC's
    thread pool backing up until deadlines expire."""

    # a queued caller never parks longer than this even without a
    # propagated deadline: past it the work is stale enough to refuse.
    # Must stay well BELOW RemoteSolver's warm RPC timeout (30s) — if the
    # queue wait outlived the client's gRPC deadline, the client would see
    # a generic transport error instead of STATUS_OVERLOADED and record a
    # real breaker failure on pure backpressure
    MAX_WAIT_S = 5.0

    def __init__(
        self,
        max_inflight: int = MAX_INFLIGHT,
        queue_depth: int = QUEUE_DEPTH,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_inflight = max(int(max_inflight), 1)
        self.queue_depth = max(int(queue_depth), 0)
        self._clock = clock
        self._cv = threading.Condition()
        self._inflight = 0  # guarded-by: self._cv
        self._waiting = 0  # guarded-by: self._cv
        self.max_depth_seen = 0  # guarded-by: self._cv

    def _publish_locked(self) -> None:
        depth = self._inflight + self._waiting
        self.max_depth_seen = max(self.max_depth_seen, depth)
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_ADMISSION_DEPTH.set(depth)
        except Exception:
            pass  # trimmed registries

    def enter(self, deadline: Optional[float] = None) -> str:
        """Claim a solve slot. Returns ``"admitted"`` (caller MUST pair
        with :meth:`leave`), ``"overloaded"`` (queue full, or the bounded
        wait ran out), or ``"deadline"`` (the caller's own deadline
        expired while queued — already-doomed work, shed it)."""
        with self._cv:
            if self._inflight < self.max_inflight and self._waiting == 0:
                self._inflight += 1
                self._publish_locked()
                return "admitted"
            if self._waiting >= self.queue_depth:
                return "overloaded"
            self._waiting += 1
            self._publish_locked()
            try:
                end = self._clock() + self.MAX_WAIT_S
                if deadline is not None:
                    end = min(end, deadline)
                while self._inflight >= self.max_inflight:
                    remaining = end - self._clock()
                    if remaining <= 0:
                        if deadline is not None and self._clock() >= deadline:
                            return "deadline"
                        return "overloaded"
                    self._cv.wait(remaining)
                self._inflight += 1
                return "admitted"
            finally:
                self._waiting -= 1
                self._publish_locked()

    def leave(self) -> None:
        with self._cv:
            self._inflight = max(self._inflight - 1, 0)
            self._cv.notify()
            self._publish_locked()

    def depth(self) -> int:
        with self._cv:
            return self._inflight + self._waiting


# ---------------------------------------------------------------------------
# server (the JAX/TPU sidecar)
# ---------------------------------------------------------------------------


class SolverService:
    """Owns the jitted kernel; one Pack call = one batched solve.

    Stateful per catalog fingerprint (v3): ``open_session_bytes`` pins a
    catalog generation's tensors on device, ``solve_bytes`` serves delta
    solves against them. The session store is an in-memory LRU — a restart
    empties it, and clients recover through NEEDS_CATALOG, so no durability
    machinery is needed.

    Readiness = the backend compiled and executed one tiny solve (warmup);
    liveness = the process responds at all. Round 1 shipped neither — a hung
    sidecar was only discovered via the 5s client deadline per batch
    (VERDICT weak #7)."""

    def __init__(
        self,
        session_max: int = SESSION_MAX,
        session_ttl: float = SESSION_TTL_S,
        clock: Callable[[], float] = time.monotonic,
        max_inflight: int = MAX_INFLIGHT,
        queue_depth: int = QUEUE_DEPTH,
        overload_retry_after: float = OVERLOAD_RETRY_AFTER_S,
        hbm_floor_bytes: int = 0,
        features: int = PROTO_FEATURES,
    ):
        self.ready = threading.Event()
        self.session_max = session_max
        self.session_ttl = session_ttl
        self._clock = clock
        # the capability word this sidecar advertises in its OpenSession
        # responses; overridable so interop tests can simulate an OLD
        # build (a server without PROTO_STREAM / PROTO_CHECKSUM) against
        # a new client without juggling two checkouts
        self.features = int(features)
        # overload control (docs/overload.md): bounded admission in front
        # of the solve executor, plus an HBM-headroom floor below which
        # NEW session uploads are refused while resident-session solves
        # keep flowing (the PR-8 headroom gauge is the sensor)
        self.admission = AdmissionGate(max_inflight, queue_depth, clock=clock)
        self.overload_retry_after = float(overload_retry_after)
        self.hbm_floor_bytes = int(hbm_floor_bytes)
        # observable overload accounting (the bench's acceptance numbers:
        # zero deadline-expired solves may reach device dispatch)
        self.dispatches = 0  # guarded-by: self._stats_lock
        self.shed: dict = {
            "queue_full": 0, "deadline": 0, "hbm_pressure": 0,
        }  # guarded-by: self._stats_lock
        # request frames rejected for a checksum mismatch, by method — the
        # sidecar's own view of wire corruption (the client attributes the
        # same failure to this member's address on its scrape)
        self.checksum_failures: dict = {}  # guarded-by: self._stats_lock
        # streamed-transport dispatch accounting (solver/stream.py): how
        # many device dispatches carried >1 coalesced solve, and how many
        # solves rode them — the bench's stream_coalesced_dispatch_rate
        self.stream_stats: dict = {
            "coalesced_dispatches": 0, "coalesced_solves": 0,
            "stream_dispatches": 0, "stream_solves": 0,
        }  # guarded-by: self._stats_lock
        self._stats_lock = threading.Lock()
        # key -> [device-resident (join, frontiers, daemon), last_used, fresh];
        # Pack handler threads race OpenSession handler threads on it.
        # ``fresh`` marks a just-uploaded session: the upload itself is the
        # recorded MISS, and the first solve against it must not count as a
        # hit — otherwise a store thrashing on every solve (miss → open →
        # retry) would report ~0.5 hit rate instead of ~0.
        self._sessions: "OrderedDict[bytes, list]" = OrderedDict()  # guarded-by: self._sessions_lock
        self._sessions_lock = threading.Lock()
        # resident pod bases (docs/delta-encoding.md): epoch digest ->
        # the 7 pod-side arrays a delta-framed Pack may elide or patch
        # against. Host-side numpy (the device upload happens per solve,
        # as ever) — what deltas kill is the client's re-serialize and
        # the wire bytes, not the sidecar's upload. LRU-bounded; a restart
        # empties it and clients recover through NEEDS_DELTA_BASE.
        self._pod_store: "OrderedDict[bytes, list]" = OrderedDict()  # guarded-by: self._pod_lock
        self._pod_lock = threading.Lock()
        # delta accounting the chaos harness asserts on (zero stale binds
        # means every miss/mismatch is VISIBLE here, not absorbed)
        self.delta_stats: dict = {
            "established": 0, "elided": 0, "patched": 0,
            "base_misses": 0, "epoch_mismatches": 0,
        }  # guarded-by: self._stats_lock

    # -- overload accounting ------------------------------------------------

    def _count_shed(self, reason: str) -> None:
        with self._stats_lock:
            self.shed[reason] = self.shed.get(reason, 0) + 1
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_ADMISSION_SHED.labels(reason=reason).inc()
        except Exception:
            pass  # trimmed registries

    def _overloaded_response(self) -> bytes:
        return _status_response(
            STATUS_OVERLOADED,
            [np.asarray([self.overload_retry_after], np.float32)],
        )

    # -- integrity ----------------------------------------------------------

    def _reject_corrupt(self, method: str) -> bytes:
        """The request's bytes are not the bytes the client sent: refuse
        with the typed status instead of solving against garbage. The
        response IS checksummed — the client negotiated integrity (it sent
        a digest), so it will require one on the way back too."""
        with self._stats_lock:
            self.checksum_failures[method] = (
                self.checksum_failures.get(method, 0) + 1
            )
        logger.error(
            "%s request failed frame checksum; rejecting (STATUS_INTEGRITY)",
            method,
        )
        return append_checksum(_status_response(STATUS_INTEGRITY))

    @staticmethod
    def _seal(response: bytes, checksummed: bool) -> bytes:
        """Checksum the response iff the request carried a (valid)
        checksum — symmetric negotiation with zero extra round trips, and
        an unchecksummed (old-client) exchange stays byte-identical."""
        return append_checksum(response) if checksummed else response

    # -- sessions -----------------------------------------------------------

    def _evict_sessions_locked(self) -> None:
        """LRU + TTL eviction; caller holds ``_sessions_lock``. Every
        evicted session also releases its HBM gauge label — a dashboard
        summing ``karpenter_solver_session_hbm_bytes`` must track what is
        actually pinned, not what ever was."""
        from karpenter_tpu.solver import session_stats

        now = self._clock()
        evicted = []
        stale = [
            k for k, v in self._sessions.items()
            if now - v[1] > self.session_ttl
        ]
        for k in stale:
            del self._sessions[k]
            evicted.append(k)
        while len(self._sessions) > self.session_max:
            k, _ = self._sessions.popitem(last=False)
            evicted.append(k)
        if evicted:
            session_stats.record_eviction(len(evicted))
            for k in evicted:
                _drop_session_hbm(k)

    def open_session_bytes(self, request: bytes) -> bytes:
        """Pin one catalog generation's tensors on device under its key.

        Idempotent for an already-resident key (another client of this
        sidecar, or a client whose own opened-LRU forgot it): the store is
        just touched — no re-upload to HBM, no spurious miss, and the
        session's fresh/aged state is left alone. The optional trailing
        flags array (``[record]``) keeps probe traffic out of the hit-rate
        stats, mirroring the in-process DeviceInvariants contract."""
        import jax

        from karpenter_tpu import obs
        from karpenter_tpu.solver import session_stats

        # wire integrity (docs/integrity.md): a corrupted upload must never
        # pin garbage catalog tensors a whole fleet of delta solves would
        # then trust — reject before touching the store or the device
        try:
            verdict = verify_checksum(request)
        except ValueError as e:
            if "version" in str(e) or "magic" in str(e):
                raise  # version skew stays a LOUD protocol error (v1→v2 contract)
            return self._reject_corrupt("open_session")
        except Exception:
            # otherwise unparseable framing IS corruption: answer the typed
            # status (the client quarantines the path) instead of crashing
            # the handler into a generic transport error
            return self._reject_corrupt("open_session")
        if verdict == "mismatch":
            return self._reject_corrupt("open_session")
        checksummed = verdict == "ok"
        key_arr, join_table, frontiers, daemon, *rest = unpack_arrays(request)
        rest = [a for a in rest if not is_checksum_array(a)]
        key = key_arr.tobytes()
        # content-address verification: the claimed key must BE the hash of
        # the uploaded tensors, or every delta solve under this key would
        # run against tensors the key does not describe (a corrupt client
        # memo — wire corruption is the checksum's job). Once per catalog
        # generation, same blake2b the client already paid.
        computed = catalog_session_key(join_table, frontiers, daemon)
        if computed != key:
            with self._stats_lock:
                self.checksum_failures["open_session_key"] = (
                    self.checksum_failures.get("open_session_key", 0) + 1
                )
            logger.error(
                "session open claims key %s but tensors hash to %s; "
                "rejecting (STATUS_INTEGRITY)",
                key.hex()[:12], computed.hex()[:12],
            )
            return self._seal(_status_response(STATUS_INTEGRITY), checksummed)
        record = bool(rest[0].reshape(-1)[0]) if rest else True
        ctx = _ctx_from_array(rest[1]) if len(rest) > 1 else None
        with self._sessions_lock:
            hit = self._sessions.get(key)
            if hit is not None:
                hit[1] = self._clock()
                self._sessions.move_to_end(key)
                self._evict_sessions_locked()
        if hit is not None:
            return self._seal(
                _status_response(
                    STATUS_OK, [np.array([self.features], np.int32)]
                ),
                checksummed,
            )
        # HBM-pressure gate (docs/overload.md): a NEW catalog upload is the
        # one request that grows device residency — below the headroom
        # floor it is refused with a retry hint while solves against
        # already-resident sessions (the touch path above) keep flowing
        if self.hbm_floor_bytes:
            headroom = publish_device_headroom()
            if headroom is not None and headroom < self.hbm_floor_bytes:
                self._count_shed("hbm_pressure")
                logger.warning(
                    "refusing session open %s: device headroom %d under "
                    "floor %d", key.hex()[:12], headroom, self.hbm_floor_bytes,
                )
                return self._seal(self._overloaded_response(), checksummed)
        if ctx is not None:
            # the catalog upload is the session protocol's one heavy moment —
            # traced as the sidecar's own child span (linked to the client's
            # trace by the trailer ids) so a slow open attributes to HBM
            # placement, not "the wire was slow"
            with obs.tracer().span(
                "sidecar.device_put",
                parent=ctx,
                attrs={"session": key.hex()[:12]},
            ):
                resident = tuple(
                    jax.device_put(a) for a in (join_table, frontiers, daemon)
                )
        else:
            resident = tuple(
                jax.device_put(a) for a in (join_table, frontiers, daemon)
            )
        # re-check under the lock: two clients racing to open the same new
        # key both pass the miss check above and both device_put — the
        # FIRST insert wins (preserving any fresh state a Pack already
        # consumed), the loser's tensors are dropped, and the stats count
        # one residency miss per logical open, not per racer
        with self._sessions_lock:
            won = key not in self._sessions
            if won:
                self._sessions[key] = [resident, self._clock(), True]
                # gauge write stays under the lock: published after release,
                # a concurrent open's eviction of this key could interleave
                # its _drop_session_hbm BEFORE our publish — resurrecting
                # the label for a session no longer resident, forever
                _publish_session_hbm(key, _resident_nbytes(resident))
            else:
                self._sessions[key][1] = self._clock()
            self._sessions.move_to_end(key)
            self._evict_sessions_locked()
        if won:
            session_stats.record_upload()
            if record:
                # the upload IS the residency miss: catalog bytes crossed
                # for the solve that triggered this open (proactive or
                # NEEDS_CATALOG retry)
                session_stats.record(False)
            # headroom is global (not per-key), so it can stay off-lock —
            # it queries the backend, which must not run under the store lock
            publish_device_headroom()
            logger.info("solver session opened (catalog key %s)", key.hex()[:12])
        # capability advertisement rides every OpenSession response: the
        # client gates its Pack trace trailer on PROTO_TRACE_TRAILER (and
        # the integrity pair on PROTO_CHECKSUM)
        return self._seal(
            _status_response(
                STATUS_OK, [np.array([self.features], np.int32)]
            ),
            checksummed,
        )

    def session_count(self) -> int:
        with self._sessions_lock:
            return len(self._sessions)

    # -- resident pod bases (docs/delta-encoding.md) -------------------------

    def _count_delta(self, what: str) -> None:
        with self._stats_lock:
            self.delta_stats[what] = self.delta_stats.get(what, 0) + 1

    def _publish_pod_store_bytes(
        self, resident: List[List[np.ndarray]]
    ) -> None:
        # Summing nbytes is pure host work, but it runs OFF the store
        # lock regardless: the lock only guards the OrderedDict.
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_DELTA_RESIDENT_BYTES.labels(side="sidecar").set(
                sum(int(np.asarray(a).nbytes) for pods in resident for a in pods)
            )
        except Exception:
            pass  # trimmed registries

    def _store_pods(self, epoch: bytes, pods: List[np.ndarray]) -> None:
        with self._pod_lock:
            self._pod_store[epoch] = [pods, self._clock()]
            self._pod_store.move_to_end(epoch)
            while len(self._pod_store) > POD_STORE_MAX:
                self._pod_store.popitem(last=False)
            resident = [entry[0] for entry in self._pod_store.values()]
        self._publish_pod_store_bytes(resident)

    def _pods_for(self, epoch: bytes) -> Optional[List[np.ndarray]]:
        with self._pod_lock:
            hit = self._pod_store.get(epoch)
            if hit is None:
                return None
            hit[1] = self._clock()
            self._pod_store.move_to_end(epoch)
            return hit[0]

    def pod_store_count(self) -> int:
        with self._pod_lock:
            return len(self._pod_store)

    def _resolve_delta(
        self, arrays: Sequence[np.ndarray]
    ) -> Tuple[Optional[List[np.ndarray]], Optional[int]]:
        """Resolve one delta-framed Pack into its concrete 7 pod arrays:
        ``(pod_arrays, None)`` or ``(None, refusal_status)``. Shared by the
        unary and streamed parse paths so both enforce the identical
        ladder: malformed framing is INTEGRITY, a missing base or a patch
        whose recomputed digest disagrees with the epoch it claims is
        NEEDS_DELTA_BASE — the stale-tensor guard. The digest recompute is
        deliberate: a sidecar NEVER trusts the client's bookkeeping about
        what the patched state should be, it proves it."""
        span = _delta_span(arrays)
        if span is None:
            return None, STATUS_INTEGRITY
        h = np.asarray(arrays[2]).reshape(-1)
        kind, n_idx = int(h[0]), int(h[1])
        base_epoch = h[2:6].tobytes()
        new_epoch = h[6:10].tobytes()
        body = [np.asarray(a) for a in arrays[3:2 + span]]
        if kind == DELTA_ESTABLISH:
            if pod_epoch_key(body) != new_epoch:
                # the claimed epoch is not the content's digest: client
                # bug or corruption the checksum missed — refuse like the
                # open_session key check, never pin a mislabeled base
                self._count_delta("epoch_mismatches")
                self._count_delta_mismatch_metric()
                return None, STATUS_INTEGRITY
            self._store_pods(new_epoch, body)
            self._count_delta("established")
            return body, None
        if kind == DELTA_ELIDE:
            pods = self._pods_for(new_epoch)
            if pods is None:
                self._count_delta("base_misses")
                return None, STATUS_NEEDS_DELTA_BASE
            self._count_delta("elided")
            return pods, None
        # DELTA_PATCH
        base = self._pods_for(base_epoch)
        if base is None:
            self._count_delta("base_misses")
            return None, STATUS_NEEDS_DELTA_BASE
        idx = body[0].reshape(-1)
        slices = body[1:]
        if idx.dtype != np.int32 or idx.size != n_idx:
            return None, STATUS_INTEGRITY
        n_pods = int(np.asarray(base[0]).shape[0])
        if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= n_pods):
            return None, STATUS_INTEGRITY
        pods = []
        for cur, rows in zip(base, slices):
            cur = np.asarray(cur)
            rows = np.asarray(rows)
            if rows.shape != (idx.size,) + cur.shape[1:] or rows.dtype != cur.dtype:
                return None, STATUS_INTEGRITY
            patched = cur.copy()
            patched[idx] = rows
            pods.append(patched)
        if pod_epoch_key(pods) != new_epoch:
            # the patch applied cleanly but does NOT produce the state the
            # client believes exists: a missed/misordered delta. The base
            # stays resident (it is still exactly what its own epoch says);
            # the client falls back to a full establish — fail loud, never
            # solve stale
            self._count_delta("epoch_mismatches")
            self._count_delta_mismatch_metric()
            return None, STATUS_NEEDS_DELTA_BASE
        self._store_pods(new_epoch, pods)
        self._count_delta("patched")
        return pods, None

    @staticmethod
    def _count_delta_mismatch_metric() -> None:
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_DELTA_EPOCH_MISMATCHES.labels(side="sidecar").inc()
        except Exception:
            pass  # trimmed registries

    # -- lifecycle ----------------------------------------------------------

    def warmup(self) -> None:
        """Compile + run a minimal solve so readiness implies a working
        backend, not just a bound port."""
        try:
            from karpenter_tpu.cloudprovider.fake import instance_types
            from karpenter_tpu.cloudprovider.requirements import catalog_requirements
            from karpenter_tpu.kube.client import Cluster
            from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
            from karpenter_tpu.scheduling.topology import Topology
            from karpenter_tpu.solver import encode as enc
            from karpenter_tpu.testing.factories import make_pod, make_provisioner

            catalog = instance_types(4)
            constraints = make_provisioner(solver="tpu").spec.constraints
            constraints.requirements = constraints.requirements.merge(
                catalog_requirements(catalog)
            )
            pods = sort_pods_ffd([make_pod(requests={"cpu": "0.1"}) for _ in range(4)])
            cluster = Cluster()
            Topology(cluster).inject(constraints, pods)
            batch = enc.encode(
                constraints, catalog, pods, daemon_overhead(cluster, constraints)
            )
            args = [np.asarray(a) for a in batch.pack_args()]
            key = catalog_session_key(*args[N_POD_ARRAYS:])
            self.open_session_bytes(
                pack_arrays([_key_array(key)] + args[N_POD_ARRAYS:])
            )
            response = self.solve_bytes(
                pack_arrays(
                    [_key_array(key), np.asarray([len(batch.pod_valid)], np.int32)]
                    + args[:N_POD_ARRAYS]
                )
            )
            status = int(unpack_arrays(response)[0].reshape(-1)[0])
            if status != STATUS_OK:
                raise RuntimeError(f"warmup solve answered status {status}")
            logger.info("solver warmup complete")
        except Exception:
            logger.exception("solver warmup failed; staying unready")
            return
        self.ready.set()

    def warmup_loop(self, max_backoff: float = 60.0) -> None:
        """Retry warmup with capped decorrelated-jitter backoff until it
        succeeds — a transient failure (TPU not plumbed yet) must not leave
        the pod NOT_SERVING forever with a healthy liveness probe, and a
        fleet of sidecars restarting together must not re-warm in lockstep
        against a shared bottleneck (resilience/policy.py)."""
        from karpenter_tpu.resilience import decorrelated_jitter

        backoffs = decorrelated_jitter(1.0, cap=max_backoff)
        while not self.ready.is_set():
            self.warmup()
            if self.ready.is_set():
                return
            time.sleep(next(backoffs))

    def health_bytes(self, request: bytes) -> bytes:
        return SERVING if self.ready.is_set() else NOT_SERVING

    def solve_bytes(self, request: bytes) -> bytes:
        """One delta solve: session key + n_max + the 7 pod-side arrays
        (+ optional trailers: trace context, propagated deadline). Unknown
        key → ``NEEDS_CATALOG`` (the client re-opens and retries).

        Overload control wraps the whole solve: the bounded admission gate
        refuses work past its caps (``STATUS_OVERLOADED`` + retry hint),
        and a propagated deadline is re-checked AFTER queueing so
        already-doomed work sheds before it ever touches the device
        (``STATUS_DEADLINE_EXCEEDED`` — non-retryable client-side).

        Wire integrity (docs/integrity.md) brackets everything: a request
        whose checksum disagrees is refused with ``STATUS_INTEGRITY``
        before any byte of it is trusted, and when the request carried a
        checksum the response carries one back."""
        try:
            verdict = verify_checksum(request)
        except ValueError as e:
            if "version" in str(e) or "magic" in str(e):
                raise  # version skew stays a LOUD protocol error (v1→v2 contract)
            return self._reject_corrupt("pack")
        except Exception:
            # otherwise unparseable framing IS corruption (truncation,
            # header flips): the typed refusal, never a handler crash the
            # client would book as a windowed availability failure
            return self._reject_corrupt("pack")
        if verdict == "mismatch":
            return self._reject_corrupt("pack")
        checksummed = verdict == "ok"
        arrays = [a for a in unpack_arrays(request) if not is_checksum_array(a)]
        # the trailer offset depends on the framing: a delta frame's body
        # is header + kind-dependent arrays, not the fixed 7 — and a patch
        # idx array that landed in the trailer slice could masquerade as
        # an i32[6] trace context, so the span must be computed, not assumed
        vals0 = np.asarray(arrays[1]).reshape(-1) if len(arrays) > 1 else np.zeros(0, np.int32)
        flags0 = int(vals0[2]) if vals0.size > 2 else 0
        if flags0 & PACK_FLAG_DELTA:
            span = _delta_span(arrays)
            if span is None:
                return self._seal(
                    _status_response(STATUS_INTEGRITY), checksummed
                )
            trailer = arrays[2 + span:]
        else:
            trailer = arrays[2 + N_POD_ARRAYS:]
        ctx, deadline_s = _parse_trailers(trailer)
        deadline = (
            None if deadline_s is None
            else self._clock() + max(deadline_s, 0.0)
        )
        adm_t0 = time.perf_counter()
        outcome = self.admission.enter(deadline)
        # queue time precedes the pack span (a backdated child would
        # corrupt self-time attribution — the provision.round precedent),
        # so it rides the span as an attribute; the fleet stitcher's
        # wire_attribution reads it to split wire vs admission-queue time
        admission_wait_s = time.perf_counter() - adm_t0
        if outcome == "deadline":
            self._count_shed("deadline")
            return self._seal(_status_response(STATUS_DEADLINE_EXCEEDED), checksummed)
        if outcome == "overloaded":
            self._count_shed("queue_full")
            return self._seal(self._overloaded_response(), checksummed)
        try:
            if deadline is not None and self._clock() >= deadline:
                # the budget died while this request sat in the admission
                # queue: shed BEFORE device dispatch — the round it
                # belonged to has already degraded to its FFD floor
                self._count_shed("deadline")
                return self._seal(
                    _status_response(STATUS_DEADLINE_EXCEEDED), checksummed
                )
            return self._seal(
                self._solve_admitted(arrays, ctx, admission_wait_s),
                checksummed,
            )
        finally:
            self.admission.leave()

    def _solve_admitted(
        self, arrays: List[np.ndarray], ctx, admission_wait_s: float = 0.0
    ) -> bytes:
        import jax

        from karpenter_tpu import obs
        from karpenter_tpu.solver import kernel, session_stats

        from karpenter_tpu.solver.pallas_kernel import pack_best

        key_arr, n_max_arr = arrays[0], arrays[1]
        key = key_arr.tobytes()
        vals = n_max_arr.reshape(-1)
        n_max = int(vals[0])
        # optional second word: 0 = keep this Pack out of the hit-rate
        # stats (shadow probes, saturation re-dispatches — one logical
        # solve must count once, matching the in-process path)
        record = bool(vals[1]) if vals.size > 1 else True
        # optional third word (PROTO_CHECKSUM / PROTO_DELTA peers only):
        # feature flags — bit 0 asks for the session-key echo so the
        # client can reject a wrong-catalog-generation pack instead of
        # decoding it; bit 1 marks the delta framing
        flags = int(vals[2]) if vals.size > 2 else 0
        if flags & PACK_FLAG_DELTA:
            pod_arrays, refusal = self._resolve_delta(arrays)
            if refusal is not None:
                return _status_response(refusal)
        else:
            pod_arrays = arrays[2:2 + N_POD_ARRAYS]
        echo = (
            [_key_array(key)] if flags & PACK_FLAG_ECHO_SESSION else []
        )
        record_hit = False
        with self._sessions_lock:
            hit = self._sessions.get(key)
            if hit is not None:
                hit[1] = self._clock()
                self._sessions.move_to_end(key)
                resident = hit[0]
                if record:
                    record_hit = not hit[2]  # fresh upload was the miss
                    hit[2] = False
            # store maintenance rides the hot path too: in steady state no
            # further OpenSession ever arrives, and TTL-expired catalog
            # generations must still release their pinned HBM (this solve's
            # own session was just touched, so it can never be the victim)
            self._evict_sessions_locked()
        if hit is None:
            # no record here: the client's re-open is the one miss this
            # logical solve contributes (open_session_bytes records it)
            return _status_response(STATUS_NEEDS_CATALOG)
        if record_hit:
            session_stats.record(True)
        with self._stats_lock:
            # from here the solve reaches the device: the overload-storm
            # acceptance bar counts dispatches vs deadline sheds
            self.dispatches += 1
        if ctx is None:
            result = pack_best(*pod_arrays, *resident, n_max=n_max)
            # one fused device→host transfer on the sidecar too — per-array
            # fetches each pay the full device round trip
            buf = jax.device_get(kernel.fuse_result(result))
            return _status_response(STATUS_OK, [np.asarray(buf), *echo])
        # traced solve: child spans around solve/fetch/serialize make the
        # sidecar's half of the RTT attributable. The spans land in THIS
        # process's trace ring (GET /debug/traces on the sidecar health
        # port), and the response grows an f32 [solve_s, fetch_s,
        # serialize_s] trailer so the client can graft the same numbers
        # into its own tree without a trace collector.
        with obs.tracer().span(
            "sidecar.pack",
            parent=ctx,
            attrs={
                "session": key.hex()[:12],
                "admission_wait_s": round(admission_wait_s, 6),
                # batch size: the regression sentinel's shape-class key —
                # a 4-pod and a 400-pod pack must not share a baseline
                "pods": int(len(pod_arrays[0])),
            },
        ) as sp:
            t0 = time.perf_counter()
            with obs.tracer().span("sidecar.solve"):
                result = pack_best(*pod_arrays, *resident, n_max=n_max)
            solve_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            with obs.tracer().span("sidecar.fetch"):
                buf = jax.device_get(kernel.fuse_result(result))
            fetch_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            response = _status_response(
                STATUS_OK, [np.asarray(buf), np.zeros(3, np.float32), *echo]
            )
            serialize_s = time.perf_counter() - t0
            sp.add_child_record("sidecar.serialize", serialize_s)
            # the stage trailer's 12 payload bytes sit right before the
            # (fixed-width: 22-byte) session echo when one was asked for,
            # else they end the message — so the measured durations
            # (serialize included, which by then has happened) patch in
            # place at a computable offset
            tail = len(response) - (22 if echo else 0)
            response = (
                response[:tail - 12]
                + struct.pack("<3f", solve_s, fetch_s, serialize_s)
                + response[tail:]
            )
        return response

    # -- the streamed transport (solver/stream.py) ---------------------------

    def stream_parse_solve(self, payload: bytes, respond, arena=None):
        """Verify and parse one streamed solve message into a
        :class:`~karpenter_tpu.solver.stream.StreamSolve` awaiting
        dispatch, or return the immediate error-response frame. The
        verification ladder is ``solve_bytes``'s exactly — same checksum
        policy, same typed refusals — because the payload IS a unary
        frame; only admission/dispatch move to the coalescer.

        ``arena`` (a ``ShmArenaReader``) marks the zero-copy variant: the
        frame carries one i32 descriptor where the unary frame carries
        the 7 pod arrays, and the pod arrays materialize as views onto
        the shared mmap — the first copy is the device upload itself."""
        from karpenter_tpu.solver.stream import StreamSolve

        try:
            verdict, arrays = verify_and_unpack(payload)
        except ValueError as e:
            if "version" in str(e) or "magic" in str(e):
                raise  # version skew stays LOUD (breaks the stream; the
                #        unary fallback then fails loudly at the codec)
            return self._reject_corrupt("stream_pack")
        except Exception:
            return self._reject_corrupt("stream_pack")
        if verdict == "mismatch":
            return self._reject_corrupt("stream_pack")
        checksummed = verdict == "ok"
        # structural guards BEFORE any positional indexing: a malformed
        # payload (a byte-flip with checksums off, or a buggy client)
        # must fail THIS message with the typed refusal — an IndexError
        # here would kill the reader thread and tear down the whole
        # multiplexed stream, amplifying one bad message into every
        # in-flight solve's failure
        if len(arrays) < 3 or np.asarray(arrays[1]).reshape(-1).size < 1:
            return self._seal(_status_response(STATUS_INTEGRITY), checksummed)
        shm = arena is not None
        key_arr, n_max_arr = arrays[0], arrays[1]
        vals = n_max_arr.reshape(-1)
        flags = int(vals[2]) if vals.size > 2 else 0
        if arena is not None:
            desc = arrays[2]
            trailer = arrays[3:]
            try:
                pod_arrays = arena.read(desc)
            except ValueError as e:
                logger.error("shm descriptor rejected: %s", e)
                return self._seal(
                    _status_response(STATUS_INTEGRITY), checksummed
                )
            if len(pod_arrays) != N_POD_ARRAYS:
                return self._seal(
                    _status_response(STATUS_INTEGRITY), checksummed
                )
        elif flags & PACK_FLAG_DELTA:
            # delta frames resolve into concrete pod arrays HERE, at parse
            # time (the one place the framing is positional), so the
            # coalescer and solve_stream_group never see a delta — their
            # group keys and vmapped dispatch are unchanged. A refusal
            # (missing base, digest mismatch, malformed header) answers
            # straight from the reader thread, like the deadline shed.
            pod_arrays, refusal = self._resolve_delta(arrays)
            if refusal is not None:
                return self._seal(_status_response(refusal), checksummed)
            span = _delta_span(arrays)
            trailer = arrays[2 + span:]
        else:
            pod_arrays = arrays[2:2 + N_POD_ARRAYS]
            trailer = arrays[2 + N_POD_ARRAYS:]
            if len(pod_arrays) != N_POD_ARRAYS:
                return self._seal(
                    _status_response(STATUS_INTEGRITY), checksummed
                )
        ctx, deadline_s = _parse_trailers(trailer)
        return StreamSolve(
            key=key_arr.tobytes(),
            n_max=int(vals[0]),
            record=bool(vals[1]) if vals.size > 1 else True,
            flags=int(vals[2]) if vals.size > 2 else 0,
            pod_arrays=[np.asarray(a) for a in pod_arrays],
            ctx=ctx,
            deadline=(
                None if deadline_s is None
                else self._clock() + max(deadline_s, 0.0)
            ),
            checksummed=checksummed,
            respond=respond,
            shm=shm,
        )

    # the deadline-shed response is constant either way (sealed bytes
    # digest a constant frame), so the reader-thread fast path pays zero
    # serialization for it
    _SHED_RESPONSES: dict = {}

    def shed_if_expired(self, entry) -> Optional[bytes]:
        """The stream reader's early deadline shed: an already-expired
        solve answers ``STATUS_DEADLINE_EXCEEDED`` straight from the
        reader thread — no dispatcher hop, no executor scheduling, no
        admission slot. Doomed work cannot shed any earlier than this
        (the group dispatch re-checks for budgets that die while
        queued, mirroring the unary path's double check)."""
        if entry.deadline is None or self._clock() < entry.deadline:
            return None
        self._count_shed("deadline")
        cached = self._SHED_RESPONSES.get(entry.checksummed)
        if cached is None:
            cached = self._SHED_RESPONSES[entry.checksummed] = self._seal(
                _status_response(STATUS_DEADLINE_EXCEEDED), entry.checksummed
            )
        return cached

    # coalesced groups are padded up to the next power of two by repeating
    # the tail entry, so the vmapped kernel compiles once per (shape, B
    # bucket) instead of once per observed group size
    _COALESCE_BUCKETS = (1, 2, 4, 8)

    def solve_stream_group(self, entries) -> None:
        """Dispatch one coalesced group of streamed solves (same session
        key, same padded pod shapes, same ``n_max`` — the coalescer's
        group key) as ONE admission slot and ONE device dispatch,
        answering each entry's ``respond`` with its own response frame.

        Everything the unary solve enforces rides along per entry: the
        propagated deadline is re-checked after queueing (already-doomed
        work sheds before dispatch), an unknown session answers
        ``NEEDS_CATALOG``, hit-rate accounting stays solve-true, and —
        because steady-state streams send no unary traffic — the TTL
        session sweep runs here too, so stale catalog generations still
        release their pinned HBM (the PR-4 solve-path sweep, extended to
        the stream path)."""
        import jax

        from karpenter_tpu.solver import kernel, session_stats

        from karpenter_tpu.solver.pallas_kernel import pack_best

        outcome = self.admission.enter()
        if outcome != "admitted":
            for e in entries:
                self._count_shed("queue_full")
                e.reply(
                    self._seal(self._overloaded_response(), e.checksummed)
                )
            return
        try:
            now = self._clock()
            live = []
            for e in entries:
                if e.deadline is not None and now >= e.deadline:
                    self._count_shed("deadline")
                    e.reply(
                        self._seal(
                            _status_response(STATUS_DEADLINE_EXCEEDED),
                            e.checksummed,
                        )
                    )
                else:
                    live.append(e)
            if not live:
                return
            key = live[0].key
            resident = None
            hits_to_record = 0
            with self._sessions_lock:
                hit = self._sessions.get(key)
                if hit is not None:
                    hit[1] = self._clock()
                    self._sessions.move_to_end(key)
                    resident = hit[0]
                    for e in live:
                        if e.record:
                            if hit[2]:
                                hit[2] = False  # fresh upload was the miss
                            else:
                                hits_to_record += 1
                # the TTL sweep rides the stream path: steady-state
                # streams send no unary solves OR opens, so this is the
                # only place a stale generation's HBM gets released
                self._evict_sessions_locked()
            if hit is None:
                for e in live:
                    # unsealed, mirroring the unary path: NEEDS_CATALOG is
                    # the capability-renegotiation channel (docs/integrity.md)
                    e.reply(_status_response(STATUS_NEEDS_CATALOG))
                return
            for _ in range(hits_to_record):
                session_stats.record(True)
            # coalescing is a DEVICE-dispatch amortization: one vmapped
            # kernel call pays the device/tunnel round trip once for B
            # solves. On a rig where pack_best would route the NATIVE
            # host packer (no device in the path), there is nothing to
            # amortize and the vmapped scan kernel would only be slower —
            # the group keeps its single admission slot but dispatches
            # per entry through pack_best's own routing.
            import os as _os

            from karpenter_tpu.solver.pallas_kernel import pallas_available

            forced = _os.environ.get("KARPENTER_PACKER", "auto").lower()
            device_route = forced in ("scan", "pallas") or (
                forced != "native" and pallas_available()
            )
            coalesced = len(live) > 1 and device_route
            with self._stats_lock:
                self.dispatches += 1
                self.stream_stats["stream_dispatches"] += 1
                self.stream_stats["stream_solves"] += len(live)
                if coalesced:
                    self.stream_stats["coalesced_dispatches"] += 1
                    self.stream_stats["coalesced_solves"] += len(live)
            if coalesced:
                try:
                    from karpenter_tpu import metrics

                    metrics.SOLVER_STREAM_COALESCED_DISPATCHES.inc()
                    metrics.SOLVER_STREAM_COALESCED_SOLVES.inc(len(live))
                except Exception:
                    pass  # trimmed registries
            n_max = live[0].n_max
            t0 = time.perf_counter()
            if not coalesced:
                # one entry, or a no-device rig: pack_best's own routing
                # per entry (native/scan/pallas), still one admission slot
                results = [
                    pack_best(*e.pod_arrays, *resident, n_max=n_max)
                    for e in live
                ]
                dispatch_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                bufs = [
                    jax.device_get(kernel.fuse_result(r)) for r in results
                ]
                fetch_s = time.perf_counter() - t0
            else:
                from functools import partial

                pad_to = next(
                    b for b in self._COALESCE_BUCKETS if b >= len(live)
                )
                padded = live + [live[-1]] * (pad_to - len(live))
                stacked = [
                    np.stack([e.pod_arrays[i] for e in padded])
                    for i in range(N_POD_ARRAYS)
                ]
                batched = jax.vmap(
                    partial(kernel.pack, n_max=n_max),
                    in_axes=(0,) * N_POD_ARRAYS + (None,) * 3,
                )
                multi = batched(*stacked, *resident)
                dispatch_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                fused = jax.device_get(jax.vmap(kernel.fuse_result)(multi))
                fetch_s = time.perf_counter() - t0
                bufs = [fused[i] for i in range(len(live))]
            for e, buf in zip(live, bufs):
                echo = (
                    [_key_array(key)]
                    if e.flags & PACK_FLAG_ECHO_SESSION else []
                )
                payload = [np.asarray(buf)]
                if e.ctx is not None:
                    # the stage trailer the client grafts as sidecar.*
                    # child records: the dispatch and fetch are SHARED
                    # across a coalesced group (each solve genuinely
                    # waited that long); serialize is the per-entry
                    # response build, negligible and folded into fetch
                    payload.append(
                        np.asarray([dispatch_s, fetch_s, 0.0], np.float32)
                    )
                payload.extend(echo)
                e.reply(
                    self._seal(
                        _status_response(STATUS_OK, payload), e.checksummed
                    )
                )
        finally:
            self.admission.leave()


def serve(
    address: str = "127.0.0.1:50051",
    max_workers: int = 4,
    health_port: int = 0,
    warmup: bool = False,
    service=None,
    shm_dir: str = "",
    coalesce_window_s: Optional[float] = None,
):
    """Start the sidecar server; returns the grpc server object.

    ``health_port`` > 0 additionally serves HTTP ``/healthz`` (liveness,
    always 200 once the process is up) and ``/readyz`` (503 until the warmup
    solve completes) for kubelet probes (deploy/solver.yaml). ``warmup``
    runs the compile-warming solve in the background; without it readiness
    is immediate (tests, in-process use). ``service`` lets a caller hand in
    a pre-built (or chaos-wrapped — testing/chaos.py) ``SolverService``.

    ``shm_dir`` enables the zero-copy colocated fast path toward clients
    that share the directory; ``coalesce_window_s`` tunes the streamed
    dispatch-coalescing collection window. The stream machinery (threads,
    executor) is built LAZILY on the first SolveStream RPC, so unary-only
    callers — every pre-stream test and deployment — pay nothing."""
    import grpc

    service = service if service is not None else SolverService()
    stream_box: list = [None]  # guarded-by: stream_lock
    stream_lock = threading.Lock()

    def stream_server():
        with stream_lock:
            if stream_box[0] is None:
                from karpenter_tpu.solver.stream import (
                    DEFAULT_COALESCE_WINDOW_S,
                    StreamServer,
                )

                stream_box[0] = StreamServer(
                    service,
                    max_workers=max_workers,
                    coalesce_window_s=(
                        DEFAULT_COALESCE_WINDOW_S
                        if coalesce_window_s is None else coalesce_window_s
                    ),
                    shm_dir=shm_dir,
                )
            return stream_box[0]

    def handler_fn(method_name, unused_handler_call_details=None):
        if method_name.method == METHOD:
            return grpc.unary_unary_rpc_method_handler(
                lambda request, ctx: service.solve_bytes(request),
                request_deserializer=None,  # raw bytes in
                response_serializer=None,  # raw bytes out
            )
        if method_name.method == OPEN_SESSION_METHOD:
            return grpc.unary_unary_rpc_method_handler(
                lambda request, ctx: service.open_session_bytes(request),
                request_deserializer=None,
                response_serializer=None,
            )
        if method_name.method == STREAM_METHOD:
            return grpc.stream_stream_rpc_method_handler(
                lambda request_iterator, ctx: stream_server().handle(
                    request_iterator, ctx
                ),
                request_deserializer=None,
                response_serializer=None,
            )
        if method_name.method == HEALTH_METHOD:
            return grpc.unary_unary_rpc_method_handler(
                lambda request, ctx: service.health_bytes(request),
                request_deserializer=None,
                response_serializer=None,
            )
        return None

    class Handler(grpc.GenericRpcHandler):
        def service(self, handler_call_details):
            return handler_fn(handler_call_details)

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ("grpc.max_send_message_length", 256 * 1024 * 1024),
        ],
    )
    server.add_generic_rpc_handlers((Handler(),))
    server.add_insecure_port(address)
    server.start()
    if warmup:
        threading.Thread(target=service.warmup_loop, daemon=True).start()
    else:
        service.ready.set()
    if health_port:
        server.health_server = _serve_health(service, health_port)
    server.solver_service = service
    # lazy accessor + "built yet?" box, so bench/tests can read stream
    # stats without forcing the machinery into unary-only servers
    server.stream_server = stream_server
    server.stream_server_box = stream_box
    # stream teardown rides server.stop: the coalescer thread and solve
    # executor must die with the server — tests and chaos harnesses
    # cycle dozens of sidecars per process, and leaked pollers add up
    grpc_stop = server.stop

    def stop(grace=None):
        box = stream_box[0]
        if box is not None:
            box.stop()
        return grpc_stop(grace)

    server.stop = stop
    logger.info("solver service listening on %s", address)
    return server


def _serve_health(service: SolverService, port: int):
    """Plain-HTTP probe endpoints for kubelet, plus ``/metrics`` and the
    trace debug surface: the session store AND the sidecar's span ring live
    in THIS process, so its catalog-residency counters and its half of
    every traced solve are only observable on the sidecar's own ports —
    the controller's registry and trace ring never see them."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Probe(BaseHTTPRequestHandler):
        def do_GET(self):
            ctype = "text/plain"
            if self.path == "/healthz":
                code, body = 200, b"ok"
            elif self.path == "/readyz":
                if service.ready.is_set():
                    code, body = 200, b"ok"
                else:
                    code, body = 503, b"warming"
            elif self.path == "/metrics":
                from prometheus_client import generate_latest

                from karpenter_tpu import metrics as _m

                code, body = 200, generate_latest(_m.REGISTRY)
            elif self.path.startswith("/debug/"):
                # every /debug/* body comes from the shared
                # obs.debug_*_payload helpers — byte-parity with the
                # controller health server by construction (karplint
                # `debug-endpoint` enforces the routing)
                from urllib.parse import urlsplit

                from karpenter_tpu import obs

                query = urlsplit(self.path).query
                code, ctype = 200, "application/json"
                if self.path.startswith("/debug/traces"):
                    body = _json.dumps(obs.debug_traces_payload(query)).encode()
                elif self.path.startswith("/debug/slo"):
                    body = _json.dumps(obs.debug_slo_payload(query)).encode()
                elif self.path.startswith("/debug/flight"):
                    body = _json.dumps(obs.debug_flight_payload(query)).encode()
                elif self.path.startswith("/debug/profile"):
                    # dual-typed: JSON by default, text/plain collapsed —
                    # the helper decides, the header must follow it (the
                    # controller handler does the same)
                    ctype, body = obs.debug_profile_payload(query)
                elif self.path.startswith("/debug/fleet"):
                    body = _json.dumps(obs.debug_fleet_payload(query)).encode()
                elif self.path.startswith("/debug/decisions"):
                    body = _json.dumps(
                        obs.debug_decisions_payload(query)
                    ).encode()
                elif self.path.startswith("/debug/forecast"):
                    body = _json.dumps(
                        obs.debug_forecast_payload(query)
                    ).encode()
                elif self.path.startswith("/debug/explain"):
                    body = _json.dumps(
                        obs.debug_explain_payload(query)
                    ).encode()
                elif self.path.startswith("/debug/incidents"):
                    body = _json.dumps(
                        obs.debug_incidents_payload(query)
                    ).encode()
                else:
                    code, ctype, body = 404, "text/plain", b"not found"
            else:
                code, body = 404, b"not found"
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    httpd = ThreadingHTTPServer(("0.0.0.0", port), Probe)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


# ---------------------------------------------------------------------------
# client (lives in the controller process)
# ---------------------------------------------------------------------------


class RemoteSolver:
    """Drop-in for ``kernel.pack``: ships the arrays to the sidecar and
    returns the PackResult tuple as host numpy arrays.

    v3: the catalog-side arrays are uploaded once per fingerprint
    (``OpenSession``); every ``pack`` ships the session key plus only the
    pod-side arrays. ``pack_begin`` dispatches without blocking (gRPC
    future) and returns ``wait()`` — the double-buffer seam: the scheduler
    releases its solve lock between the two, so encode(i+1) overlaps
    solve(i)'s wire+device time."""

    # fingerprint memos retained (catalog-side array identity -> key);
    # bounded like encode's _fp_cache, and holding the array refs so the
    # ids stay valid for each entry's lifetime
    KEY_MEMO_MAX = 8
    # opened-session keys retained: a churning catalog fingerprint mints a
    # new 16-byte key per generation and must not grow the set for the
    # process lifetime; evicting a LIVE key merely costs one redundant
    # re-open on its next use
    OPENED_MAX = 64

    def __init__(
        self,
        address: str,
        timeout: float = 30.0,
        cold_timeout: float = 180.0,
        checksum: bool = False,
        stream: bool = False,
        shm_dir: str = "",
        delta: bool = False,
    ):
        import grpc

        self.address = address
        self.timeout = timeout
        # resident pod-side deltas (docs/delta-encoding.md): when enabled
        # AND the sidecar advertised PROTO_DELTA, Pack requests frame the
        # pod side as establish/elide/patch against the base the sidecar
        # keeps resident — the steady state ships a 40-byte header instead
        # of re-serializing ~MBs of unchanged pod tensors every round
        self.delta = bool(delta)
        # (epoch, pod array refs) last shipped; the refs keep the identity
        # memo below valid and give the patch planner its diff base
        self._delta_base: Optional[Tuple[bytes, List[np.ndarray]]] = None  # guarded-by: self._lock
        # identity-memoized pod epochs, CatalogKeyMemo-style: the host
        # ResidentEncoder returns the SAME batch object on no-churn rounds,
        # so the hot path never re-hashes megabytes of pod tensors
        self._pod_epoch_memo: "OrderedDict[tuple, tuple]" = OrderedDict()  # guarded-by: self._lock
        # streaming transport (docs/solver-transport.md § Streaming):
        # when enabled AND the sidecar advertised PROTO_STREAM, solves
        # multiplex over one persistent stream (credit flow control,
        # out-of-order completion) with transparent unary fallback;
        # shm_dir additionally engages the zero-copy colocated fast path
        # once the sidecar acks the arena
        self._stream_enabled = bool(stream)
        self._shm_dir = shm_dir
        self._stream = None  # guarded-by: self._lock
        # end-to-end frame integrity (docs/integrity.md): when enabled AND
        # the sidecar advertised PROTO_CHECKSUM, Pack exchanges carry a
        # blake2b trailer both ways and the response must echo the session
        # key it solved against. OpenSession requests carry the trailer
        # unconditionally (old servers' variadic tail ignores it).
        self.checksum = bool(checksum)
        # first call per (P, n_max) shape must cover the sidecar's XLA
        # compile; later calls get the short deadline
        self.cold_timeout = cold_timeout
        self._warm_shapes = set()  # guarded-by: self._lock
        # capability bits the sidecar advertised in its OpenSession
        # response; 0 (an old sidecar, or no open yet) means the Pack
        # trace trailer is never sent — an old server's `*pod_arrays`
        # unpack would swallow it as an extra pod array mid-upgrade
        self._server_features = 0  # guarded-by: self._lock
        # catalog keys this client has uploaded (bounded LRU); a sidecar
        # restart orphans them server-side — NEEDS_CATALOG triggers the
        # transparent re-open
        self._opened: "OrderedDict[bytes, bool]" = OrderedDict()  # guarded-by: self._lock
        self._key_memo = CatalogKeyMemo(self.KEY_MEMO_MAX)
        self.session_uploads = 0  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
            ],
        )
        self._call = self._channel.unary_unary(METHOD)
        self._open_call = self._channel.unary_unary(OPEN_SESSION_METHOD)
        self._health_call = self._channel.unary_unary(HEALTH_METHOD)

    def health(self, timeout: float = 2.0) -> bool:
        """True when the sidecar reports SERVING (warmup done)."""
        try:
            return self._health_call(b"", timeout=timeout) == SERVING
        except Exception:
            return False

    # -- sessions -----------------------------------------------------------

    def _catalog_key(self, catalog_side: Tuple) -> bytes:
        return self._key_memo.key(catalog_side)

    def _open_session(
        self,
        key: bytes,
        catalog_side: Tuple,
        timeout: float,
        force: bool = False,
        record: bool = True,
    ) -> None:
        from karpenter_tpu import obs

        with self._lock:
            if not force and key in self._opened:
                self._opened.move_to_end(key)
                return
        arrays = (
            [_key_array(key)]
            + [np.asarray(a) for a in catalog_side]
            + [np.asarray([1 if record else 0], np.int32)]
        )
        span = obs.tracer().current()
        if span is not None:
            # safe on ANY server: old sidecars unpack the open request with
            # a variadic tail and ignore extra arrays
            arrays.append(_trace_ctx_array(span.context))
        request = pack_arrays(arrays)
        if self.checksum:
            # also safe on any server (variadic tail); a PROTO_CHECKSUM
            # server verifies it and checksums its response in kind
            request = append_checksum(request)
        with self._lock:
            require = bool(
                self.checksum and (self._server_features & PROTO_CHECKSUM)
            )
        with obs.tracer().span("solver.wire_open", attrs={"address": self.address}):
            response = self._dispatch_open(request, timeout)
        status, payload = self._receive_open(response, require)
        if status == STATUS_OVERLOADED:
            # HBM pressure or admission refusal: typed so the pool's soft
            # breaker (and the scheduler's local fallback) can tell
            # backpressure from failure — no real breaker may trip on it
            raise OverloadedError(
                f"solver {self.address} refused session open (overloaded)",
                retry_after=self._retry_after(payload),
            )
        if status != STATUS_OK:
            # typed verdicts (a corrupt OPEN request answers
            # STATUS_INTEGRITY → IntegrityError, which the pool turns into
            # a quarantine, not a windowed failure) + loud unknowns
            self._check_status(status, payload)
        features = int(payload[0].reshape(-1)[0]) if payload else 0
        with self._lock:
            self._server_features = features
        with self._lock:
            self._opened[key] = True
            self._opened.move_to_end(key)
            while len(self._opened) > self.OPENED_MAX:
                self._opened.popitem(last=False)
            self.session_uploads += 1

    # -- streamed transport ---------------------------------------------------

    def _stream_for(self, features: int):
        """The established stream client, or None (disabled, server too
        old, or down-and-backing-off — the unary path is the wait-free
        fallback in every case)."""
        if not self._stream_enabled or not (features & PROTO_STREAM):
            return None
        with self._lock:
            client = self._stream
            if client is None:
                from karpenter_tpu.solver.stream import StreamClient

                client = self._stream = StreamClient(
                    self._channel, self.address, shm_dir=self._shm_dir
                )
        return client if client.ensure() else None

    def _count_stream_fallback(self, reason: str) -> None:
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_STREAM_FALLBACKS.labels(
                address=self.address, reason=reason
            ).inc()
        except Exception:
            pass  # trimmed registries

    def _dispatch_open(self, request: bytes, timeout: float) -> bytes:
        """OpenSession, preferring the stream when one is up (the
        NEEDS_CATALOG re-open after a mid-stream sidecar restart rides
        the freshly re-established stream, not a unary detour)."""
        from karpenter_tpu.solver.stream import (
            StreamBrokenError,
            StreamUnavailable,
        )

        with self._lock:
            client = self._stream
        if client is not None and client.up:
            try:
                return client.open(request).result(timeout=timeout + 5.0)
            except (StreamBrokenError, StreamUnavailable):
                self._count_stream_fallback("open")
            except futures.TimeoutError:
                self._count_stream_fallback("open_timeout")
                client.break_stream("open future timed out")
        return self._open_call(request, timeout=timeout)

    @staticmethod
    def _split_status(response: bytes) -> Tuple[int, List[np.ndarray]]:
        status_arr, *payload = unpack_arrays(response)
        # the integrity trailer is transport framing, not payload
        payload = [a for a in payload if not is_checksum_array(a)]
        return int(status_arr.reshape(-1)[0]), payload

    def _receive(self, response: bytes, require_checksum: bool) -> Tuple[int, List[np.ndarray]]:
        """Verify-then-parse one Pack response frame. With integrity
        negotiated (``require_checksum``) a missing or disagreeing digest —
        or a frame too mangled to parse at all — is a typed
        :class:`IntegrityError` attributed to this member; without it, a
        present-but-wrong digest still fails (free defense), and parse
        errors propagate raw.

        One deliberate tolerance: a cleanly-parsing UNsealed
        ``NEEDS_CATALOG`` is the rollback signature — a member restarted
        on a pre-checksum build has an empty session store AND no seal —
        and its only effect is the forced re-open, which IS the
        capability-renegotiation channel (:meth:`_receive_open` decides
        there whether the downgrade is legitimate). Coherently rewriting a
        sealed frame into this shape would require re-framing, which
        random corruption does not do, and the worst it buys is one
        redundant re-open — never a decoded array."""
        try:
            verdict = verify_checksum(response)
            status, payload = self._split_status(response)
        except Exception as e:
            if require_checksum:
                self._record_checksum_failure()
                raise IntegrityError(
                    f"solver {self.address} sent an unparseable frame ({e})",
                    address=self.address, kind="frame",
                ) from e
            raise
        if verdict == "mismatch" or (
            verdict == "missing"
            and require_checksum
            and status != STATUS_NEEDS_CATALOG
        ):
            self._record_checksum_failure()
            raise IntegrityError(
                f"solver {self.address} response failed frame checksum "
                f"({verdict})",
                address=self.address, kind="checksum",
            )
        return status, payload

    def _receive_open(self, response: bytes, require_checksum: bool) -> Tuple[int, List[np.ndarray]]:
        """:meth:`_receive` with one extra tolerance: a cleanly-parsing
        UNchecksummed OpenSession response whose features word no longer
        advertises ``PROTO_CHECKSUM`` is a legitimate rollback to a
        pre-checksum build, NOT corruption — the open response IS the
        capability-negotiation channel (exactly as trusted as the original
        negotiation was), and refusing it would quarantine a healthy,
        merely older member until this process restarts. A response that
        still claims ``PROTO_CHECKSUM`` while omitting its negotiated
        trailer — or any digest mismatch — stays fatal: stripping a
        trailer coherently requires rewriting the framing, which random
        corruption does not do."""
        try:
            verdict = verify_checksum(response)
            status, payload = self._split_status(response)
        except Exception as e:
            if require_checksum:
                self._record_checksum_failure()
                raise IntegrityError(
                    f"solver {self.address} sent an unparseable open "
                    f"response ({e})",
                    address=self.address, kind="frame",
                ) from e
            raise
        if verdict == "mismatch":
            self._record_checksum_failure()
            raise IntegrityError(
                f"solver {self.address} open response failed frame checksum",
                address=self.address, kind="checksum",
            )
        if verdict == "missing" and require_checksum:
            features = (
                int(payload[0].reshape(-1)[0])
                if status == STATUS_OK and payload else 0
            )
            if features & PROTO_CHECKSUM:
                self._record_checksum_failure()
                raise IntegrityError(
                    f"solver {self.address} advertises PROTO_CHECKSUM but "
                    "sent no frame checksum",
                    address=self.address, kind="checksum",
                )
            logger.warning(
                "solver %s no longer advertises PROTO_CHECKSUM; disabling "
                "frame checksums toward this member", self.address,
            )
        return status, payload

    def _record_checksum_failure(self) -> None:
        try:
            from karpenter_tpu.solver import integrity

            integrity.record_checksum_failure(self.address)
        except Exception:
            pass  # trimmed registries

    @staticmethod
    def _retry_after(payload: List[np.ndarray]) -> float:
        """The f32 retry-after hint an OVERLOADED response leads with."""
        try:
            return float(np.asarray(payload[0]).reshape(-1)[0])
        except Exception:
            return 1.0

    def _check_status(self, status: int, payload: List[np.ndarray]) -> None:
        """Raise the typed verdict for any terminal non-OK status. An
        unknown word fails LOUD — a silent mis-parse on status would be
        the exact bug the version-skew check exists to prevent."""
        if status == STATUS_OK:
            return
        if status == STATUS_DEADLINE_EXCEEDED:
            raise DeadlineExceededError(
                f"solver {self.address} shed the solve: propagated round "
                "budget expired before device dispatch"
            )
        if status == STATUS_OVERLOADED:
            raise OverloadedError(
                f"solver {self.address} refused the solve (overloaded)",
                retry_after=self._retry_after(payload),
            )
        if status == STATUS_INTEGRITY:
            # the REQUEST arrived corrupt server-side: same quarantine
            # semantics as a corrupt response — the path, not the payload,
            # is what's broken, so never retry it on this member
            self._record_checksum_failure()
            raise IntegrityError(
                f"solver {self.address} rejected a corrupt request frame "
                "(checksum mismatch server-side)",
                address=self.address, kind="checksum",
            )
        raise RuntimeError(
            f"unknown solver status word {status} from {self.address}"
        )

    # -- pod-side deltas (docs/delta-encoding.md) ----------------------------

    POD_EPOCH_MEMO_MAX = 4
    # a patch only pays off while the changed-row slice is a fraction of
    # the full pod set; past a quarter of the rows the establish frame is
    # simpler and barely bigger
    PATCH_MAX_ROW_FRACTION = 4

    def _pod_epoch(self, pod_np: List[np.ndarray]) -> bytes:
        """Identity-memoized :func:`pod_epoch_key`: the no-churn round
        re-presents the same array objects, so the steady state skips the
        multi-MB blake2b entirely."""
        id_key = tuple(map(id, pod_np))
        with self._lock:
            hit = self._pod_epoch_memo.get(id_key)
            if hit is not None:
                self._pod_epoch_memo.move_to_end(id_key)
                return hit[1]
        epoch = pod_epoch_key(pod_np)
        with self._lock:
            self._pod_epoch_memo[id_key] = (tuple(pod_np), epoch)
            while len(self._pod_epoch_memo) > self.POD_EPOCH_MEMO_MAX:
                self._pod_epoch_memo.popitem(last=False)
        return epoch

    def _plan_delta(
        self, epoch: bytes, pod_np: List[np.ndarray], p: int
    ) -> Tuple[int, List[np.ndarray], bytes]:
        """Choose the delta frame kind against the last-shipped base:
        ``(kind, body arrays, base_epoch)``. Same epoch → elide; same
        shapes with few changed rows → patch; anything else → establish.
        The choice is pure optimization — every kind names ``epoch`` as
        its new_epoch, and the sidecar PROVES the resolved content hashes
        to it."""
        with self._lock:
            base = self._delta_base
        if base is not None and base[0] == epoch:
            return DELTA_ELIDE, [], epoch
        if base is not None and all(
            b.shape == a.shape and b.dtype == a.dtype
            for b, a in zip(base[1], pod_np)
        ):
            changed = np.zeros(p, dtype=bool)
            for b, a in zip(base[1], pod_np):
                diff = b != a
                changed |= diff.any(axis=tuple(range(1, diff.ndim))) if diff.ndim > 1 else diff
            idx = np.flatnonzero(changed).astype(np.int32)
            if idx.size and idx.size <= max(1, p // self.PATCH_MAX_ROW_FRACTION):
                return DELTA_PATCH, [idx] + [a[idx] for a in pod_np], base[0]
        return DELTA_ESTABLISH, list(pod_np), b"\x00" * 16

    def _remember_delta_base(self, epoch: bytes, pod_np: List[np.ndarray]) -> None:
        with self._lock:
            self._delta_base = (epoch, list(pod_np))

    @staticmethod
    def _count_delta_applied() -> None:
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_DELTA_APPLIED.labels(path="wire").inc()
        except Exception:
            pass  # trimmed registries

    @staticmethod
    def _count_delta_base_miss() -> None:
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_DELTA_EPOCH_MISMATCHES.labels(side="client").inc()
            metrics.SOLVER_DELTA_FULL_REENCODES.labels(reason="wire").inc()
        except Exception:
            pass  # trimmed registries

    # -- solves -------------------------------------------------------------

    def pack_begin(
        self, *inputs, n_max: int, prof: Optional[dict] = None, record: bool = True
    ):
        """Serialize the pod-side delta, ensure the session is open, and
        dispatch the Pack RPC WITHOUT blocking. Returns ``wait()`` →
        PackResult (host arrays). ``prof`` (the scheduler's per-solve stage
        dict) receives ``wire_ser_s``/``wire_deser_s`` so the bench can
        attribute serialization separately from the in-flight wait.
        ``record=False`` keeps this Pack out of the sidecar's hit-rate
        stats (shadow probes, saturation re-dispatches)."""
        from karpenter_tpu.resilience import current_budget
        from karpenter_tpu.solver.kernel import split_result

        # client-side pre-shed: a round whose budget already expired must
        # not even pay serialization — straight to the caller's FFD floor
        budget = current_budget.get()
        if budget is not None and budget.expired:
            raise DeadlineExceededError(
                "round budget expired before solver dispatch"
            )
        pod_side, catalog_side = inputs[:N_POD_ARRAYS], inputs[N_POD_ARRAYS:]
        key = self._catalog_key(catalog_side)
        p = len(inputs[0])
        r = inputs[6].shape[1]  # pod_req
        shape = (p, n_max)
        with self._lock:
            warm = shape in self._warm_shapes
        timeout = self.timeout if warm else self.cold_timeout
        # proactive open: the steady state short-circuits on the _opened
        # set; only a fresh catalog generation pays the upload RTT here
        self._open_session(key, catalog_side, timeout, record=record)
        from karpenter_tpu import obs

        t0 = time.perf_counter()
        with self._lock:
            features = self._server_features
        # integrity pair (docs/integrity.md), gated like every other
        # capability: frame checksums both ways + the session-key echo that
        # rejects a wrong-catalog-generation pack before decode
        integrity_on = bool(self.checksum and (features & PROTO_CHECKSUM))
        # pod-side deltas (docs/delta-encoding.md), gated like every other
        # capability: only after the sidecar advertised PROTO_DELTA
        delta_on = bool(self.delta and (features & PROTO_DELTA))
        flags = 0
        if integrity_on:
            flags |= PACK_FLAG_ECHO_SESSION
        if delta_on:
            flags |= PACK_FLAG_DELTA
        vals = [n_max, 1 if record else 0]
        if flags:
            vals.append(flags)
        head = [_key_array(key), np.asarray(vals, np.int32)]
        pod_np = [np.asarray(a) for a in pod_side]
        epoch = None
        delta_body: List[np.ndarray] = []
        if delta_on:
            epoch = self._pod_epoch(pod_np)
            kind, body, base_epoch = self._plan_delta(epoch, pod_np, p)
            n_idx = int(body[0].size) if kind == DELTA_PATCH else 0
            delta_body = [delta_header(kind, n_idx, base_epoch, epoch)] + body
            # optimistic: if the dispatch sheds before the sidecar pins
            # the new epoch, the next round's elide/patch misses and the
            # NEEDS_DELTA_BASE recovery re-establishes — fail loud, cheap
            self._remember_delta_base(epoch, pod_np)
            if kind != DELTA_ESTABLISH:
                self._count_delta_applied()
            if prof is not None:
                prof["delta_kind"] = (
                    "elide" if kind == DELTA_ELIDE
                    else "patch" if kind == DELTA_PATCH else "establish"
                )
        # optional trailers, each capability-gated on the bits the sidecar
        # advertised in its OpenSession response — an untraced (or
        # old-peer) frame is byte-identical to before, so rolling upgrades
        # in either order keep solving:
        # - trace context: the span active at DISPATCH time parents the
        #   sidecar's child spans (PROTO_TRACE_TRAILER);
        # - deadline: the round Budget's REMAINING seconds (relative —
        #   clocks never agree across the wire), so the sidecar can shed
        #   already-doomed work before device dispatch (PROTO_DEADLINE)
        trailers: List[np.ndarray] = []
        span = obs.tracer().current()
        if span is not None and (features & PROTO_TRACE_TRAILER):
            trailers.append(_trace_ctx_array(span.context))
        if budget is not None and (features & PROTO_DEADLINE):
            trailers.append(np.asarray([budget.remaining()], np.float32))

        def build_inline() -> bytes:
            req = pack_arrays(
                head + (delta_body if delta_on else pod_np) + trailers
            )
            # checksum LAST, over the final bytes: the digest covers
            # every trailer
            return append_checksum(req) if integrity_on else req

        def build_establish() -> bytes:
            """The NEEDS_DELTA_BASE (or post-re-open) fallback frame: the
            full pod set under a DELTA_ESTABLISH header — satisfiable by
            ANY delta-capable sidecar state, including a cold restart."""
            hdr = delta_header(DELTA_ESTABLISH, 0, b"\x00" * 16, epoch)
            self._remember_delta_base(epoch, pod_np)
            req = pack_arrays(head + [hdr] + pod_np + trailers)
            return append_checksum(req) if integrity_on else req

        # transport selection ladder (docs/solver-transport.md):
        # stream+shm → stream inline → unary. Credit exhaustion raises the
        # typed OverloadedError (kind="credits") HERE, at the sender —
        # the pool's soft-backoff path consumes the hint exactly as it
        # does a STATUS_OVERLOADED refusal. Stream unavailability is
        # never an error: the unary path is the wait-free fallback.
        from karpenter_tpu.solver.stream import (
            StreamBrokenError,
            StreamUnavailable,
        )

        request: Optional[bytes] = None
        stream_fut = None
        arena_token = None
        transport = "unary"
        stream = self._stream_for(features)
        if stream is not None:
            # delta frames always ride inline: a resident base must
            # outlive the arena slot it would arrive in (slots recycle
            # per solve), and the steady-state elide/patch payload is
            # already tiny — the arena only ever carried the full pod set
            wrote = None if delta_on else stream.write_arena(pod_np)
            if wrote is not None:
                arena_token, desc = wrote
                shm_req = pack_arrays(head + [desc] + trailers)
                if integrity_on:
                    shm_req = append_checksum(shm_req)
                try:
                    stream_fut = stream.solve_shm(shm_req)
                    transport = "stream_shm"
                except OverloadedError:
                    stream.free_arena(arena_token)
                    raise
                except StreamUnavailable:
                    stream.free_arena(arena_token)
                    arena_token = None
            if stream_fut is None:
                request = build_inline()
                try:
                    stream_fut = stream.solve(request)
                    transport = "stream"
                except StreamUnavailable:
                    pass  # fell down between ensure() and dispatch
        if stream_fut is None:
            if request is None:
                request = build_inline()
            grpc_future = self._call.future(request, timeout=timeout)
        else:
            grpc_future = None
        try:
            from karpenter_tpu import metrics

            metrics.SOLVER_STREAM_SOLVES.labels(
                address=self.address, transport=transport
            ).inc()
        except Exception:
            pass  # trimmed registries
        if prof is not None:
            prof["wire_ser_s"] = (
                prof.get("wire_ser_s", 0.0) + time.perf_counter() - t0
            )
            prof["solver_transport"] = transport
            # decision-audit provenance (docs/decisions.md): which pinned
            # catalog generation this solve rode — the replay tool and the
            # decision record name the session the sidecar solved against
            prof["session_key"] = key.hex()

        def redispatch(req: bytes) -> bytes:
            """The synchronous NEEDS_CATALOG retry dispatch: over the
            stream when one is up (the re-open itself just rode it), else
            unary. Stream failure mid-retry degrades to unary — the
            overlap is already lost, correctness wins."""
            if stream is not None and stream.up:
                try:
                    return stream.solve(req).result(timeout=timeout + 5.0)
                except (StreamBrokenError, StreamUnavailable):
                    self._count_stream_fallback("retry")
                except futures.TimeoutError:
                    self._count_stream_fallback("retry_timeout")
                    stream.break_stream("retry future timed out")
                except OverloadedError:
                    raise  # typed backpressure: the pool backs off
            return self._call(req, timeout=timeout)

        def wait():
            nonlocal request, arena_token
            with obs.tracer().span(
                "solver.wire",
                attrs={"address": self.address, "transport": transport},
            ) as wsp:
                # belt over the RPC's own deadline: the future resolves by
                # `timeout` in every healthy case, the slack only bounds a
                # misbehaving transport (karplint bounded-wait)
                if stream_fut is not None:
                    try:
                        response = stream_fut.result(timeout=timeout + 5.0)
                    except StreamBrokenError:
                        # the stream died with this solve in flight: the
                        # background thread is already re-establishing;
                        # THIS solve retries over the unary path now
                        self._count_stream_fallback("broken")
                        wsp.set_attribute("stream_fallback", True)
                        if request is None:
                            request = build_inline()
                        response = self._call(request, timeout=timeout)
                    except futures.TimeoutError:
                        self._count_stream_fallback("timeout")
                        wsp.set_attribute("stream_fallback", True)
                        stream.break_stream("solve future timed out")
                        if request is None:
                            request = build_inline()
                        response = self._call(request, timeout=timeout)
                    finally:
                        if arena_token is not None:
                            stream.free_arena(arena_token)
                            arena_token = None
                else:
                    response = grpc_future.result(timeout=timeout + 5.0)
                buf = stage = None
                # integrity expectation for THIS exchange; the forced
                # re-open below refreshes it, so a member rolled back to a
                # pre-checksum build recovers on the in-flight retry
                # instead of waiting out another breaker cool-off
                require = integrity_on
                # each distinct refusal reason earns ONE synchronous
                # recovery + redispatch (the overlap is already lost);
                # the same reason twice fails loud. Bounded: three
                # possible reasons, so ≤ 4 receives ever happen — a
                # sidecar restart legitimately chains two (delta base
                # gone AND catalog gone) and still converges.
                recovered: set = set()
                for _ in range(4):
                    status, payload = self._receive(response, require)
                    if status == STATUS_NEEDS_CATALOG:
                        reason = "not resident"
                    elif status == STATUS_NEEDS_DELTA_BASE:
                        # the sidecar no longer holds (or could not
                        # reproduce) the pod base this delta named —
                        # restart, LRU eviction, or a missed delta; the
                        # full establish below is satisfiable by any state
                        reason = "delta base missing"
                    else:
                        if status != STATUS_OK:
                            # typed verdicts (deadline/overload/integrity)
                            # + loud unknowns
                            wsp.set_attribute("status", status)
                            self._check_status(status, payload)
                        buf, stage, echoed = self._parse_pack_payload(payload)
                        if not require or echoed in (None, key):
                            break
                        # session-generation guard (docs/integrity.md): the
                        # sidecar solved against a DIFFERENT catalog
                        # generation (concurrent evict/re-open race, store
                        # rollback, replayed response) — never decode a
                        # wrong-catalog pack; audit, then recover through
                        # the NEEDS_CATALOG machinery
                        reason = "wrong-session echo"
                        try:
                            from karpenter_tpu.solver import integrity

                            integrity.record_session_mismatch(self.address)
                        except Exception:
                            pass  # trimmed registries
                        logger.warning(
                            "solver %s echoed session %s for a solve against "
                            "%s; re-opening", self.address,
                            echoed.hex()[:12], key.hex()[:12],
                        )
                    if reason in recovered:
                        if reason == "wrong-session echo":
                            raise IntegrityError(
                                f"solver {self.address} kept answering with "
                                f"the wrong catalog session (want "
                                f"{key.hex()[:12]})",
                                address=self.address, kind="session",
                            )
                        if reason == "delta base missing":
                            # the establish retry carried the FULL pod set
                            # and was still refused: the store is broken
                            # or thrashing — the caller's breaker turns
                            # this into the in-process fallback
                            raise RuntimeError(
                                "solver delta establish did not take "
                                f"(catalog key {key.hex()[:12]})"
                            )
                        # fail loud: something is evicting faster than we
                        # open (session_max=0, or a thrashing key) — the
                        # caller's breaker turns this into the in-process
                        # fallback
                        raise RuntimeError(
                            "solver session re-open did not take "
                            f"(catalog key {key.hex()[:12]})"
                        )
                    recovered.add(reason)
                    logger.info(
                        "solver session %s %s; recovering",
                        key.hex()[:12], reason,
                    )
                    if reason == "delta base missing":
                        wsp.set_attribute("delta_establish_retry", True)
                        self._count_delta_base_miss()
                    else:
                        # sidecar restarted, evicted this catalog, or
                        # served the wrong generation: re-open, then retry
                        wsp.set_attribute("needs_catalog_retry", True)
                        self._open_session(
                            key, catalog_side, timeout, force=True,
                            record=record,
                        )
                        with self._lock:
                            # DOWNWARD-only refresh: the server seals iff
                            # the REQUEST carried a checksum, and the
                            # retried request is the original bytes — so a
                            # re-open that just learned PROTO_CHECKSUM
                            # (pre-checksum member upgraded mid-flight)
                            # must not raise the expectation above what
                            # this request asked for
                            require = require and bool(
                                self._server_features & PROTO_CHECKSUM
                            )
                    if delta_on:
                        # ANY recovery redispatch ships the full pod set:
                        # an elide/patch retried against a re-opened but
                        # restarted sidecar would only bounce once more
                        request = build_establish()
                    elif request is None:
                        request = build_inline()
                    response = redispatch(request)
                else:
                    raise RuntimeError(
                        f"solver {self.address} retry loop exhausted"
                    )  # unreachable: ≤3 distinct reasons, repeats raise above
                with self._lock:
                    self._warm_shapes.add(shape)
                t1 = time.perf_counter()
                if stage is not None:
                    # the sidecar's stage trailer: graft its half of the RTT
                    # into this tree as completed child records — the
                    # remainder of the wire span is pure transport
                    for name, seconds in zip(
                        ("sidecar.solve", "sidecar.fetch", "sidecar.serialize"),
                        stage[:3],
                    ):
                        wsp.add_child_record(name, float(seconds))
                out = split_result(buf, p, n_max, r)
                if prof is not None:
                    prof["wire_deser_s"] = (
                        prof.get("wire_deser_s", 0.0) + time.perf_counter() - t1
                    )
                    prof["solver_address"] = self.address  # pack provenance
                return out

        return wait

    @staticmethod
    def _parse_pack_payload(payload: List[np.ndarray]):
        """An OK Pack payload → ``(fused buf, stage trailer | None,
        echoed session key | None)``. Trailers are shape/dtype-addressed
        (f32[3] = sidecar stages, i32[4] = the 16-byte session echo), so
        any subset in any order parses — the rolling-upgrade contract."""
        buf = payload[0]
        stage = echoed = None
        for extra in payload[1:]:
            a = np.asarray(extra).reshape(-1)
            if a.dtype == np.float32 and a.size == 3:
                stage = a
            elif a.dtype == np.int32 and a.size == 4:
                echoed = a.tobytes()
        return buf, stage, echoed

    def pack(self, *inputs, n_max: int):
        """Synchronous convenience wrapper over ``pack_begin``."""
        return self.pack_begin(*inputs, n_max=n_max)()

    def close(self) -> None:
        with self._lock:
            stream = self._stream
            self._stream = None
        if stream is not None:
            stream.close()
        self._channel.close()


def main(argv: Optional[List[str]] = None) -> None:
    """Sidecar entrypoint: ``python -m karpenter_tpu.solver.service``."""
    import argparse
    import time

    ap = argparse.ArgumentParser(prog="karpenter-solver-service")
    ap.add_argument("--address", default="127.0.0.1:50051")
    ap.add_argument("--max-workers", type=int, default=4)
    ap.add_argument("--health-port", type=int, default=8081)
    ap.add_argument("--session-max", type=int, default=SESSION_MAX)
    ap.add_argument("--session-ttl", type=float, default=SESSION_TTL_S)
    ap.add_argument("--solver-max-inflight", type=int, default=MAX_INFLIGHT,
                    help="concurrent solves admitted to the device executor; "
                         "everything past this queues (docs/overload.md)")
    ap.add_argument("--solver-queue-depth", type=int, default=QUEUE_DEPTH,
                    help="solve requests allowed to queue behind the "
                         "inflight cap; beyond it requests are refused "
                         "STATUS_OVERLOADED with a retry-after hint")
    ap.add_argument("--overload-retry-after", type=float,
                    default=OVERLOAD_RETRY_AFTER_S,
                    help="retry-after hint (seconds) carried by "
                         "STATUS_OVERLOADED responses; pool clients sit "
                         "out the member for this window")
    ap.add_argument("--hbm-floor-bytes", type=int, default=0,
                    help="device-memory headroom floor: below it NEW "
                         "session uploads are refused STATUS_OVERLOADED "
                         "while resident-session solves keep flowing "
                         "(0 disables)")
    ap.add_argument("--solver-shm-dir", default="",
                    help="shared-memory directory for the zero-copy "
                         "colocated fast path: clients on the same host "
                         "pass pod arrays through an mmap'd arena and the "
                         "stream carries only offsets ('' disables; "
                         "docs/solver-transport.md)")
    ap.add_argument("--solver-coalesce-window", type=float, default=None,
                    metavar="SECONDS",
                    help="cross-stream dispatch-coalescing collection "
                         "window: concurrent streamed solves with matching "
                         "session/shapes within it share ONE device "
                         "dispatch (default 0.002; 0 still coalesces "
                         "whatever is already queued)")
    ap.add_argument("--flight-dir", default="",
                    help="capped on-disk ring for slow-solve flight records "
                         "('' disables; served at GET /debug/flight)")
    ap.add_argument("--flight-budget-ms", type=float, default=100.0,
                    help="sidecar.pack spans over this budget are recorded")
    ap.add_argument("--slo-window", type=float, default=300.0,
                    help="online SLO fast evaluation window in seconds "
                         "(slow burn-rate window is 12x; GET /debug/slo)")
    ap.add_argument("--slo-config", default="",
                    help="objectives file ('' = the sidecar defaults: "
                         "sidecar.pack.p99 + session.catalog_hit_rate)")
    ap.add_argument("--profile-hz", type=float, default=19.0,
                    help="sampling-profiler stack-sample rate in Hz "
                         "(0 disables; GET /debug/profile serves the folds)")
    ap.add_argument("--telemetry-dir", default="",
                    help="shared fleet-telemetry directory this sidecar "
                         "flushes its span trees / SLO histograms / profile "
                         "folds into ('' disables; docs/telemetry.md)")
    ap.add_argument("--telemetry-flush-interval", type=float, default=10.0,
                    help="seconds between telemetry flushes")
    ap.add_argument("--sentinel", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="regression sentinel over the sidecar's own span "
                         "stream (sidecar.pack and the solve stages): "
                         "online latency baselines + change-point "
                         "detection; GET /debug/incidents serves the "
                         "incident records")
    ap.add_argument("--sentinel-dir", default="",
                    help="directory the sentinel persists learned baselines "
                         "into across restarts ('' = memory-only)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    from karpenter_tpu import obs

    if args.flight_dir:
        # the sidecar's end-to-end unit is its own pack span
        obs.configure_flight(
            args.flight_dir, budget_s=args.flight_budget_ms / 1e3,
            watch=("sidecar.pack",),
        )
    # the sidecar judges its own half of the objectives: its pack span and
    # the session store it owns (controller-side spans never reach here)
    obs.configure_slo(
        objectives=(
            obs.load_objectives(args.slo_config)
            if args.slo_config
            else obs.SIDECAR_OBJECTIVES
        ),
        window_s=args.slo_window,
    )
    if args.profile_hz > 0:
        # always-on sampling profiler: the sidecar's device/serialize hot
        # loops are exactly the frames a fleet-wide slow solve needs named
        obs.configure_profiler(hz=args.profile_hz)
    if args.sentinel:
        # the sidecar learns baselines for its OWN stages (the pack span
        # plus the device solve/fetch legs) — the controller's sentinel
        # only sees wire totals, so device-side regressions attribute here
        obs.configure_sentinel(
            directory=args.sentinel_dir,
            watch=("sidecar.pack", "sidecar.solve", "sidecar.fetch"),
        )
    if args.telemetry_dir:
        # flush-only member of the fleet telemetry plane: the controller's
        # collector stitches this ring's sidecar.pack trees into its own
        # solver.wire parents (docs/telemetry.md)
        obs.configure_telemetry(
            identity=f"sidecar-{args.address}",
            role="sidecar",
            directory=args.telemetry_dir,
            flush_interval=args.telemetry_flush_interval,
        )
    server = serve(
        args.address, args.max_workers, health_port=args.health_port, warmup=True,
        service=SolverService(
            session_max=args.session_max, session_ttl=args.session_ttl,
            max_inflight=args.solver_max_inflight,
            queue_depth=args.solver_queue_depth,
            overload_retry_after=args.overload_retry_after,
            hbm_floor_bytes=args.hbm_floor_bytes,
        ),
        shm_dir=args.solver_shm_dir,
        coalesce_window_s=args.solver_coalesce_window,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop(grace=2)


if __name__ == "__main__":
    main()
