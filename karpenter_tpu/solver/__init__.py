"""The TPU-native batch bin-pack solver.

Replaces the reference's FFD hot loop
(``pkg/controllers/provisioning/scheduling/scheduler.go:84-99`` +
``node.go:46-66``) with a two-level design built for XLA:

- **Host (signature layer)**: the full requirements algebra (complement sets,
  escape hatches, taints, offerings) runs once per *constraint signature* —
  the equivalence class of a pod's scheduling constraints — instead of once
  per pod×node. Signatures, their pairwise join table, surviving
  instance-type masks, and Pareto capacity frontiers are dense arrays handed
  to the device. See ``signature.py``.
- **Device (packing kernel)**: a jitted ``lax.scan`` performs exact first-fit
  in FFD order; per-node state is just {signature id, hostname id, resource
  totals}, and the fit test is a compare against the signature's capacity
  frontier. See ``kernel.py``.

The decomposition is behavior-preserving: the parity suite asserts
assignment-identical results against the FFD reference on randomized
scenarios (``tests/test_solver_parity.py``).
"""

from karpenter_tpu.solver.backend import TpuScheduler  # noqa: F401
