"""Constraint signatures: the host-side half of the TPU solver.

A *core* is the canonical form of one pod's own scheduling requirements
(nodeSelector + folded node affinity), excluding the hostname key (hostname
has unbounded vocabulary and single-value join semantics, so the kernel
carries it as an int field instead).

A *signature* is the constraint state of a virtual node: the provisioner's
base constraints joined with the cores of every pod placed on it. Signatures
form a closure under join; the closure, the join table, each signature's
surviving instance types, and each signature's Pareto capacity frontier are
computed here with the exact ``Requirements`` algebra, so the device kernel
never needs to understand label semantics.

Mirrors the accept test of ``scheduling/node.go:46-66``:
  accept = (node has pods → Requirements.Compatible(node, pod))
           ∧ (∃ surviving instance type fitting requests)
Compatibility lives in the join table; type survival + fit live in the
frontier.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from collections import OrderedDict

import numpy as np

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement, Pod
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider.requirements import compatible as type_compatible
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.utils import resources as res

# A core: tuple of (key, operator, sorted values) triples, sorted by key then
# position — canonical and hashable.
Core = Tuple[Tuple[str, str, Tuple[str, ...]], ...]

MAX_SIGNATURES = 512  # closure cap; beyond this the backend falls back to FFD


def pod_core_and_hostname(pod: Pod) -> Tuple[Core, Optional[str]]:
    """Canonicalize a pod's own requirements, split into (core, hostname).

    Must fold exactly like ``Requirements.from_pod`` (nodeSelector + heaviest
    preferred term + first required term), but without building Requirements
    objects per pod — this runs for every pod in a 10k batch.
    """
    reqs: List[Tuple[str, str, Tuple[str, ...]]] = []
    hostname: Optional[str] = None
    for key, value in pod.spec.node_selector.items():
        key = lbl.NORMALIZED_LABELS.get(key, key)
        if key in lbl.IGNORED_LABELS:
            continue
        if key == lbl.HOSTNAME:
            hostname = value
            continue
        reqs.append((key, "In", (value,)))
    aff = pod.spec.affinity
    if aff is not None and aff.node_affinity is not None:
        na = aff.node_affinity
        terms: List[NodeSelectorRequirement] = []
        if na.preferred:
            heaviest = max(na.preferred, key=lambda t: t.weight)
            terms.extend(heaviest.preference.match_expressions)
        if na.required:
            terms.extend(na.required[0].match_expressions)
        for t in terms:
            key = lbl.NORMALIZED_LABELS.get(t.key, t.key)
            if key in lbl.IGNORED_LABELS:
                continue
            if key == lbl.HOSTNAME and t.operator == "In" and len(t.values) == 1:
                hostname = t.values[0]
                continue
            reqs.append((key, t.operator, tuple(t.values)))
    return tuple(sorted(reqs)), hostname


def core_to_requirements(core: Core) -> Requirements:
    return Requirements.new(
        *(NodeSelectorRequirement(key=k, operator=op, values=list(vals)) for k, op, vals in core)
    )


@dataclass
class Signature:
    """One node-constraint state in the closure."""

    sig_id: int
    requirements: Requirements  # base ⊕ joined cores (hostname-free)
    type_mask: np.ndarray  # [T] bool — types surviving requirement compat
    frontier: np.ndarray  # [F, R] f32 — Pareto-max usable capacities
    has_fit: bool  # any type survives at all


def _pareto_max(points: np.ndarray) -> np.ndarray:
    """Pareto-maximal rows of [n, R] (rows not dominated elementwise-≤ by
    another row), deduplicated. Broadcasted O(n²·R) numpy — this runs once
    per signature, inside the solve latency budget."""
    if len(points) == 0:
        return points
    points = np.unique(points, axis=0)  # dedupe (and sorts rows)
    ge = np.all(points[:, None, :] >= points[None, :, :], axis=-1)  # ge[j,i]: j ≥ i everywhere
    gt = np.any(points[:, None, :] > points[None, :, :], axis=-1)  # gt[j,i]: j > i somewhere
    dominated = np.any(ge & gt, axis=0)  # i dominated by some j
    return points[~dominated]


class SignatureTable:
    """Closure of node-constraint signatures under pod-core joins.

    Lazily materialized: signatures and join entries are computed on demand
    and memoized, so a solve only pays for the combinations its pods produce.
    """

    def __init__(
        self,
        base: Constraints,
        instance_types: Sequence[InstanceType],
        usable_capacity: np.ndarray,  # [T, R] capacity - overhead, f32
        resource_axes: Sequence[str],
    ):
        self.base = base
        self.instance_types = list(instance_types)
        self.usable = usable_capacity
        self.axes = list(resource_axes)
        self.signatures: List[Signature] = []
        self._sig_by_req_str: Dict[str, int] = {}
        self._open_cache: Dict[Core, int] = {}  # core -> sig id of base⊕core
        self._join_cache: Dict[Tuple[int, Core], int] = {}
        self._core_reqs: Dict[Core, Requirements] = {}
        self._mask_matrix: Optional[np.ndarray] = None
        # per-cores-vocabulary closure results (dense local reindex, join
        # table, frontiers, open sigs) — filled by encode; valid for the
        # table's lifetime because joins/signatures are append-only and
        # base-invariant (set_base only refreshes hostname state, which is
        # deliberately outside signatures)
        self._closure_memo: "OrderedDict[Tuple, Tuple]" = OrderedDict()
        # per-(closure, daemon, active-axes) TRIMMED catalog-side arrays —
        # filled by encode so steady-state solves return identity-stable
        # frontiers/daemon objects (the session transport fingerprints the
        # catalog side by id; a fresh array per solve would re-hash the
        # full tensors under the solve lock every batch)
        self._trim_memo: "OrderedDict[Tuple, Tuple]" = OrderedDict()
        # signature 0 is the base itself
        self._base_hostnames = base.requirements.get(lbl.HOSTNAME)
        self._intern(self._strip_hostname(base.requirements))

    def set_base(self, base: Constraints) -> None:
        """Refresh the per-solve hostname state on a table reused across
        solves (topology injection registers fresh generated hostnames into
        the constraints every batch; signatures themselves are
        hostname-free, so they stay valid)."""
        self.base = base
        self._base_hostnames = base.requirements.get(lbl.HOSTNAME)

    def type_mask_matrix(self) -> np.ndarray:
        """[S, T] stacked signature→type compatibility, cached until the
        closure grows — re-stacking per decode was a hot spot."""
        if self._mask_matrix is None or self._mask_matrix.shape[0] != len(self.signatures):
            self._mask_matrix = np.stack([s.type_mask for s in self.signatures])
        return self._mask_matrix

    # hostname is carried separately by the kernel; keep it out of signatures
    def _strip_hostname(self, reqs: Requirements) -> Requirements:
        return Requirements.new(
            *(r for r in reqs.requirements if r.key != lbl.HOSTNAME)
        )

    def hostname_in_base(self, hostname: str) -> bool:
        return self._base_hostnames.has(hostname)

    def _core_requirements(self, core: Core) -> Requirements:
        r = self._core_reqs.get(core)
        if r is None:
            r = core_to_requirements(core)
            self._core_reqs[core] = r
        return r

    def _intern(self, requirements: Requirements) -> int:
        key = str(requirements)
        sid = self._sig_by_req_str.get(key)
        if sid is not None:
            return sid
        if len(self.signatures) >= MAX_SIGNATURES:
            raise SignatureOverflow(f"signature closure exceeded {MAX_SIGNATURES}")
        type_mask = np.array(
            [type_compatible(it, requirements) for it in self.instance_types], dtype=bool
        )
        usable = self.usable[type_mask]
        frontier = _pareto_max(usable)
        sid = len(self.signatures)
        self.signatures.append(
            Signature(
                sig_id=sid,
                requirements=requirements,
                type_mask=type_mask,
                frontier=frontier,
                has_fit=bool(type_mask.any()),
            )
        )
        self._sig_by_req_str[key] = sid
        return sid

    def open_signature(self, core: Core) -> int:
        """Signature of a fresh node opened for a pod with this core: the
        base constraints merged with the pod's requirements. No compatibility
        check — the reference skips Compatible for a node's first pod
        (node.go:52-57); only type survival gates it (checked by the caller
        via the frontier)."""
        sid = self._open_cache.get(core)
        if sid is None:
            merged = self.signatures[0].requirements.add(
                *self._core_requirements(core).requirements
            )
            sid = self._intern(merged)
            self._open_cache[core] = sid
        return sid

    def join(self, sig_id: int, core: Core) -> int:
        """Join a pod core onto a node signature. Returns the joined
        signature id, or -1 if Requirements.Compatible rejects the pod
        (node.go:52-57 → requirements.go:175-191)."""
        key = (sig_id, core)
        out = self._join_cache.get(key)
        if out is None:
            node_reqs = self.signatures[sig_id].requirements
            pod_reqs = self._core_requirements(core)
            if node_reqs.compatible(pod_reqs):
                out = -1
            else:
                out = self._intern(node_reqs.add(*pod_reqs.requirements))
            self._join_cache[key] = out
        return out


class SignatureOverflow(Exception):
    """Raised when the constraint diversity of a batch exceeds the closure
    cap; the backend falls back to the host FFD path."""
