"""Constraint-elimination attribution: WHY a (pod, instance-type) pair died.

The packing kernel returns an assignment, never a reason — which makes the
single most-asked operational question ("why is my pod still pending?" /
"why THIS instance type?") unanswerable from the solver alone. This module
answers it from the tensors :mod:`solver.encode` already built, with cheap
mask reductions OFF the hot path:

- a pod's fresh-node signature (``pod_open_sig``) carries the exact
  requirement algebra the kernel solved with — its ``type_mask`` says which
  catalog types survive requirement compatibility, and replaying the
  per-key checks of ``cloudprovider.requirements.compatible`` against the
  signature's ``Requirements`` names the dimension that killed each
  excluded type (label requirement vs zone/capacity-type offering);
- the trimmed ``usable`` capacity matrix + ``pod_req`` + ``daemon`` split
  the resource story three ways: the type can't fit the pod at all
  (``resource_fit``), it fits the pod alone but not plus the daemon
  overhead (``daemon_overhead``), or — pod-level — no requirement-
  compatible type fits, i.e. the signature's Pareto capacity frontier
  admits nothing (``capacity_frontier``, the kernel's native formulation);
- ``pod_open_host == -2`` is the poisoned-hostname state (the pod pins a
  hostname the base domains exclude): ``hostname``.

Because everything here is a pure function of the ENCODED batch (host
context) plus the assignment — and every accelerated route (native,
device, pool, streamed, coalesced) is assignment-bit-exact by the parity
contract — the verdicts are identical regardless of which backend served
the solve. tests/test_explain.py pins the attribution against brute-force
single-constraint ablation re-solves on the native packer, and
tests/test_solver_stream.py pins streamed/coalesced parity.

The ``taint`` dimension never reaches the solver (selection's
``validate_pod`` gates intolerant pods before a batch forms); the decision
plane maps selection-level rejections onto it (obs/decisions.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api import labels as lbl

# The elimination dimensions (one vocabulary: per-candidate reasons, pod
# top reasons, the karpenter_pods_unschedulable{reason} label, and the
# PodUnschedulable event message all use these tokens).
REASON_RESOURCE = "resource_fit"
REASON_REQUIREMENT = "requirement"
REASON_ZONE = "zone_topology"
REASON_DAEMON = "daemon_overhead"
REASON_FRONTIER = "capacity_frontier"
# hostname appears as a verdict ANNOTATION (a poisoned pin never
# eliminates a fresh-node placement — the reference skips compatibility
# for a node's first pod), kept in the vocabulary for the gauge label
REASON_HOSTNAME = "hostname"
REASON_TAINT = "taint"  # selection/admission layer (decisions.py maps it)

ALL_REASONS = (
    REASON_RESOURCE, REASON_REQUIREMENT, REASON_ZONE, REASON_DAEMON,
    REASON_FRONTIER, REASON_HOSTNAME, REASON_TAINT,
)

# per-pod candidate list cap: the COUNTS are always complete; the listed
# examples are bounded so a 400-type catalog never inflates a record
DEFAULT_MAX_CANDIDATES = 20


def _requirement_dimension(it, requirements, sets=None) -> Tuple[str, str]:
    """Which check of ``cloudprovider.requirements.compatible`` excluded
    this type from the signature — the same checks in the same order, so
    the attributed dimension is the one the encoder actually applied.
    Returns ``(reason, detail key)``. ``sets`` hoists the five ValueSet
    lookups out of a per-type loop."""
    if sets is None:
        sets = _req_sets(requirements)
    it_set, arch_set, os_set, zone_set, ct_set = sets
    if not it_set.has(it.name):
        return REASON_REQUIREMENT, lbl.INSTANCE_TYPE
    if not arch_set.has(it.architecture):
        return REASON_REQUIREMENT, lbl.ARCH
    if not os_set.has_any(it.operating_systems):
        return REASON_REQUIREMENT, lbl.OS
    for key, value in it.labels.items():
        if requirements.has(key) and not requirements.get(key).has(value):
            return REASON_REQUIREMENT, key
    if not any(
        zone_set.has(o.zone) and ct_set.has(o.capacity_type)
        for o in it.offerings
    ):
        return REASON_ZONE, lbl.TOPOLOGY_ZONE
    # compatible() said no but every individual check passes — cannot
    # happen while the two walks agree; report honestly rather than lie
    return REASON_REQUIREMENT, "unknown"


def _req_sets(requirements):
    return (
        requirements.get(lbl.INSTANCE_TYPE),
        requirements.get(lbl.ARCH),
        requirements.get(lbl.OS),
        requirements.get(lbl.TOPOLOGY_ZONE),
        requirements.get(lbl.CAPACITY_TYPE),
    )


def _sig_requirement_verdicts(sig, types) -> List[Optional[Tuple[str, str]]]:
    """Per-type requirement-family verdicts for one signature — ``None``
    for requirement-compatible types. MEMOIZED ON the Signature object:
    the verdicts are a pure function of (signature requirements, catalog),
    both fixed for the signature's lifetime (the SignatureTable pins its
    catalog), so steady-state rounds re-explaining the same signature pay
    one dict probe, not a 400-type replay — the explain hot-path budget
    (<1% of solve) depends on this."""
    cached = getattr(sig, "_explain_req_verdicts", None)
    if cached is not None and len(cached) == len(types):
        return cached
    sets = _req_sets(sig.requirements)
    mask = np.asarray(sig.type_mask, bool)
    verdicts: List[Optional[Tuple[str, str]]] = [
        None if mask[t]
        else _requirement_dimension(types[t], sig.requirements, sets)
        for t in range(len(types))
    ]
    try:
        sig._explain_req_verdicts = verdicts
    except AttributeError:
        pass  # a frozen/foreign signature object: just don't memoize
    return verdicts


def _binding_axes(usable_row, need, axis_names) -> List[str]:
    """The resource axes where the request exceeds this type's usable
    capacity — the concrete numbers behind a resource_fit verdict."""
    over = np.flatnonzero(np.asarray(need) > np.asarray(usable_row))
    return [axis_names[int(i)] for i in over]


# cross-round verdict memo capacity, kept on each SignatureTable (the
# table outlives batches via the EncodeCache, so steady-state rounds
# re-explaining the same (signature, request) pay one dict probe)
_VERDICT_MEMO_MAX = 64


def _verdict_core(batch, sig_id: int, need_alone, need_with, max_candidates):
    """The (pod-independent) elimination aggregation for one (signature,
    request vector): complete per-dimension counts + detail keys, the
    capped example-candidate list, viable-type count, and the frontier
    verdict. Memoized on the batch's SignatureTable keyed by (signature,
    request bytes) — the table pins catalog + usable + daemon context."""
    table = batch.table
    memo = getattr(table, "_explain_memo", None)
    if memo is None:
        from collections import OrderedDict as _OD

        memo = table._explain_memo = _OD()
    sig = batch.signatures[sig_id]
    # keyed by the SIGNATURE OBJECT, never the batch-local sig id: encode
    # re-indexes ids densely per core vocabulary, so the same local id
    # names different signatures across batches while this memo outlives
    # them on the shared table. The axis tuple pins the trimmed-axis
    # identity (same-length request bytes over different active axes must
    # not collide). Signature objects are table-held and append-only, so
    # their ids are stable for the memo's lifetime.
    key = (
        id(sig),
        need_alone.tobytes(),
        np.asarray(batch.daemon).tobytes(),
        tuple(batch.axis_names),
    )
    hit = memo.get(key)
    if hit is not None:
        memo.move_to_end(key)
        return hit
    types = table.instance_types
    usable = np.asarray(batch.usable)
    mask = np.asarray(sig.type_mask, bool)
    fit_alone = (usable >= need_alone).all(axis=1)
    fit_with = (usable >= need_with).all(axis=1)
    # the kernel's own gate: does ANY Pareto frontier row of this
    # signature admit the pod (request + daemon)?
    fr = np.asarray(batch.frontiers[sig_id])
    frontier_admits = bool((fr >= need_with).all(axis=-1).any())

    counts: Dict[str, int] = {}
    details: Dict[str, set] = {}
    candidates: List[Dict] = []

    def add(type_name: str, reason: str, detail: str) -> None:
        counts[reason] = counts.get(reason, 0) + 1
        if detail:
            details.setdefault(reason, set()).add(detail)
        if len(candidates) < max_candidates:
            candidates.append(
                {"type": type_name, "reason": reason, "detail": detail}
            )

    req_verdicts = _sig_requirement_verdicts(sig, types)
    for t in np.flatnonzero(~mask):
        reason, detail = req_verdicts[int(t)]
        add(types[int(t)].name, reason, detail)
    for t in np.flatnonzero(mask & ~fit_alone):
        axes = _binding_axes(usable[int(t)], need_alone, batch.axis_names)
        add(types[int(t)].name, REASON_RESOURCE, ",".join(axes))
    for t in np.flatnonzero(mask & fit_alone & ~fit_with):
        axes = _binding_axes(usable[int(t)], need_with, batch.axis_names)
        add(types[int(t)].name, REASON_DAEMON, ",".join(axes))
    viable = int((mask & fit_with).sum())

    top = top_reason(counts, viable=viable, frontier_admits=frontier_admits)
    sig_str = getattr(sig, "_explain_str", None)
    if sig_str is None:
        sig_str = str(sig.requirements)
        try:
            sig._explain_str = sig_str
        except AttributeError:
            pass
    # everything pod-independent lives in the memo — a steady-state round
    # re-explaining the same (signature, request) shape merges one dict
    out = {
        "signature": sig_str,
        "types_total": len(types),
        "viable_types": viable,
        "frontier_admits": frontier_admits,
        "reasons": counts,
        "reason_details": {k: sorted(v) for k, v in details.items()},
        "candidates": candidates,
        "top_reason": top,
        "message": reason_message(counts, top, viable=viable),
    }
    memo[key] = out
    while len(memo) > _VERDICT_MEMO_MAX:
        memo.popitem(last=False)
    return out


def explain_pod(
    batch,
    idx: int,
    max_candidates: int = DEFAULT_MAX_CANDIDATES,
) -> Dict:
    """Per-candidate elimination breakdown for one pod of the batch
    (``idx`` is the batch-local index, i.e. FFD solve order).

    Pure host numpy + the signature's Requirements object — no device, no
    wire, no route dependence. Candidate counts are complete; the listed
    example candidates are capped at ``max_candidates``. The per-
    (signature, request) aggregation is memoized on the batch's
    SignatureTable, so template-collapsed pods — and steady-state rounds
    re-explaining the same shapes — pay one dict probe."""
    table = batch.table
    types = table.instance_types
    pod = batch.pods[idx]
    sig_id = int(np.asarray(batch.pod_open_sig)[idx])

    need_alone = np.asarray(batch.pod_req)[idx]
    need_with = need_alone + np.asarray(batch.daemon)
    core = _verdict_core(batch, sig_id, need_alone, need_with, max_candidates)
    out = {"pod": pod.key, **core}
    if int(np.asarray(batch.pod_open_host)[idx]) == -2:
        # poisoned hostname pin (the pod's hostname is outside the base
        # domains): per the reference semantics a node's FIRST pod skips
        # the compatibility check (node.go:52-57), so the pin never
        # eliminates placement by itself — it only poisons the opened
        # node for later hostname-constrained peers. Annotation, not an
        # eliminator.
        hid = int(np.asarray(batch.pod_host)[idx])
        out["hostname_poisoned"] = (
            batch.hostnames[hid] if hid >= 0 else "?"
        )
    return out


def top_reason(
    counts: Dict[str, int], viable: int = 0, frontier_admits: bool = True
) -> str:
    """The single dominant dimension (the metrics label / event headline).

    ``capacity_frontier`` is the pod-level rollup for "requirement-
    compatible types exist, but none fits the request + daemon" — unless
    every compatible type fails even WITHOUT the daemon overhead
    (``resource_fit``) or every one fits alone and only the overhead kills
    it (``daemon_overhead``), which are the sharper verdicts."""
    if viable > 0:
        return ""  # a viable fresh-node type exists: not eliminated here
    if REASON_HOSTNAME in counts:
        return REASON_HOSTNAME
    req_family = {
        k: v for k, v in counts.items()
        if k in (REASON_REQUIREMENT, REASON_ZONE, REASON_TAINT)
    }
    res_family = {
        k: v for k, v in counts.items()
        if k in (REASON_RESOURCE, REASON_DAEMON)
    }
    if res_family and not frontier_admits:
        if REASON_RESOURCE not in counts:
            return REASON_DAEMON
        if REASON_DAEMON not in counts:
            return REASON_RESOURCE
        return REASON_FRONTIER
    if res_family:
        return REASON_FRONTIER
    if req_family:
        return max(req_family, key=req_family.get)
    return REASON_FRONTIER if not frontier_admits else ""


def reason_message(
    counts: Dict[str, int], top: str, viable: int = 0
) -> str:
    """Human headline, e.g. ``no type satisfies requirement ∧
    zone_topology`` — every dimension that eliminated at least one type,
    dominant first."""
    if viable > 0 or not counts:
        return "schedulable on a fresh node"
    parts = sorted(counts, key=counts.get, reverse=True)
    joined = " ∧ ".join(parts)
    if top and top not in parts:
        joined = f"{top} ({joined})"
    return f"no type satisfies {joined}"


def explain_batch(
    batch,
    assignment: Optional[np.ndarray] = None,
    only_unschedulable: bool = True,
    max_candidates: int = DEFAULT_MAX_CANDIDATES,
) -> List[Dict]:
    """Verdicts for a batch: by default only the pods the assignment left
    unplaced (``assignment < 0``; ``assignment=None`` = every pod, the
    pre-solve view)."""
    n = batch.n_pods
    if assignment is not None:
        a = np.asarray(assignment).reshape(-1)[:n]
        indices = (
            np.flatnonzero(a < 0).tolist() if only_unschedulable
            else list(range(n))
        )
    else:
        indices = list(range(n))
    out = []
    for i in indices:
        verdict = explain_pod(batch, int(i), max_candidates=max_candidates)
        if assignment is not None:
            placed = bool(np.asarray(assignment).reshape(-1)[i] >= 0)
            verdict["placed"] = placed
        out.append(verdict)
    return out
