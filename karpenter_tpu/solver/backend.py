"""TpuScheduler: the accelerator-backed solve path.

Same contract as ``FFDScheduler.solve`` (and assignment-identical results —
see tests/test_solver_parity.py): sort, inject topology, encode to dense
tensors, run the packing kernel, decode virtual nodes. Falls back to the host
FFD when a batch's constraint diversity overflows the signature closure.
"""

from __future__ import annotations

import copy
import logging
import random
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement, Pod
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.resilience.integrity import IntegrityError
from karpenter_tpu.resilience.overload import (
    DeadlineExceededError,
    OverloadedError,
)
from karpenter_tpu.scheduling.ffd import (
    FFDScheduler,
    VirtualNode,
    daemon_overhead,
    sort_pods_ffd_with_statics,
)
from karpenter_tpu.scheduling.topology import (
    Topology,
    restore_selectors,
    snapshot_selectors,
)
from karpenter_tpu.solver import encode as enc
from karpenter_tpu.solver import kernel
from karpenter_tpu.solver.signature import SignatureOverflow
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils import resources as res

logger = logging.getLogger("karpenter.solver")

# Sidecar RPC budget: short deadline + an open circuit after failure so a
# dead sidecar costs one bounded stall, not one per batch.
REMOTE_SOLVE_TIMEOUT = 5.0
REMOTE_BREAKER_SECONDS = 30.0

# Per-shape-class pack breaker: two failures of a shape class open it and
# its solves route straight to the FFD fallback (no failure latency per
# batch) until a half-open probe finds the accelerated path healthy again.
PACK_BREAKER_WINDOW = 6
PACK_BREAKER_MIN_VOLUME = 2
PACK_BREAKER_OPEN_SECONDS = 30.0

# (P, S, F, n_max) whose fused compile/run failed — those shapes take the
# unfused ladder from then on (mirrors pallas_kernel._pallas_failed_shapes).
# Written from solve threads and the router's shadow-probe thread while
# other solves iterate it: snapshot/mutate under the lock, or a probe's
# add() lands mid-iteration and raises RuntimeError inside a solve.
_fused_failed_lock = threading.Lock()
_fused_failed_shapes: set = set()  # guarded-by: _fused_failed_lock


def _with_hostname(reqs, hostname: str, cache: dict):
    """``reqs.add(NodeSelectorRequirement(HOSTNAME, In, [hostname]))`` with
    the signature-invariant parts (requirements tuple, sorted sets minus the
    hostname entry, the hostname key's position and prior ValueSet) computed
    once per signature — decode runs this for every hostname-pinned node."""
    from karpenter_tpu.api.requirements import Requirements
    from karpenter_tpu.utils.sets import ValueSet

    hit = cache.get(id(reqs))
    if hit is None:
        items = list(reqs._sets)
        host_pos = None
        base_set = None
        for pos, (k, vs) in enumerate(items):
            if k == lbl.HOSTNAME:
                host_pos = pos
                base_set = vs
                break
        if host_pos is None:
            # insertion point that keeps the items key-sorted
            host_pos = sum(1 for k, _ in items if k < lbl.HOSTNAME)
        hit = cache[id(reqs)] = (reqs, reqs.requirements, items, host_pos, base_set)
    _, base_reqs, items, host_pos, base_set = hit
    vs = ValueSet.of(hostname)
    if base_set is not None:
        vs = vs.intersection(base_set)
        out_items = list(items)
        out_items[host_pos] = (lbl.HOSTNAME, vs)
    else:
        out_items = list(items)
        out_items.insert(host_pos, (lbl.HOSTNAME, vs))
    req = NodeSelectorRequirement(
        key=lbl.HOSTNAME, operator="In", values=[hostname]
    )
    return Requirements(base_reqs + (req,), tuple(out_items))


class TpuScheduler:
    def __init__(
        self,
        cluster: Cluster,
        rng: Optional[random.Random] = None,
        service_address: Optional[str] = None,
        pack_checksum: Optional[bool] = None,
        canary_rate: Optional[float] = None,
        solver_stream: Optional[bool] = None,
        solver_shm_dir: Optional[str] = None,
        solver_delta: Optional[bool] = None,
    ):
        import os as _os

        from karpenter_tpu.options import env_bool, env_float

        self.cluster = cluster
        # streaming transport knobs (docs/solver-transport.md § Streaming):
        # persistent multiplexed streams toward the sidecar(s), plus the
        # zero-copy shm arena when controller and sidecar share a host.
        # None = the env twins, the same contract as the integrity knobs.
        self.solver_stream = (
            bool(solver_stream) if solver_stream is not None
            else env_bool("KARPENTER_SOLVER_STREAM")
        )
        self.solver_shm_dir = (
            solver_shm_dir if solver_shm_dir is not None
            else _os.environ.get("KARPENTER_SOLVER_SHM_DIR", "")
        )
        # corruption defense (docs/integrity.md): per-frame wire checksums
        # on the sidecar path (capability-gated; off keeps the wire
        # byte-identical), and the canary cross-check rate — the fraction
        # of device/pool solves re-solved on the in-process native packer
        # off the hot path and compared. None = the env twins (one parser,
        # options.py's), so bench legs and tests can flip them without
        # re-plumbing constructors.
        self.pack_checksum = (
            bool(pack_checksum) if pack_checksum is not None
            else env_bool("KARPENTER_PACK_CHECKSUM")
        )
        self.canary_rate = (
            float(canary_rate) if canary_rate is not None
            else env_float("KARPENTER_CANARY_RATE")
        )
        # seeded so a bench/test run's canary sampling is reproducible;
        # the rate, not the sequence, is the contract
        self._canary_rng = random.Random(0xCA7A17)  # guarded-by: self._canary_lock
        self._canary_thread: Optional[threading.Thread] = None  # guarded-by: self._canary_lock
        self._canary_lock = threading.Lock()
        self.topology = Topology(cluster, rng=rng)
        self._ffd_fallback = FFDScheduler(cluster, rng=rng)
        # remote sidecar transport (SURVEY §5.8); None = in-process kernel
        self.service_address = service_address
        self._remote = None  # guarded-by: self._remote_init_lock
        self._remote_init_lock = threading.Lock()
        # circuit breaker after RPC failure (resilience layer): window 1 /
        # min_volume 1 keeps the round-1 contract — a dead sidecar trips on
        # ANY failure, success history notwithstanding, and costs one
        # bounded stall, not one per batch (half-open probes re-admit it)
        from karpenter_tpu.resilience import BreakerBoard, CircuitBreaker

        self._remote_breaker = CircuitBreaker(
            dependency=f"solver-service:{service_address}" if service_address else "",
            window=1, min_volume=1, failure_rate=0.5,
            open_seconds=REMOTE_BREAKER_SECONDS,
        )
        # per-shape-class breakers over the whole accelerated pack: a shape
        # whose device AND native paths keep failing degrades to FFD
        # immediately instead of re-paying the failure latency every solve
        self._pack_breakers = BreakerBoard(
            window=PACK_BREAKER_WINDOW,
            min_volume=PACK_BREAKER_MIN_VOLUME,
            failure_rate=0.5,
            open_seconds=PACK_BREAKER_OPEN_SECONDS,
        )
        # solve-invariant encode state (signature table, capacity matrix),
        # reused across this worker's batches; the lock covers the rare
        # concurrent solve (warmup thread vs first real batch)
        self._encode_cache = enc.EncodeCache()
        # resident delta encoding (docs/delta-encoding.md): keep the encoded
        # pod side resident across rounds and patch it from per-pod deltas,
        # epoch-guarded so staleness fails loud into a full re-encode. None
        # = the env twin, the same contract as the streaming knobs. Used
        # only under the solve lock (the EncodeCache contract).
        self.solver_delta = (
            bool(solver_delta) if solver_delta is not None
            else env_bool("KARPENTER_SOLVER_DELTA")
        )
        self._resident = None
        if self.solver_delta:
            from karpenter_tpu.solver.delta import ResidentEncoder

            self._resident = ResidentEncoder(self._encode_cache)
        # per-axis-vocabulary scale vectors for decode: axis_names is
        # identity-stable across steady-state solves (the trim memo), so
        # the AXIS_SCALES gather runs once per vocabulary, not per decode
        self._scales_memo: Dict[int, tuple] = {}
        # decode residency (docs/delta-encoding.md): when the SAME resident
        # batch solves to a bit-identical result under compatible
        # constraints, the VirtualNodes are rebuilt from the previous
        # decode's derived per-node rows instead of re-running the
        # grouping/readout pipeline. Written/read as one tuple snapshot —
        # decode runs OFF the solve lock, and a losing racer only pays a
        # fresh decode. The hit flag is thread-local like the profile.
        self._dec_memo: Optional[tuple] = None
        self._dec_tl = threading.local()
        # validation memo: (decode memo generation, pods list, daemon) of
        # the last PASSED _validate_pack. A decode served from the
        # residency memo is bit-identical to the plan that passed, so
        # re-deriving 10k per-pod totals would re-prove a proved fact; a
        # FAILED validation never arms the memo, so corrupt results are
        # re-checked every round no matter how often the device repeats
        # them bit-for-bit
        self._validate_memo: Optional[tuple] = None
        # device-resident solve invariants for the fused dispatch; the lock
        # guards the lazy init — the shadow-probe thread and a production
        # solve can both hit the None check, and two DeviceInvariants would
        # split the LRU (every solve re-uploading what the other cached)
        self._device_cache = None  # guarded-by: self._device_cache_lock
        # pod-side device residency (docs/delta-encoding.md § device),
        # lazy like the invariants cache and only with --solver-delta
        self._pod_residency = None  # guarded-by: self._device_cache_lock
        self._device_cache_lock = threading.Lock()
        self._solve_lock = threading.Lock()
        # per-stage timings of the most recent solve (bench surfaces these
        # as the latency breakdown the <100ms target is judged against);
        # published at solve BEGIN, so it may be mid-flight
        self.last_profile: Dict[str, float] = {}
        # the most recent COMPLETED solve's profile, published atomically
        # after its last stage write — what observers (the provisioning
        # stage histogram) snapshot, so they never see a concurrent
        # solve's partial dict. The thread-local holds the SAME thing per
        # calling thread: a worker sharing this scheduler must observe its
        # OWN solve's stages, not whichever solve completed last.
        self.last_completed_profile: Dict[str, float] = {}
        self._completed_tl = threading.local()
        # the most recent COMPLETED solve's decision context (encoded
        # batch + assignment + route provenance) — what the decision
        # audit log (obs/decisions.py) attributes eliminations from.
        # Thread-local like the profile (a worker sharing this scheduler
        # must record ITS round, not a concurrent one's), and CONSUMED on
        # read so a finished round's multi-MB EncodedBatch is not pinned
        # until the next solve.
        self._decision_tl = threading.local()
        # measured-cost backend routing (VERDICT r4 weak #3: `auto` used to
        # prefer the device by platform, never by cost)
        from karpenter_tpu.solver.router import default_router

        self.router = default_router()
        # probe starts now happen in the finish phase, OFF the solve lock:
        # two batches finishing together must not double-spawn a probe
        self._probe_thread: Optional[threading.Thread] = None  # guarded-by: self._probe_lock
        self._probe_lock = threading.Lock()
        # flight-recorder state panels: when a slow solve is recorded, its
        # incident file carries the router's beliefs, the breaker states,
        # and the session cache's disposition AT THAT MOMENT — the three
        # questions a human asks first. Names are stable across scheduler
        # hot-swaps (re-registering replaces the provider).
        from karpenter_tpu import obs
        from karpenter_tpu.solver import session_stats

        obs.register_state("router_ema", self.router.report)
        obs.register_state("pack_breakers_open", self._pack_breakers.open_dependencies)
        obs.register_state("remote_breaker", lambda: self._remote_breaker.state)
        obs.register_state("session_cache", session_stats.snapshot)
        # the integrity panel: checksum/canary/screen/quarantine counters
        # at incident time — the first question after a quarantine fires
        from karpenter_tpu.solver import integrity as _integrity

        obs.register_state("integrity", _integrity.snapshot)

    def _pack(self, batch: enc.EncodedBatch):
        """BEGIN the packing solve (called under the solve lock): route by
        MEASURED cost when more than one backend can serve the batch — the
        device path (sidecar / fused / Pallas ladder) and the native C++
        packer are both first-class contenders, and the per-shape EMA of
        end-to-end pack time decides (``solver: tpu`` must never be slower
        than its own CPU path, solver/router.py) — and dispatch the chosen
        backend WITHOUT blocking. Returns ``finish()`` →
        ``(PackResult, typemask-or-None)`` with HOST numpy arrays (one
        device→host transfer): only ``finish`` blocks on the fetch/RPC, so
        the caller releases the solve lock between the two phases and the
        next batch's encode overlaps this solve's in-flight device time
        (the double-buffered pipeline, docs/solver-transport.md).
        ``KARPENTER_PACKER`` forces still bypass routing."""
        import os

        # captured under the lock: by finish time a concurrent solve may
        # have re-published last_profile, and this solve's bookkeeping must
        # not land in that solve's dict
        prof = self.last_profile
        if os.environ.get("KARPENTER_PACKER", "auto").lower() == "auto":
            candidates = self._pack_candidates()
            if len(candidates) > 1:
                key = self._route_key(batch)
                backend = self.router.choose(key, candidates)
                # the router's decision and its inputs land on the active
                # span (solve.pack_begin): a trace of a slow solve shows
                # which backend served it and what the EMAs believed
                from karpenter_tpu import obs

                cur = obs.tracer().current()
                if cur is not None:
                    cur.set_attribute("router_backend", backend)
                    cur.set_attribute("router_key", "x".join(map(str, key)))
                    for c in candidates:
                        ema = self.router.ema(key, c)
                        if ema is not None:
                            cur.set_attribute(f"router_ema_{c}_ms", round(ema * 1e3, 3))
                t0 = time.perf_counter()
                if backend == "native":
                    # synchronous host compute — nothing in flight to
                    # overlap, so it runs wholly in the finish phase and
                    # the solve lock is held only for the dispatch-shaped
                    # begin, same as the device path
                    def finish_native():
                        try:
                            out = self._pack_native(batch, prof=prof)
                        except Exception:
                            # a failed pack must record a PENALTY, not its
                            # (tiny) elapsed time — a fast-failing backend
                            # would otherwise win the EMA and pin every
                            # future solve to the broken path. Probes
                            # rehabilitate it once it works again.
                            self.router.record_failure(key, backend)
                            # containment parity with the old pack_best
                            # ladder: a broken native lib degrades to the
                            # device path, never crashes the reconcile
                            logger.exception(
                                "routed native pack failed; device ladder fallback"
                            )
                            out = self._pack_device(batch, prof=prof)()
                        else:
                            self.router.record(key, backend, time.perf_counter() - t0)
                        # packer_backend is set by the path that actually
                        # served (the fallback may differ from the route)
                        if self.router.should_probe(key):
                            self._shadow_probe(batch, key, candidates, backend)
                        return out

                    return finish_native
                try:
                    device_finish = self._pack_device(batch, prof=prof)
                except (OverloadedError, DeadlineExceededError):
                    # a shed is backpressure, not a path failure: poisoning
                    # the device EMA with the 60s penalty would route every
                    # future solve off a path that is merely full right now
                    raise
                except Exception:
                    self.router.record_failure(key, backend)
                    raise  # the device ladder already ends in lax.scan

                def finish_device():
                    try:
                        out = device_finish()
                    except (OverloadedError, DeadlineExceededError):
                        raise  # shed, not failure: no EMA penalty
                    except Exception:
                        self.router.record_failure(key, backend)
                        raise
                    self.router.record(key, backend, time.perf_counter() - t0)
                    if self.router.should_probe(key):
                        self._shadow_probe(batch, key, candidates, backend)
                    return out

                return finish_device
        return self._pack_device(batch, prof=prof)

    def _shadow_probe(self, batch, key, candidates, winner: str) -> None:
        """Re-measure the losing backend(s) OFF the critical path — on a
        daemon thread, at most one in flight — so drift (tunnel weather,
        chip attach, host load) can re-win the route without production
        solves ever paying a loser's latency. The device probe's fetch wait
        releases the GIL; a losing native probe is slow precisely when it
        lost, so it must not run inline either."""
        losers = [c for c in candidates if c != winner]
        if not losers:
            return

        def probe():
            nonlocal batch
            try:
                for loser in losers:
                    t0 = time.perf_counter()
                    try:
                        if loser == "native":
                            self._pack_native(batch, prof={})
                        else:
                            self._pack_device(
                                batch, prof={}, record_session=False
                            )()
                    except Exception:
                        logger.debug("%s shadow probe failed", loser, exc_info=True)
                    else:
                        self.router.record(key, loser, time.perf_counter() - t0)
            finally:
                # drop the closure's cell: _probe_thread keeps the finished
                # Thread (and this closure) alive until the next probe for
                # this worker, which for a rare shape class would pin the
                # multi-MB EncodedBatch indefinitely
                batch = None

        with self._probe_lock:
            if self._probe_thread is not None and self._probe_thread.is_alive():
                return  # previous probe still running; next cadence hit retries
            t = threading.Thread(
                target=probe, name="karpenter-router-probe", daemon=True
            )
            self._probe_thread = t
            # started under the lock: is_alive() is False for an assigned-
            # but-unstarted thread, so a concurrent finisher checking the
            # guard before this start() would spawn a second probe
            t.start()

    @staticmethod
    def _route_key(batch: enc.EncodedBatch) -> tuple:
        """Shape CLASS for the router's cost memos: P is already bucketed
        by encode's padding, but S (signature count) and F (frontier width)
        are exact per-batch values — a churning cluster would mint a fresh
        key per reconcile mix, re-paying cold start on production solves
        and growing the process-shared EMA tables without bound. Pow2
        bucketing keeps the landscape to a few dozen classes whose cost is
        smooth within each.

        The last element is CONSTRAINT DENSITY: whether affinity/topology
        decisions pinned any pod to a hostname. BENCH_r05's blindspot —
        affinity-dense solves (device pack_fetch 220ms vs native 1.7ms)
        shared an EMA with hostname-free batches of the same (P, S, F), so
        the device path's win on the sparse shape routed the dense one
        blind. Splitting the class lets dense solves route to native until
        the device path earns them back."""
        S, F = batch.frontiers.shape[0], batch.frontiers.shape[1]
        return (
            len(batch.pod_valid),
            1 << max(S - 1, 0).bit_length(),
            1 << max(F - 1, 0).bit_length(),
            int(bool((batch.pod_host >= 0).any())),
        )

    def _pack_candidates(self) -> List[str]:
        """Backends that can serve this worker right now, in cold-start
        preference order: the device path first (its one-time compile then
        lands in the worker warmup; always servable — the lax.scan kernel
        needs only jax), then the native packer (non-blocking — while its
        g++ build is still running it simply isn't a candidate)."""
        from karpenter_tpu.solver import native

        candidates = ["device"]
        if native.native_available():
            candidates.append("native")
        return candidates

    def _pack_native(self, batch: enc.EncodedBatch, prof: Optional[dict] = None):
        """The native C++ packer as a routed first-class backend, with the
        same small-table-then-retry contract as the device path. ``prof``
        lets a shadow probe keep its bookkeeping out of ``last_profile``."""
        from karpenter_tpu.solver import native

        prof = self.last_profile if prof is None else prof
        p = len(batch.pod_valid)
        n_max = max(256, p // 4)
        prof["packer_backend"] = "native"
        prof["pack_dispatches"] = 0
        args = batch.pack_args()
        while True:
            prof["pack_dispatches"] += 1
            result = native.pack_native(*args, n_max=n_max)
            saturated = int(result.n_nodes) == n_max and bool(
                (np.asarray(result.assignment)[: batch.n_pods] < 0).any()
            )
            if not saturated or n_max >= p:
                return result, None
            n_max = p

    def _pack_device(
        self,
        batch: enc.EncodedBatch,
        prof: Optional[dict] = None,
        record_session: bool = True,
    ):
        """BEGIN the device-path ladder — sidecar when configured, fused
        single-dispatch when eligible, then the pack_best kernel ladder —
        and return ``finish()``. The begin phase dispatches the first
        attempt (async — JAX dispatch and the gRPC future both return
        before the solve lands); only ``finish`` blocks on the fetch.

        ``record_session=False`` (shadow probes) keeps the catalog-residency
        stats solve-only; saturation re-dispatches within one solve are
        likewise counted once.

        The node table starts small (512 slots — per-pod kernel cost is
        linear in the table size, and real packings open far fewer nodes
        than pods) and retries at full P on saturation (table full with
        unscheduled pods); the rare retry re-dispatches inside ``finish``,
        off the solve lock."""
        prof = self.last_profile if prof is None else prof
        p = len(batch.pod_valid)
        route0 = self._fused_route(batch, min(p, 512))
        n_max0 = min(p, 512) if route0 else max(256, p // 4)
        prof["pack_dispatches"] = 0
        args_box: list = [None]
        rec_box: list = [record_session]  # consumed by the first fused lookup

        def dispatch(n_max: int, route: Optional[str]):
            """One async dispatch → ``(fetch, route-or-None)``. A fused
            DISPATCH failure (trace/compile) blacklists the shape and falls
            straight to the unfused ladder."""
            prof["pack_dispatches"] += 1
            rec, rec_box[0] = rec_box[0], False
            if route:
                try:
                    fetch = self._pack_fused_begin(batch, n_max, route, record=rec)
                except Exception:
                    self._fused_blacklist(batch, n_max, route)
                else:
                    prof["packer_backend"] = "device"
                    return fetch, route
            if args_box[0] is None:
                args_box[0] = batch.pack_args()
            return self._pack_once_begin(args_box[0], p, n_max, prof, record=rec), None

        fetch0, taken0 = dispatch(n_max0, route0)

        def finish():
            n_max, fetch, taken = n_max0, fetch0, taken0
            while True:
                try:
                    result, typemask = fetch()
                except Exception:
                    if taken is None:
                        raise
                    # same containment contract as pack_best: one
                    # pathological shape must not crash the batch or
                    # degrade other shapes — record it and take the
                    # unfused ladder (which has its own v1→v2→scan
                    # fallbacks)
                    self._fused_blacklist(batch, n_max, taken)
                    if args_box[0] is None:
                        args_box[0] = batch.pack_args()
                    prof["pack_dispatches"] += 1
                    # record=False: this solve already counted at dispatch
                    fetch = self._pack_once_begin(
                        args_box[0], p, n_max, prof, record=False
                    )
                    taken = None
                    continue
                saturated = int(result.n_nodes) == n_max and bool(
                    (np.asarray(result.assignment)[: batch.n_pods] < 0).any()
                )
                if not saturated or n_max >= p:
                    return result, typemask
                n_max = p
                # routing is n_max-dependent (the v2 VMEM gate): re-derive
                # for the full-table retry
                fetch, taken = dispatch(n_max, self._fused_route(batch, n_max))

        return finish

    def _fused_blacklist(self, batch: enc.EncodedBatch, n_max: int, route: str) -> None:
        shape = self._fused_shape(batch, n_max)
        logger.exception(
            "fused %s solve failed for shape %s; unfused ladder", route, shape,
        )
        with _fused_failed_lock:
            _fused_failed_shapes.add(shape)

    @staticmethod
    def _fused_shape(batch: enc.EncodedBatch, n_max: int) -> tuple:
        return (
            len(batch.pod_valid), batch.frontiers.shape[0],
            batch.frontiers.shape[1], n_max,
        )

    def _fused_route(self, batch: enc.EncodedBatch, n_max: int) -> Optional[str]:
        """Which fused single-dispatch route serves this batch at this node
        table size — ``"v1"`` (the unrolled Pallas kernel's shapes: TPU,
        lane-aligned P, S·F within the unroll budget), ``"v2"`` (the
        matmul-gather kernel for constraint-diverse batches past the v1
        budget whose tables fit VMEM), or ``None`` (unfused ladder). Both
        require the interned ids to fit the compact i16 upload. A
        configured sidecar takes precedence (its own process owns the
        device), and a shape whose fused compile/run already failed stays
        on the unfused ladder."""
        import os

        if os.environ.get("KARPENTER_PACKER", "auto").lower() not in ("auto", "fused"):
            return None
        if self.service_address and self._remote_breaker.available():
            return None
        from karpenter_tpu.solver import fused
        from karpenter_tpu.solver.pallas_kernel import (
            BLOCK,
            pallas_available,
            pallas_shape_eligible,
        )
        from karpenter_tpu.solver.pallas_kernel_v2 import v2_vmem_ok

        P = len(batch.pod_valid)
        S, F = batch.frontiers.shape[0], batch.frontiers.shape[1]
        with _fused_failed_lock:
            failed = any(s[:3] == (P, S, F) for s in _fused_failed_shapes)
        if failed:
            return None
        if not fused.ids_fit(batch):
            return None
        if pallas_shape_eligible(P, S, F):
            return "v1"
        C = batch.join_table.shape[1]
        R = batch.frontiers.shape[2]
        if (
            pallas_available()
            and P % BLOCK == 0
            and v2_vmem_ok(S, n_max, C, F * R)
        ):
            return "v2"
        return None

    def _pack_fused_begin(
        self, batch: enc.EncodedBatch, n_max: int, route: str, record: bool = True
    ):
        """Dispatch the fused single-dispatch solve (one compact upload,
        solver/fused.py) and return ``fetch()`` — the one fused device→host
        transfer, the only blocking step. Join table, frontiers, daemon,
        type masks and usable capacities — and on the v2 route the per-core
        join tables — ride the device-resident invariants cache (``record``
        gates its session-residency stats — see DeviceInvariants.get)."""
        import jax

        from karpenter_tpu.solver import fused

        if self._device_cache is None:
            with self._device_cache_lock:
                if self._device_cache is None:
                    self._device_cache = fused.DeviceInvariants()
        if self.solver_delta and self._pod_residency is None:
            with self._device_cache_lock:
                if self._pod_residency is None:
                    self._pod_residency = fused.PodResidency()
        if self._pod_residency is not None:
            # pod-side residency (docs/delta-encoding.md § device): a
            # no-churn round reuses the resident upload by batch identity,
            # a small-churn round patches it in place on device
            pod_tab, open_by_core, bhh, uniq = self._pod_residency.get(batch)
        else:
            pod_tab, open_by_core, bhh = fused.pack_pod_table(batch)
            uniq = fused.pad_uniq_req(batch.uniq_req)
        if route == "v2":
            (front_j_d, compat_j_d, jvals_d, front_d, daemon_d, mask_d,
             usable_d) = self._device_cache.get_v2(batch, record=record)
            out = fused.fused_solve_v2(
                pod_tab, open_by_core, bhh, uniq,
                front_j_d, compat_j_d, jvals_d, front_d, daemon_d,
                mask_d, usable_d,
                n_max=n_max,
                F=batch.frontiers.shape[1],
                R=batch.frontiers.shape[2],
            )
        else:
            join_d, front_d, daemon_d, mask_d, usable_d = self._device_cache.get(
                batch, record=record
            )
            from karpenter_tpu.solver.pallas_kernel import pallas_available

            out = fused.fused_solve(
                pod_tab, open_by_core, bhh, uniq,
                join_d, front_d, daemon_d, mask_d, usable_d,
                n_max=n_max, kernel="pallas" if pallas_available() else "scan",
            )

        def fetch():
            buf = jax.device_get(out)
            return fused.split_fused(
                buf, len(batch.pod_valid), n_max, batch.usable.shape[1],
                batch.usable.shape[0],
            )

        return fetch

    def _remote_or_init(self):
        if self._remote is None:
            # under-lock init: the router's device shadow probe can
            # reach here concurrently with a cold-starting solve
            with self._remote_init_lock:
                if self._remote is None:
                    if "," in self.service_address:
                        # sidecar POOL: consistent-hash session routing with
                        # per-member breakers and ring failover; this outer
                        # breaker then only trips when the whole pool is
                        # exhausted (solver/pool.py)
                        from karpenter_tpu.solver.pool import SolverPool

                        pool = SolverPool(
                            self.service_address.split(","),
                            timeout=REMOTE_SOLVE_TIMEOUT,
                            checksum=self.pack_checksum,
                            stream=self.solver_stream,
                            shm_dir=self.solver_shm_dir,
                            delta=self.solver_delta,
                        )
                        # integrity quarantines fired inside the pool
                        # surface as cluster Warning events through the
                        # scheduler (the pool has no cluster handle)
                        pool.on_quarantine = self._integrity_event
                        self._remote = pool
                    else:
                        from karpenter_tpu.solver.service import RemoteSolver

                        self._remote = RemoteSolver(
                            self.service_address, timeout=REMOTE_SOLVE_TIMEOUT,
                            checksum=self.pack_checksum,
                            stream=self.solver_stream,
                            shm_dir=self.solver_shm_dir,
                            delta=self.solver_delta,
                        )
        return self._remote

    def _remote_failure(self, e: Exception) -> None:
        # open the circuit: a dead sidecar must not stall every
        # batch for a full RPC deadline; half-open probes re-admit
        # it once it answers again
        tripped = self._remote_breaker.record_failure()
        metrics.SOLVER_BREAKER_OPEN.labels(address=self.service_address).set(1)
        if tripped:
            metrics.SOLVER_BREAKER_TRIPS.labels(address=self.service_address).inc()
        logger.error(
            "solver service %s failed (%s); in-process kernel for %.0fs",
            self.service_address, e, REMOTE_BREAKER_SECONDS,
        )

    # -- integrity (docs/integrity.md) ---------------------------------------

    def _integrity_event(self, reason: str, address: str, detail: str) -> None:
        """Every quarantine is a cluster Warning event: an operator must
        see 'this member produced corrupt data' next to the pods it almost
        mis-scheduled, not only on a dashboard."""
        try:
            from karpenter_tpu.kube.events import recorder_for

            recorder_for(self.cluster).event(
                "Solver", address or "in-process", "IntegrityQuarantine",
                f"pack integrity violation ({reason}): {detail} — "
                "docs/integrity.md has the runbook",
                type="Warning",
            )
        except Exception:
            logger.debug("integrity event write failed", exc_info=True)

    def _remote_integrity_failure(self, e: IntegrityError) -> None:
        """Corruption attributed to the single configured sidecar (a pool
        quarantines its own member internally and never re-raises
        IntegrityError): quarantine it — ``trip()``, the immediate-OPEN
        correctness edge — and let the caller serve in-process."""
        logger.error(
            "solver service %s quarantined for corruption (%s); in-process "
            "kernel for %.0fs", self.service_address, e, REMOTE_BREAKER_SECONDS,
        )
        self._quarantine_source(
            e.address or self.service_address or "", e.kind, str(e)
        )

    def _quarantine_source(
        self, address: str, reason: str, detail: str, batch=None
    ) -> None:
        """Quarantine whatever produced a corrupt pack RESULT (screen,
        canary, invalid decoded plan), attributed by the pack's provenance:
        a pool member's own breaker when the solve named one (one bad
        member must not poison the whole remote path), the single-sidecar
        remote breaker otherwise, and the shape class's pack breaker for
        the in-process device path (local SDC has no address to blame)."""
        from karpenter_tpu.solver import integrity as integ

        remote = self._remote
        if address and remote is not None and hasattr(remote, "quarantine"):
            # pool member: trips, records, and fires the event hook
            remote.quarantine(address, reason, detail)
            return
        if address and self.service_address:
            self._remote_breaker.trip()
            metrics.SOLVER_BREAKER_OPEN.labels(
                address=self.service_address
            ).set(1)
            metrics.SOLVER_BREAKER_TRIPS.labels(
                address=self.service_address
            ).inc()
        elif batch is not None:
            self._pack_breakers.get(
                "pack:" + "x".join(map(str, self._route_key(batch)))
            ).trip()
        integ.record_quarantine(address, reason, detail)
        self._integrity_event(reason, address, detail)

    def _maybe_canary(self, batch: enc.EncodedBatch, result, prof) -> None:
        """Start the canary cross-check for a fraction of device/pool
        solves: re-solve the SAME encoded batch on the in-process native
        packer OFF the hot path (daemon thread, at most one in flight —
        the shadow-probe discipline) and compare. Brownout-aware: while
        the router's probes are paused (ladder rung >= 1), the canary —
        pure verification spend — pauses with them."""
        if self.canary_rate <= 0 or prof.get("packer_backend") != "device":
            return
        if self.router.probes_paused():
            return
        from karpenter_tpu.solver import native

        if not native.native_available():
            return
        address = str(prof.get("solver_address") or "")
        with self._canary_lock:
            if self._canary_rng.random() >= self.canary_rate:
                return
            if self._canary_thread is not None and self._canary_thread.is_alive():
                return  # previous canary still comparing; sample the next draw
            t = threading.Thread(
                target=self._canary_check, args=(batch, result, address),
                name="karpenter-integrity-canary", daemon=True,
            )
            self._canary_thread = t
            # started under the lock, like the shadow probe: is_alive() is
            # False for an assigned-but-unstarted thread
            t.start()

    def _canary_check(self, batch: enc.EncodedBatch, result, address: str) -> None:
        """The canary body (synchronous — tests call it directly): native
        re-solve at the SAME node-table size, exact compare, quarantine the
        serving member on disagreement."""
        from karpenter_tpu.solver import integrity as integ
        from karpenter_tpu.solver import native

        try:
            n_max = int(np.asarray(result[1]).shape[0])  # node_sig is [n_max]
            reference = native.pack_native(*batch.pack_args(), n_max=n_max)
            diff = integ.compare_results(result, reference, n_pods=batch.n_pods)
        except Exception:
            # a canary that cannot run proves nothing either way — it must
            # never fail a healthy solve
            logger.debug("integrity canary re-solve failed", exc_info=True)
            return
        integ.record_canary(address, mismatch=diff is not None)
        if diff is None:
            return
        logger.error(
            "integrity canary mismatch (%s) for pack served by %s; "
            "quarantining", diff, address or "in-process",
        )
        self._quarantine_source(address, "canary", diff, batch=batch)

    def _pack_once_begin(
        self, args, p: int, n_max: int, prof: dict, record: bool = True
    ):
        """Dispatch one unfused solve — sidecar RPC future when configured,
        in-process kernel otherwise — returning ``fetch()`` →
        ``(PackResult, None)``. An RPC failure discovered at fetch time
        trips the breaker and re-dispatches in-process inside the same
        fetch, preserving the v2 containment contract. ``record`` rides to
        the sidecar so probes/retries stay out of its hit-rate stats."""
        if self.service_address and self._remote_breaker.allow():
            try:
                # pack_begin serializes + opens the session (host work,
                # cheap in steady state) and dispatches the RPC future
                pending = self._remote_or_init().pack_begin(
                    *args, n_max=n_max, prof=prof, record=record
                )
            except DeadlineExceededError:
                # the round budget already expired (client-side pre-shed,
                # or the sidecar's queue check): non-retryable by
                # construction — no breaker, no local re-solve, the round
                # takes its FFD floor in _solve
                raise
            except OverloadedError as e:
                # the sidecar (or whole pool) is FULL, not broken: its real
                # breaker must stay closed — overload tripping it would add
                # half-open probe traffic and reroutes onto whatever
                # capacity remains. Local capacity is unaffected; solve here.
                logger.info(
                    "solver service %s overloaded (retry after %.2fs); "
                    "in-process kernel serves this batch",
                    self.service_address, e.retry_after,
                )
            except IntegrityError as e:
                # corruption at dispatch/open time: quarantine (trip, not
                # the windowed path) and solve in-process — never a retry
                # against transport that just lied about its bytes
                self._remote_integrity_failure(e)
            except Exception as e:
                self._remote_failure(e)
            else:
                def fetch_remote():
                    try:
                        result = pending()
                    except DeadlineExceededError:
                        raise  # shed, not failure: straight to the floor
                    except OverloadedError as e:
                        logger.info(
                            "solver service %s shed the solve (overloaded, "
                            "retry after %.2fs); in-process kernel fallback",
                            self.service_address, e.retry_after,
                        )
                        return self._pack_local_begin(args, p, n_max, prof)()
                    except IntegrityError as e:
                        # corrupt response frame or a wrong-session echo
                        # that survived the forced re-open: quarantine and
                        # re-solve in-process — the corrupt bytes never
                        # reach decode
                        self._remote_integrity_failure(e)
                        return self._pack_local_begin(args, p, n_max, prof)()
                    except Exception as e:
                        self._remote_failure(e)
                        return self._pack_local_begin(args, p, n_max, prof)()
                    self._remote_breaker.record_success()
                    # unconditional: the gauge is process-global per
                    # address, and another scheduler instance (worker
                    # hot-swap, second provisioner) may have set it
                    metrics.SOLVER_BREAKER_OPEN.labels(
                        address=self.service_address
                    ).set(0)
                    prof["packer_backend"] = "device"  # sidecar owns the chip
                    return result, None

                return fetch_remote
        return self._pack_local_begin(args, p, n_max, prof)

    def _pack_local_begin(self, args, p: int, n_max: int, prof: dict):
        """Dispatch the in-process kernel ladder; fetch is the one fused
        device→host transfer (a no-op for the native CPU result)."""
        from karpenter_tpu.solver.pallas_kernel import pack_best

        result = pack_best(*args, n_max=n_max)
        if isinstance(result.assignment, np.ndarray):
            # native CPU packer (forced, or the ladder's no-TPU branch):
            # already host arrays, and no wire was crossed
            prof["packer_backend"] = "native"
            return lambda: (result, None)
        prof["packer_backend"] = "device"
        buf = kernel.fuse_result(result)  # still on device; async

        def fetch():
            import jax

            host = jax.device_get(buf)
            return kernel.split_result(host, p, n_max, args[6].shape[1]), None

        return fetch

    def solve(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        pods: Sequence[Pod],
    ) -> List[VirtualNode]:
        if not pods:
            return []
        prof: Dict[str, float] = {}
        try:
            return self._solve(constraints, instance_types, pods, prof)
        finally:
            # every stage write (including the degrade paths') precedes
            # this; the assignment itself is atomic, so a reader copying
            # last_completed_profile never races a writer. finish() runs on
            # the calling thread, so the thread-local binds each caller to
            # its own solve's profile.
            self.last_completed_profile = prof
            self._completed_tl.profile = prof

    def completed_profile(self) -> Dict[str, float]:
        """This THREAD's most recently completed solve profile (falling
        back to the scheduler-wide latest) — what per-batch observers
        should read under concurrent solves."""
        prof = getattr(self._completed_tl, "profile", None)
        return dict(prof if prof is not None else self.last_completed_profile)

    def _publish_decision(self, ctx: Dict) -> None:
        from karpenter_tpu.obs import decisions as _dec

        if _dec.enabled():
            self._decision_tl.ctx = ctx

    def completed_decision(self) -> Dict:
        """This THREAD's most recent solve's decision context — consumed
        on read (one record per round; holding the batch longer would pin
        it). {} when nothing completed since the last read or the
        decision plane is disabled (docs/decisions.md)."""
        ctx = getattr(self._decision_tl, "ctx", None)
        self._decision_tl.ctx = None
        return ctx or {}

    def _solve(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        pods: Sequence[Pod],
        prof: Dict[str, float],
    ) -> List[VirtualNode]:
        from karpenter_tpu import obs

        tr = obs.tracer()
        # stage spans mirror the prof dict: the prof clock runs INSIDE
        # each span, so both bracket the same region and the exported
        # trace agrees with Scheduler.last_stage_profile() to within the
        # span enter/exit slivers (tests hold them to 1ms — a prof window
        # opened outside the span would let a 1-core GIL preemption land
        # between the two clocks and break that)
        # resident delta path (docs/delta-encoding.md): each stage records
        # its DELTA prof key when served from resident state and the full
        # key otherwise, so the bench's stage breakdown and host_share_ms /
        # delta_hit_rate attribution fall out of the profile directly
        resident = self._resident
        with tr.span("solve.sort"):
            t0 = time.perf_counter()
            constraints = constraints.clone()
            if resident is not None:
                pods, sts, sort_hit = resident.sort(pods)
            else:
                pods, sts = sort_pods_ffd_with_statics(pods)
                sort_hit = False
            instance_types = sorted(
                instance_types, key=lambda it: it.effective_price()
            )
            prof["sort_delta_s" if sort_hit else "sort_s"] = time.perf_counter() - t0
        # Double-buffered host pipeline (docs/solver-transport.md): the
        # solve lock covers only the HOST-side prepare stages
        # (inject/encode) and the non-blocking dispatch. The blocking
        # fused-result fetch and the decode run OFF the lock — JAX dispatch
        # (and the sidecar RPC future) is async, so while solve i is in
        # flight on the device/wire, the next batch's encode proceeds under
        # the freed lock instead of queueing behind the fetch.
        with self._solve_lock:
            # published under the lock: a concurrent warmup solve must
            # not clobber the profile observers read
            self.last_profile = prof
            # decision-plan injection: topology choices land in the plan,
            # NOT in the pods' nodeSelectors — the TPU path never mutates
            # (and never restores) pod objects. `pods` is already this
            # solve's own sorted list; passing it (not a copy) lets encode
            # reuse the plan's statics pass (plan._pods identity check).
            with tr.span("solve.inject"):
                t0 = time.perf_counter()
                topo = True
                plan_reused = False
                if resident is not None and resident.eligible(sts):
                    # topology-free batch: the injected plan is empty by
                    # construction, so the per-pod discovery sweep is skipped
                    topo = False
                    plan = resident.empty_plan(pods, sts)
                    daemon = daemon_overhead(self.cluster, constraints)
                elif resident is not None:
                    # topology batch: the injected round is a deterministic
                    # function of (sorted batch, pre-inject constraints
                    # content, cluster state) — when none moved, reuse the
                    # cached post-inject constraints + plan + daemon and
                    # skip the per-pod discovery sweep entirely. The key is
                    # built BEFORE inject mutates the constraints clone.
                    pkey = resident.plan_key(constraints, self.cluster.version())
                    hit = resident.plan_reuse(pkey, sts)
                    if hit is not None:
                        constraints, plan, daemon = hit
                        plan_reused = True
                    else:
                        plan = self.topology.inject_plan(constraints, pods, sts=sts)
                        daemon = daemon_overhead(self.cluster, constraints)
                        resident.remember_plan(pkey, sts, constraints, plan, daemon)
                else:
                    plan = self.topology.inject_plan(constraints, pods, sts=sts)
                    daemon = daemon_overhead(self.cluster, constraints)
                prof[
                    "inject_delta_s" if (not topo or plan_reused) else "inject_s"
                ] = time.perf_counter() - t0
            with tr.span("solve.encode") as enc_sp:
                t0 = time.perf_counter()
                enc_kind = "full"
                try:
                    if resident is not None:
                        batch, enc_kind = self._resident_encode(
                            constraints, instance_types, pods, sts, daemon,
                            plan, topo=topo, plan_reused=plan_reused,
                        )
                    else:
                        batch = self._encode_retry(constraints, instance_types, pods, daemon, plan)
                except SignatureOverflow as e:
                    logger.warning("falling back to FFD: %s", e)
                    enc_sp.set_attribute("signature_overflow", True)
                    return self._ffd_degrade(constraints, instance_types, pods, daemon, plan)
                if enc_kind != "full":
                    enc_sp.set_attribute("delta", enc_kind)
                prof["encode_delta_s" if enc_kind != "full" else "encode_s"] = (
                    time.perf_counter() - t0
                )
            # the shape class's pack breaker: while open, the batch routes
            # to FFD immediately — pods still schedule, and nobody re-pays
            # the accelerated path's failure latency every solve. A closed
            # (or half-open-probing) breaker sees the pack's outcome.
            breaker = self._pack_breakers.get(
                "pack:" + "x".join(map(str, self._route_key(batch)))
            )
            if not breaker.allow():
                metrics.SOLVER_DEGRADED.labels(reason="breaker_open", address="").inc()
                prof["packer_backend"] = "ffd-degraded"
                return self._ffd_degrade(constraints, instance_types, pods, daemon, plan)
            try:
                with tr.span("solve.pack_begin"):
                    t0 = time.perf_counter()
                    pending = self._pack(batch)
                    begin_s = time.perf_counter() - t0
            except (OverloadedError, DeadlineExceededError) as e:
                # a shed is NOT a shape failure: the pack breaker stays
                # closed (overload tripping it would pin the shape class to
                # FFD for the full open window after load recedes) and the
                # batch takes the floor once, non-retryably
                reason = (
                    "deadline" if isinstance(e, DeadlineExceededError)
                    else "overload"
                )
                metrics.SOLVER_DEGRADED.labels(reason=reason, address="").inc()
                logger.warning(
                    "accelerated pack shed (%s); FFD floor serves this batch",
                    e,
                )
                prof["packer_backend"] = "ffd-degraded"
                return self._ffd_degrade(constraints, instance_types, pods, daemon, plan)
            except Exception:
                breaker.record_failure()
                metrics.SOLVER_DEGRADED.labels(reason="pack_failure", address="").inc()
                logger.exception(
                    "accelerated pack failed; FFD fallback serves this batch"
                )
                prof["packer_backend"] = "ffd-degraded"
                return self._ffd_degrade(constraints, instance_types, pods, daemon, plan)
        # lock released: solve i is in flight; only its fetch blocks here
        try:
            with tr.span("solve.pack_fetch") as fetch_sp:
                t0 = time.perf_counter()
                result, typemask = pending()
                fetch_wait_s = time.perf_counter() - t0
                fetch_sp.set_attribute("backend", prof.get("packer_backend"))
        except (OverloadedError, DeadlineExceededError) as e:
            # shed mid-flight (sidecar admission or the propagated round
            # deadline): no breaker state moves — overload is backpressure,
            # and retrying an expired deadline is useless by definition.
            # One non-retryable drop to the FFD floor, never a retry storm.
            reason = (
                "deadline" if isinstance(e, DeadlineExceededError)
                else "overload"
            )
            metrics.SOLVER_DEGRADED.labels(reason=reason, address="").inc()
            logger.warning(
                "accelerated pack shed (%s); FFD floor serves this batch", e,
            )
            prof["packer_backend"] = "ffd-degraded"
            with self._solve_lock:
                return self._ffd_degrade(constraints, instance_types, pods, daemon, plan)
        except Exception:
            breaker.record_failure()
            metrics.SOLVER_DEGRADED.labels(reason="pack_failure", address="").inc()
            logger.exception(
                "accelerated pack failed; FFD fallback serves this batch"
            )
            prof["packer_backend"] = "ffd-degraded"
            # the FFD floor shares per-scheduler state (the fallback
            # scheduler, pod selector snapshots): take the lock back
            with self._solve_lock:
                return self._ffd_degrade(constraints, instance_types, pods, daemon, plan)
        # host-side NaN/bounds screen over the RAW result, before decode
        # can launder non-finite totals into a plausible-looking plan: a
        # checksummed frame proves the bytes crossed intact, not that an
        # SDC-afflicted device computed them correctly (docs/integrity.md).
        # Runs on EVERY accelerated solve — µs of numpy against a >1ms
        # decode — so detection never depends on the sampled canary.
        from karpenter_tpu.solver import integrity as integ

        screen = integ.screen_result(result, n_pods=batch.n_pods)
        if screen:
            address = str(prof.get("solver_address") or "")
            integ.record_screen_failure(address)
            self._quarantine_source(address, "screen", screen, batch=batch)
            # provenance label: one vocabulary with the integrity counters
            # ("local" for the in-process path), so a per-address join
            # across the two families matches
            metrics.SOLVER_DEGRADED.labels(
                reason="integrity_screen", address=address or "local"
            ).inc()
            logger.error(
                "accelerated pack failed the integrity screen (%s); source "
                "quarantined, FFD fallback serves this batch", screen,
            )
            prof["packer_backend"] = "ffd-degraded"
            with self._solve_lock:
                return self._ffd_degrade(constraints, instance_types, pods, daemon, plan)
        breaker.record_success()
        # wire serialization is attributed separately (wire_ser_s /
        # wire_deser_s, set by RemoteSolver) so pack_fetch_s is the
        # in-flight dispatch+fetch wait alone; both windows ran inside
        # their spans, so trace and profile agree by construction
        prof["pack_fetch_s"] = max(
            begin_s + fetch_wait_s
            - prof.get("wire_ser_s", 0.0)
            - prof.get("wire_deser_s", 0.0),
            0.0,
        )
        with tr.span("solve.decode"):
            t0 = time.perf_counter()
            nodes = self._decode(batch, result, typemask, constraints, instance_types)
            prof[
                "decode_delta_s"
                if getattr(self._dec_tl, "hit", False) else "decode_s"
            ] = time.perf_counter() - t0
        # host-side sanity check BEFORE the plan reaches the launch/bind
        # path: a bad device/remote solve (bit flips on the wire, a kernel
        # regression, a corrupted session) must never produce an invalid
        # bind. Violations quarantine BY PROVENANCE — the serving pool
        # member's breaker when the pack names one (one bad member must not
        # poison the whole remote path), the shape class outright for the
        # in-process path — and this is a correctness failure, not an
        # availability blip, so the trip is immediate, never the windowed
        # failure rate.
        # a decode-residency hit is bit-identical to a previously decoded
        # plan; when THAT plan passed this guard (the memo is only armed on
        # a pass, and is keyed to the decode memo generation), the verdict
        # is a pure function of inputs proved unchanged — skip the re-check
        vmemo = self._validate_memo
        if (
            getattr(self._dec_tl, "hit", False)
            and vmemo is not None
            and vmemo[0] is self._dec_memo
            and vmemo[1] is pods
            and vmemo[2] == daemon
        ):
            violation = None
        else:
            violation = self._validate_pack(nodes, pods, daemon)
            if violation is None:
                self._validate_memo = (self._dec_memo, pods, dict(daemon))
        if violation:
            address = str(prof.get("solver_address") or "")
            self._quarantine_source(address, "invalid_pack", violation, batch=batch)
            metrics.SOLVER_DEGRADED.labels(
                reason="invalid_pack", address=address or "local"
            ).inc()
            logger.error(
                "accelerated pack produced an invalid plan (%s); source "
                "quarantined, FFD fallback serves this batch", violation,
            )
            prof["packer_backend"] = "ffd-degraded"
            with self._solve_lock:
                return self._ffd_degrade(constraints, instance_types, pods, daemon, plan)
        # canary cross-check (docs/integrity.md): a sampled fraction of
        # device/pool solves is re-solved on the native packer off the hot
        # path and compared — the layer that catches a plausible-shaped,
        # screen-clean pack computed from corrupt inputs
        self._maybe_canary(batch, result, prof)
        # decision context for the audit log (obs/decisions.py): the
        # encoded batch + served assignment + provenance. Attribution is a
        # pure function of these, so the verdicts are identical whichever
        # route (native/device/pool/streamed/coalesced) produced the
        # bit-exact assignment. The assignment slice is copied — the
        # result buffers must not stay pinned through the record's life.
        self._publish_decision({
            "batch": batch,
            "assignment": np.asarray(result[0])[: batch.n_pods].copy(),
            "n_max": int(np.asarray(result[1]).shape[0]),
            "route": prof.get("packer_backend"),
            "transport": prof.get("solver_transport"),
            "address": prof.get("solver_address"),
            "session_key": prof.get("session_key"),
        })
        return nodes

    @staticmethod
    def _validate_pack(nodes, pods, daemon) -> Optional[str]:
        """Host-verified invariants of a decoded pack result: every pod
        placed at most once, every placed pod from THIS batch, and every
        node's recomputed totals (pod requests + daemon overhead) fit at
        least one of its surviving instance types. Returns a description of
        the first violation, or None. Pure host numpy/python — safe to run
        on every solve (µs against a >1ms decode)."""
        batch_keys = {p.key for p in pods}
        seen: set = set()
        for i, node in enumerate(nodes):
            for pod in node.pods:
                if pod.key in seen:
                    return f"pod {pod.key} assigned to more than one node"
                if pod.key not in batch_keys:
                    return f"pod {pod.key} not part of this batch"
                seen.add(pod.key)
            if not node.instance_type_options:
                return f"node {i} has no surviving instance type"
            totals = res.merge(
                daemon, *[res.requests_for_pods(p) for p in node.pods]
            )
            if not any(
                res.fits(totals, it.resources)
                for it in node.instance_type_options
            ):
                return (
                    f"node {i} capacity exceeded: {res.to_string(totals)} "
                    "fits none of its surviving instance types"
                )
        return None

    def _ffd_degrade(self, constraints, instance_types, pods, daemon, plan) -> List[VirtualNode]:
        """The degradation ladder's floor: materialize the topology plan
        into the pods' selectors (restored afterwards — the TPU path's
        never-mutate contract) and serve the batch with the host FFD."""
        # a degraded round still lands in the decision audit log with its
        # route; tensor-level attribution needs the accelerated result
        # (docs/decisions.md documents the asymmetry)
        self._publish_decision({"route": "ffd-degraded"})
        saved = snapshot_selectors(pods)
        try:
            plan.materialize(list(pods))
            return self._ffd_fallback.solve_injected(
                constraints, instance_types, pods, daemon
            )
        finally:
            restore_selectors(pods, saved)

    def _resident_encode(
        self, constraints, instance_types, pods, sts, daemon, plan,
        topo=False, plan_reused=False,
    ):
        """The resident path with the same overflow-retry contract as
        ``_encode_retry``: a cached table accumulates signatures across
        batches, so an overflow may be an accumulation artifact — drop the
        cache AND the resident state (its stable vocab belongs to the
        dropped table) and retry from cold."""
        try:
            return self._resident.encode(
                constraints, instance_types, pods, sts, daemon, plan,
                topo=topo, plan_reused=plan_reused,
            )
        except SignatureOverflow:
            self._encode_cache.clear()
            self._resident.reset()
            return self._resident.encode(
                constraints, instance_types, pods, sts, daemon, plan,
                topo=topo, plan_reused=plan_reused,
            )

    def _encode_retry(self, constraints, instance_types, pods, daemon, plan) -> enc.EncodedBatch:
        """Encode with the reusable cache; a cached table accumulates
        signatures across batches, so an overflow may be an accumulation
        artifact — drop the cache and retry fresh before declaring the
        batch itself too diverse."""
        try:
            return enc.encode(
                constraints, instance_types, pods, daemon, cache=self._encode_cache,
                plan=plan,
            )
        except SignatureOverflow:
            self._encode_cache.clear()
            return enc.encode(
                constraints, instance_types, pods, daemon, cache=self._encode_cache,
                plan=plan,
            )

    def _decode(
        self,
        batch: enc.EncodedBatch,
        result,
        typemask,  # [N, T] bool from the fused dispatch, or None
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
    ) -> List[VirtualNode]:
        # _pack already fused the device→host transfer; these are host arrays
        assignment, node_sig, node_host, node_req, n_nodes_arr = result
        assignment = assignment[: batch.n_pods]
        n_nodes = int(np.asarray(n_nodes_arr).reshape(-1)[0])

        unschedulable = int((assignment < 0).sum())
        if unschedulable:
            logger.error("Failed to schedule %d pods", unschedulable)

        # decode residency: a bit-identical result for the SAME resident
        # batch under compatible constraints rebuilds the nodes from the
        # previous decode's derived rows (docs/delta-encoding.md § decode).
        # Gated with the rest of the resident machinery — the --no-solver-
        # delta twin must measure the genuine full path.
        self._dec_tl.hit = False
        memo_on = self._resident is not None
        if memo_on:
            nodes = self._decode_from_memo(
                batch, assignment, node_sig, node_host, node_req, n_nodes,
                typemask, constraints, instance_types,
            )
            if nodes is not None:
                self._dec_tl.hit = True
                return nodes

        # group pods per node (order-preserving, like FFD append order);
        # indices ≥ n_nodes would be out of the kernel contract — skip them
        # like the old range(n_nodes) loop did rather than crash decode.
        # Vectorized: stable argsort by node index replaces the per-pod
        # dict/setdefault loop (a decode hot spot at 10k pods).
        a = np.asarray(assignment)
        valid_idx = np.flatnonzero((a >= 0) & (a < n_nodes))
        order = valid_idx[np.argsort(a[valid_idx], kind="stable")]
        groups, starts = np.unique(a[order], return_index=True)
        bounds = np.append(starts, len(order)).tolist()
        # plain list comprehension over PYTHON ints: measured 10x faster
        # than object-array slicing, and indexing a list with np.int64
        # scalars pays a boxing cost per element
        order_l = order.tolist()
        batch_pods = batch.pods
        pods_by_node: Dict[int, List[Pod]] = {
            int(g): [batch_pods[i] for i in order_l[bounds[k]:bounds[k + 1]]]
            for k, g in enumerate(groups)
        }

        axis_names = batch.axis_names
        # axis_names is identity-stable across steady-state solves (trim
        # memo), so the per-axis scale gather memoizes on it; the value
        # holds the list so the id cannot be recycled under the memo
        hit = self._scales_memo.get(id(axis_names))
        if hit is not None and hit[0] is axis_names:
            scales = hit[1]
        else:
            scales = np.array(
                [res.AXIS_SCALES.get(nm, res._DEFAULT_SCALE) for nm in axis_names]
            )
            if len(self._scales_memo) >= 8:
                self._scales_memo.clear()
            self._scales_memo[id(axis_names)] = (axis_names, scales)
        live = sorted(pods_by_node)
        # surviving types for ALL nodes: the fused dispatch computed the
        # [N, T] mask on device; otherwise one batched host comparison
        # (signature-compatible ∧ fit the node total) — the per-node [T, R]
        # scan was the decode hot spot at 1k+ nodes
        if live:
            live_idx = np.asarray(live, np.int64)
            if typemask is not None:
                ok_all = typemask[live_idx]
            else:
                totals = node_req[live_idx]  # [L, R]
                fit_all = np.all(
                    batch.usable[None, :, :] >= totals[:, None, :], axis=-1
                )  # [L, T]
                mask_arr = batch.type_mask_matrix()  # [S_local, T]
                mask_all = mask_arr[np.asarray(node_sig)[live_idx]]  # [L, T]
                ok_all = fit_all & mask_all
            types_arr = np.array(instance_types, dtype=object)
            # most nodes share identical surviving-type masks (few
            # signatures × similar totals): build each distinct list once
            # and share the object — safe under the codebase-wide
            # replace-never-mutate convention (VirtualNode.add REPLACES
            # instance_type_options). Materializing 431×380 per-node lists
            # was the decode hot spot.
            _, uniq_row, row_of = np.unique(
                np.packbits(ok_all, axis=1), axis=0,
                return_index=True, return_inverse=True,
            )
            uniq_lists = [list(types_arr[ok_all[int(r)]]) for r in uniq_row]
            row_of = row_of.reshape(-1)
        nodes: List[VirtualNode] = []
        if not live:
            return nodes
        # bulk host conversion for the per-node readout: one vectorized
        # division + three .tolist() calls replace per-element numpy
        # scalar boxing (float(total[i]) / scales[i] boxed a scalar per
        # axis per node — THE remaining decode hot spot at 1k+ nodes).
        # Same IEEE float64 divide, so the requests dicts are bit-exact.
        totals_live = np.asarray(node_req)[live_idx]  # [L, R]
        totals_l = totals_live.tolist()
        scaled_l = (totals_live / scales[None, :]).tolist()
        sig_l = np.asarray(node_sig)[live_idx].tolist()
        host_l = np.asarray(node_host)[live_idx].tolist()
        row_of_l = row_of.tolist()
        # hostname requirement fast path: all nodes of one signature share
        # (reqs tuple, sets minus hostname); per node only the hostname
        # ValueSet intersection and one tuple splice differ —
        # assignment-identical to sig.requirements.add(hostname In [h])
        sig_host_cache: Dict[int, tuple] = {}
        memo_rows = []
        for row, n in enumerate(live):
            sig = batch.signatures[sig_l[row]]
            total = totals_l[row]
            scaled = scaled_l[row]
            surviving = uniq_lists[row_of_l[row]]
            node_constraints = constraints.clone()
            reqs = sig.requirements
            h = host_l[row]
            if h >= 0:
                reqs = _with_hostname(
                    reqs, batch.hostnames[h], sig_host_cache
                )
            node_constraints.requirements = reqs
            requests = {
                name: scaled[i]
                for i, name in enumerate(axis_names)
                if total[i]
            }
            pods_list = pods_by_node[n]
            if memo_on:
                # memo holds its OWN copies of the mutable per-node state (a
                # consumer appending to node.pods must not poison the cache);
                # the requirements object and the surviving list are shared
                # under the replace-never-mutate convention, exactly as
                # uniq_lists already shares them across this round's nodes
                memo_rows.append((reqs, dict(requests), surviving, list(pods_list)))
            nodes.append(
                VirtualNode(
                    constraints=node_constraints,
                    instance_type_options=surviving,
                    pods=pods_list,
                    requests=requests,
                )
            )
        if memo_on:
            # one atomic snapshot (decode runs off the solve lock); the
            # copies decouple the memo from result buffers the device path
            # may reuse
            self._dec_memo = (
                batch,
                list(instance_types),
                constraints,
                np.asarray(assignment).copy(),
                np.asarray(node_sig)[:n_nodes].copy(),
                np.asarray(node_host)[:n_nodes].copy(),
                np.asarray(node_req)[:n_nodes].copy(),
                n_nodes,
                None if typemask is None else np.asarray(typemask).copy(),
                memo_rows,
            )
        return nodes

    def _decode_from_memo(
        self, batch, assignment, node_sig, node_host, node_req, n_nodes,
        typemask, constraints, instance_types,
    ) -> Optional[List[VirtualNode]]:
        """The decode-side reuse rung: None unless every input the decoded
        nodes are a function of matches the memo — the resident batch by
        identity, the raw result and typemask bit-for-bit, the catalog by
        element identity, and the constraints by content (the requirements
        object itself rides the resident plan cache, so identity holds in
        steady state). On a hit the nodes are rebuilt from the memoized
        per-node rows: fresh clones/copies for everything a consumer may
        mutate, shared objects for everything replace-never-mutate."""
        memo = self._dec_memo
        if memo is None or memo[0] is not batch:
            return None
        (_, mits, mcon, mass, msig, mhost, mreq, mn, mmask, rows) = memo
        if n_nodes != mn:
            return None
        if len(instance_types) != len(mits) or any(
            a is not b for a, b in zip(instance_types, mits)
        ):
            return None
        if not (
            constraints.requirements is mcon.requirements
            and constraints.kubelet_configuration is mcon.kubelet_configuration
            and constraints.provider is mcon.provider
            and constraints.labels == mcon.labels
            and constraints.taints == mcon.taints
        ):
            return None
        if (typemask is None) != (mmask is None):
            return None
        if not (
            np.array_equal(np.asarray(assignment), mass)
            and np.array_equal(np.asarray(node_sig)[:n_nodes], msig)
            and np.array_equal(np.asarray(node_host)[:n_nodes], mhost)
            and np.array_equal(np.asarray(node_req)[:n_nodes], mreq)
            and (mmask is None or np.array_equal(np.asarray(typemask), mmask))
        ):
            return None
        from karpenter_tpu import metrics

        metrics.SOLVER_DELTA_APPLIED.labels(path="decode").inc()
        nodes: List[VirtualNode] = []
        for reqs, requests, surviving, pods_list in rows:
            node_constraints = constraints.clone()
            node_constraints.requirements = reqs
            nodes.append(
                VirtualNode(
                    constraints=node_constraints,
                    instance_type_options=surviving,
                    pods=list(pods_list),
                    requests=dict(requests),
                )
            )
        return nodes
