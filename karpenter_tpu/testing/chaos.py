"""Chaos-injection harness for the cloud control-plane doubles.

The one-shot ``SimCloudAPI.inject_error`` can stage exactly one failure per
method — enough for unit tests, useless for proving the resilience layer
(retries, breakers, budgets) holds up under a *sustained* failure regime.
``ChaosPolicy`` + ``chaos_wrap`` turn any control-plane double
(``SimCloudAPI``, ``SimGkeAPI`` — and, by wrapping the double handed to
``CloudAPIServer``/``GkeAPIServer``, the HTTP wire too: injected errors
cross as 5xx/429/409) into a statistically misbehaving dependency:

- a per-call **error probability** (optionally per method), alternating
  injected control-plane failures with throttles;
- an **injected latency** distribution calibrated by its p95 (exponential,
  tail-capped so a single sample can't stall a test past its budget);
- **ICE storms**: windows during which every ``create_fleet`` override
  answers insufficient-capacity (the typed all-ICE error, carrying the
  overrides, exactly like a real exhausted region);
- **blackouts**: windows during which every wrapped method fails;
- a **seeded RNG** so a chaos run is reproducible bit-for-bit, and
  per-method injection counters so tests can assert chaos actually fired.

Programming/fault-injection helpers (``inject_error``,
``send_disruption_notice``, ``set_stockout`` …) and attribute access pass
through unwrapped: chaos applies to the control-plane *calls*, not to the
test's ability to program the double.
"""

from __future__ import annotations

import math
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# The control-plane surfaces chaos applies to. Anything else (programming
# helpers, attributes, the disruption injectors) passes through untouched.
CHAOS_METHODS = frozenset({
    # SimCloudAPI
    "describe_instance_types", "describe_subnets", "describe_security_groups",
    "ensure_launch_template", "delete_launch_template", "create_fleet",
    "describe_instances", "terminate_instances", "poll_disruptions",
    # SimGkeAPI
    "create_node_pool", "delete_node_pool", "delete_instance",
    # solver sidecar (service.SolverService) — a chaos-wrapped service
    # handed to service.serve() simulates a slow/failing device solve, the
    # pipeline-smoke test's way of proving encode(i+1) hides under solve(i).
    # solve_stream_group is the STREAMED dispatch path (solver/stream.py):
    # without it a latency-floor policy would slow unary solves while
    # streamed ones sailed through, and the stream-storm leg would measure
    # an unthrottled device
    "solve_bytes", "open_session_bytes", "solve_stream_group",
})

# The byte-level corruption surface (docs/integrity.md): silent-data-
# corruption chaos applies to the solver wire only — the cloud doubles
# speak python objects, where "corruption" has no byte representation.
CORRUPT_METHODS = frozenset({"solve_bytes", "open_session_bytes"})

# The seeded corruption modes the corruption-storm leg must prove are
# all detected (bench.py --corruption-storm):
# - bit_flip: one random bit of the request or response frame — what the
#   checksum layer exists for;
# - truncate: the frame cut short mid-array — loud at the codec/checksum;
# - stale_session: the response's echoed session key swapped and the
#   checksum RECOMPUTED — a checksum-valid wrong-catalog response only the
#   session-generation guard can reject;
# - nan_inject: the f32 NaN bit pattern written over the first result word
#   and the checksum RECOMPUTED — device SDC's shape: a perfectly framed,
#   checksum-valid pack computed wrong, caught by the host-side screen;
# - stale_delta: a delta-framed request's epoch words garbled and the
#   checksum RECOMPUTED — a missed/misordered delta's shape
#   (docs/delta-encoding.md): perfectly framed, checksum-valid, naming pod
#   bases that do not exist or cannot produce the claimed state. Only the
#   sidecar's digest-recompute epoch guard can refuse it (NEEDS_DELTA_BASE
#   → the client re-establishes) — a stale-tensor solve must never bind.
CORRUPTION_MODES = ("bit_flip", "truncate", "stale_session", "nan_inject",
                    "stale_delta")

# exponential p95 = mean * ln(20); invert to calibrate the mean from a p95
_LN20 = 2.9957322735539909


@dataclass(frozen=True)
class ChaosWindow:
    """Half-open [start, end) window in seconds since the policy armed."""

    start: float
    end: float

    def contains(self, t: float) -> bool:
        return self.start <= t < self.end


@dataclass
class ChaosPolicy:
    """What misbehavior to inject, and how much."""

    error_rate: float = 0.0          # per-call failure probability
    latency_p95: float = 0.0         # seconds; 0 = no injected latency
    # deterministic per-call latency floor (seconds), added before any
    # random draw: overlap tests need a KNOWN in-flight time to hide host
    # work under, which an exponential draw can't guarantee
    latency_floor: float = 0.0
    throttle_fraction: float = 0.25  # this share of injected errors throttle (429)
    ice_storms: Sequence[ChaosWindow] = ()
    blackouts: Sequence[ChaosWindow] = ()
    seed: int = 0
    # restrict chaos to these methods (None = every CHAOS_METHODS member)
    methods: Optional[frozenset] = None
    # cap one latency sample so a tail draw can't stall a test (× p95)
    latency_cap_factor: float = 4.0
    # silent-data-corruption injection (CORRUPT_METHODS only): per-call
    # probability that the frame is corrupted, and the mode pool drawn from
    corrupt_rate: float = 0.0
    corruption_modes: Sequence[str] = CORRUPTION_MODES

    def applies_to(self, method: str) -> bool:
        if method not in CHAOS_METHODS:
            return False
        return self.methods is None or method in self.methods

    def corrupt_applies_to(self, method: str) -> bool:
        if method not in CORRUPT_METHODS:
            return False
        return self.methods is None or method in self.methods


class ChaosProxy:
    """Wraps a control-plane double with a :class:`ChaosPolicy`.

    Duck-typed: any object whose public methods appear in ``CHAOS_METHODS``
    gets those calls intercepted; everything else proxies through, so the
    wrapped double still serves ``CloudAPIServer``/``GkeAPIServer`` and the
    tests' programming surface unchanged.
    """

    def __init__(self, delegate, policy: ChaosPolicy, clock=time.monotonic):
        import random

        self._delegate = delegate
        self.policy = policy
        self._clock = clock
        self._t0 = clock()
        # one lock around the RNG: chaos fires from server handler threads
        # and controller threads at once, and a seeded run must stay
        # deterministic in its draw SEQUENCE (interleaving may still vary)
        self._rng = random.Random(policy.seed)
        self._rng_mu = threading.Lock()
        self.injected: Dict[str, int] = {}   # method -> injected failures
        self.delayed: Dict[str, int] = {}    # method -> latency injections
        self.corrupted: Dict[str, int] = {}  # corruption mode -> injections
        self.calls: Dict[str, int] = {}      # method -> chaos-surface calls
        self._count_mu = threading.Lock()

    # -- bookkeeping --------------------------------------------------------
    def _note(self, table: Dict[str, int], method: str) -> None:
        with self._count_mu:
            table[method] = table.get(method, 0) + 1

    def injected_total(self) -> int:
        with self._count_mu:
            return sum(self.injected.values())

    def corrupted_total(self) -> int:
        with self._count_mu:
            return sum(self.corrupted.values())

    def calls_total(self, method: str = "solve_bytes") -> int:
        with self._count_mu:
            return self.calls.get(method, 0)

    def elapsed(self) -> float:
        return self._clock() - self._t0

    # -- the wrap -----------------------------------------------------------
    def __getattr__(self, name: str):
        attr = getattr(self._delegate, name)
        corruptible = callable(attr) and name in CORRUPT_METHODS
        if not callable(attr) or (
            not self.policy.applies_to(name) and not corruptible
        ):
            return attr

        def chaotic(*args, **kwargs):
            if name in CORRUPT_METHODS:
                self._note(self.calls, name)
            if self.policy.applies_to(name):
                self._maybe_disturb(name, args)
            mode = seed = None
            request_side = False
            if (
                self.policy.corrupt_rate > 0
                and self.policy.corrupt_applies_to(name)
            ):
                with self._rng_mu:
                    if self._rng.random() < self.policy.corrupt_rate:
                        mode = self._rng.choice(
                            list(self.policy.corruption_modes)
                        )
                        # bit flips hit either direction; stale_delta is a
                        # REQUEST-side mode (the delta header rides the
                        # Pack request); the other structured modes model
                        # a corrupt RESPONSE (stale replay and SDC both
                        # happen server/device-side)
                        request_side = mode == "stale_delta" or (
                            mode == "bit_flip" and self._rng.random() < 0.5
                        )
                        seed = self._rng.randrange(2**31)
            if mode is not None and request_side:
                self._note(self.corrupted, mode)
                return attr(_corrupt_frame(args[0], mode, seed), *args[1:], **kwargs)
            out = attr(*args, **kwargs)
            if mode is not None:
                self._note(self.corrupted, mode)
                out = _corrupt_frame(out, mode, seed)
            return out

        return chaotic

    def _maybe_disturb(self, method: str, args: tuple) -> None:
        from karpenter_tpu.cloudprovider.httpapi import ThrottlingError
        from karpenter_tpu.cloudprovider.simulated import (
            CloudAPIError,
            InsufficientCapacityError,
        )

        now = self.elapsed()
        policy = self.policy
        with self._rng_mu:
            roll = self._rng.random()
            throttle = self._rng.random() < policy.throttle_fraction
            delay = 0.0
            if policy.latency_p95 > 0.0:
                delay = min(
                    self._rng.expovariate(_LN20 / policy.latency_p95),
                    policy.latency_p95 * policy.latency_cap_factor,
                )
        delay += policy.latency_floor
        if delay > 0.0:
            self._note(self.delayed, method)
            time.sleep(delay)
        if any(w.contains(now) for w in policy.blackouts):
            self._note(self.injected, method)
            raise CloudAPIError(f"chaos blackout: {method} unavailable")
        if method == "create_fleet" and any(
            w.contains(now) for w in policy.ice_storms
        ):
            self._note(self.injected, method)
            overrides = [
                (args[0], it, zone) for (_lt, it, zone) in (args[1] if len(args) > 1 else [])
            ]
            raise InsufficientCapacityError(
                "chaos ICE storm: all pools exhausted", overrides=overrides
            )
        if roll < policy.error_rate:
            self._note(self.injected, method)
            if throttle:
                raise ThrottlingError(retry_after=0.01)
            raise CloudAPIError(f"chaos: injected {method} failure")


def chaos_wrap(api, policy: ChaosPolicy, clock=time.monotonic) -> ChaosProxy:
    """Wrap a ``SimCloudAPI``/``SimGkeAPI`` (or anything speaking their
    method protocols) in a chaos proxy. The result is a drop-in wherever
    the bare double went — ``SimulatedCloudProvider(api=...)``,
    ``GkeCloudProvider(api=...)``, ``CloudAPIServer(api=...)``."""
    return ChaosProxy(api, policy, clock=clock)


# ---------------------------------------------------------------------------
# silent-data-corruption injectors (docs/integrity.md): each mode is a pure
# seeded function over one wire frame, so a storm replays bit-for-bit
# ---------------------------------------------------------------------------


def _corrupt_frame(frame: bytes, mode: str, seed: int) -> bytes:
    if not isinstance(frame, (bytes, bytearray)):
        return frame  # not a wire frame (already-raised paths)
    if mode == "truncate":
        return _truncate(bytes(frame), seed)
    if mode == "stale_session":
        return _stale_session(bytes(frame), seed)
    if mode == "nan_inject":
        return _nan_inject(bytes(frame), seed)
    if mode == "stale_delta":
        return _stale_delta(bytes(frame), seed)
    return _bit_flip(bytes(frame), seed)


def _bit_flip(frame: bytes, seed: int) -> bytes:
    """Flip one random bit past the magic/version words (those fail loudly
    on their own and prove nothing about the checksum layer)."""
    import random

    rng = random.Random(seed)
    if len(frame) <= 8:
        return frame
    out = bytearray(frame)
    out[rng.randrange(8, len(out))] ^= 1 << rng.randrange(8)
    return bytes(out)


def _truncate(frame: bytes, seed: int) -> bytes:
    import random

    rng = random.Random(seed)
    if len(frame) <= 5:
        return frame[:1]
    return frame[:rng.randrange(4, len(frame))]


def _stale_session(frame: bytes, seed: int) -> bytes:
    """Swap the echoed session key for a random one and RECOMPUTE the
    checksum: a wrong-catalog-generation response that sails through every
    byte-level check — only the client's session-generation guard can
    reject it. Frames without an echo degrade to a bit flip."""
    import random

    import numpy as np

    from karpenter_tpu.solver import service

    rng = random.Random(seed)
    try:
        arrays = service.unpack_arrays(frame)
    except Exception:
        return _bit_flip(frame, seed)
    had_checksum = bool(arrays) and service.is_checksum_array(arrays[-1])
    arrays = [a for a in arrays if not service.is_checksum_array(a)]
    swapped = False
    for i, a in enumerate(arrays):
        a = np.asarray(a)
        if i > 0 and a.dtype == np.int32 and a.ndim == 1 and a.size == 4:
            arrays[i] = np.frombuffer(
                bytes(rng.randrange(256) for _ in range(16)), np.int32
            )
            swapped = True
            break
    if not swapped:
        return _bit_flip(frame, seed)
    out = service.pack_arrays(arrays)
    return service.append_checksum(out) if had_checksum else out


def _stale_delta(frame: bytes, seed: int) -> bytes:
    """Garble the epoch words of a delta-framed request's i32[10] header
    and RECOMPUTE the checksum — the shape a missed or misordered delta
    takes on the wire: perfectly framed, checksum-valid, but naming a base
    epoch the sidecar does not hold (or a new epoch the patched content
    cannot hash to). Only the sidecar's digest-recompute epoch guard
    (docs/delta-encoding.md) can refuse it. Frames without a delta header
    degrade to a bit flip."""
    import random

    import numpy as np

    from karpenter_tpu.solver import service

    rng = random.Random(seed)
    try:
        arrays = service.unpack_arrays(frame)
    except Exception:
        return _bit_flip(frame, seed)
    had_checksum = bool(arrays) and service.is_checksum_array(arrays[-1])
    arrays = [np.array(a) for a in arrays if not service.is_checksum_array(a)]
    hit = False
    for i, a in enumerate(arrays):
        # the delta header: i32[DELTA_HEADER_WORDS] right after the
        # key/n_max prelude — shape-distinct from the trace context (6
        # words) and the session echo (4 words)
        if (
            i > 1
            and a.dtype == np.int32
            and a.ndim == 1
            and a.size == service.DELTA_HEADER_WORDS
        ):
            # words 2..10 hold base_epoch + new_epoch (4 i32 each); keep
            # the kind/n_idx words so the frame still parses as a delta
            a[2:] = np.frombuffer(
                bytes(rng.randrange(256) for _ in range(32)), np.int32
            )
            hit = True
            break
    if not hit:
        return _bit_flip(frame, seed)
    out = service.pack_arrays(arrays)
    return service.append_checksum(out) if had_checksum else out


def _nan_inject(frame: bytes, seed: int) -> bytes:
    """Write the f32 NaN bit pattern over the first word of the fused
    result buffer and RECOMPUTE the checksum — the shape real device SDC
    takes: a perfectly framed, checksum-valid pack whose CONTENT is wrong.
    Only the host-side screen / canary cross-check can catch it. Frames
    without a result buffer degrade to a bit flip."""
    import numpy as np

    from karpenter_tpu.solver import service

    try:
        arrays = service.unpack_arrays(frame)
    except Exception:
        return _bit_flip(frame, seed)
    had_checksum = bool(arrays) and service.is_checksum_array(arrays[-1])
    arrays = [np.array(a) for a in arrays if not service.is_checksum_array(a)]
    hit = False
    for i, a in enumerate(arrays):
        # the fused result buffer: the one big i32 array (f32 totals are
        # bitcast into it); the status word (size 1), session echo (4) and
        # trace words (6) are all far smaller
        if i > 0 and a.dtype == np.int32 and a.ndim == 1 and a.size > 16:
            a.reshape(-1)[0] = np.float32(np.nan).view(np.int32)
            hit = True
            break
    if not hit:
        return _bit_flip(frame, seed)
    out = service.pack_arrays(arrays)
    return service.append_checksum(out) if had_checksum else out


# ---------------------------------------------------------------------------
# control-plane partition scenarios (docs/partition.md): the KUBE apiserver
# misbehaving — the one dependency every subsystem shares
# ---------------------------------------------------------------------------


class ApiServerChaos:
    """Chaos for the Kubernetes control plane: wraps ``TestApiServer``
    (``TestApiServer(chaos=...)`` or ``server.chaos = ...``) so every REST
    request — reads, writes, lease renewals, watch connects — can be
    seeded-randomly failed, throttled, slowed, or dropped:

    - **error_rate**: per-request probability of an injected 503 (a
      browning-out apiserver), optionally overridden per HTTP verb
      (``per_verb={"PATCH": 0.5}``);
    - **throttle_rate**: probability of a 429 WITH a ``Retry-After``
      header — the signal the transport's backoff must honor;
    - **latency_floor / latency_p95**: server-side delay (deterministic
      floor + exponential tail capped at 4x p95);
    - **blackout windows**: the connection is dropped without a response
      (the client sees ``RemoteDisconnected`` — a real partition's shape,
      not a polite error document). ``blackout(seconds)`` opens a window
      starting now; ``blackouts`` pre-seeds windows relative to arming.

    Counters (``injected``/``throttled``/``dropped``/``delayed`` by verb)
    let tests assert chaos actually fired; the RNG is seeded and drawn
    under a lock so a storm's draw SEQUENCE is reproducible."""

    def __init__(
        self,
        error_rate: float = 0.0,
        throttle_rate: float = 0.0,
        retry_after: float = 0.25,
        latency_p95: float = 0.0,
        latency_floor: float = 0.0,
        blackouts: Sequence[ChaosWindow] = (),
        per_verb: Optional[Dict[str, float]] = None,
        seed: int = 0,
        clock=time.monotonic,
    ):
        import random

        self.error_rate = error_rate
        self.throttle_rate = throttle_rate
        self.retry_after = retry_after
        self.latency_p95 = latency_p95
        self.latency_floor = latency_floor
        self.blackouts = list(blackouts)
        self.per_verb = dict(per_verb or {})
        self._clock = clock
        self._t0 = clock()
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self.injected: Dict[str, int] = {}   # verb -> injected 503s
        self.throttled: Dict[str, int] = {}  # verb -> injected 429s
        self.dropped: Dict[str, int] = {}    # verb -> blackout drops
        self.delayed: Dict[str, int] = {}    # verb -> latency injections
        # verb -> remaining forced failures (fail_next): the deterministic
        # "exactly the next N requests fail" primitive retry tests need —
        # probabilistic rates make "retried then succeeded" flaky
        self._forced: Dict[str, int] = {}

    def elapsed(self) -> float:
        return self._clock() - self._t0

    def blackout(self, seconds: float) -> ChaosWindow:
        """Open a blackout window starting NOW (storm legs phase these)."""
        now = self.elapsed()
        window = ChaosWindow(now, now + seconds)
        with self._mu:
            self.blackouts.append(window)
        return window

    def in_blackout(self) -> bool:
        now = self.elapsed()
        with self._mu:
            return any(w.contains(now) for w in self.blackouts)

    def _note(self, table: Dict[str, int], verb: str) -> None:
        with self._mu:
            table[verb] = table.get(verb, 0) + 1

    def counts(self, table: Dict[str, int]) -> int:
        with self._mu:
            return sum(table.values())

    def fail_next(self, verb: str, n: int = 1) -> None:
        """Force exactly the next ``n`` requests of ``verb`` to answer 503
        (counted in ``injected``) regardless of rates — the deterministic
        arm for proving a retry ladder recovers."""
        with self._mu:
            self._forced[verb] = self._forced.get(verb, 0) + n

    def intercept(self, handler, method: str, path: str) -> bool:
        """Chaos disposition for one request. Returns True when the chaos
        layer handled it (sent an error / dropped the connection) and the
        real handler must not run."""
        with self._mu:
            roll = self._rng.random()
            throttle_roll = self._rng.random()
            forced = self._forced.get(method, 0) > 0
            if forced:
                self._forced[method] -= 1
            delay = self.latency_floor
            if self.latency_p95 > 0.0:
                delay += min(
                    self._rng.expovariate(_LN20 / self.latency_p95),
                    self.latency_p95 * 4.0,
                )
        if delay > 0.0:
            self._note(self.delayed, method)
            time.sleep(delay)
        if self.in_blackout():
            # a partition, not a polite error: drop the connection without
            # a response — the client sees RemoteDisconnected/reset
            self._note(self.dropped, method)
            handler.close_connection = True
            try:
                import socket as _socket

                handler.connection.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            return True
        if forced or roll < self.per_verb.get(method, self.error_rate):
            self._note(self.injected, method)
            handler._send_json(503, {
                "apiVersion": "v1", "kind": "Status", "status": "Failure",
                "code": 503, "reason": "ServiceUnavailable",
                "message": "chaos: injected apiserver failure",
            })
            return True
        if throttle_roll < self.throttle_rate:
            self._note(self.throttled, method)
            handler._send_json(
                429,
                {
                    "apiVersion": "v1", "kind": "Status", "status": "Failure",
                    "code": 429, "reason": "TooManyRequests",
                    "message": "chaos: apiserver brownout",
                },
                headers={"Retry-After": f"{self.retry_after:g}"},
            )
            return True
        return False


# ---------------------------------------------------------------------------
# crash-consistency scenarios (docs/launch-journal.md): kill a replica
# between the launch path's three writes (cloud create → Node object → bind)
# ---------------------------------------------------------------------------


class LaunchCrash(BaseException):
    """Simulated process death at an armed launch-path point.

    Deliberately a ``BaseException``: the provisioning worker's launch and
    run loops contain ``Exception`` (a failed launch requeues its pods and
    the loop continues), but a CRASH kills the thread outright — nothing
    runs after the armed point, exactly like a SIGKILL between two writes.
    The journal entry the launch recorded beforehand is the only survivor.
    """


class LaunchCrashCluster:
    """Cluster proxy that simulates a replica dying mid-launch.

    Wraps the (shared) cluster a runtime is built over and intercepts the
    Node write the launch path makes; everything else proxies through, so
    the OTHER replicas of a fleet scenario keep using the bare cluster.

    Armable one-shot points, named for the crash windows the acceptance
    criteria call out:

    - ``before_node_write`` — the cloud create committed (instance exists,
      token stamped, journal entry in ``intent``) but the Node object was
      never written: the orphan the GC sweep must ADOPT.
    - ``after_node_write`` — the Node object landed but no pod was bound
      (journal entry still unresolved): recovery must confirm the Node
      already tracks the instance and resolve, with the pods re-entering
      selection on their own.
    """

    POINTS = ("before_node_write", "after_node_write")

    def __init__(self, cluster):
        self._cluster = cluster
        self._mu = threading.Lock()
        self._armed: Optional[str] = None  # guarded-by: self._mu
        self.crashes: Dict[str, int] = {}  # point -> fired count; guarded-by: self._mu
        # point -> node/instance name the interrupted write was for — the
        # scenario's authoritative handle on WHICH instance was orphaned
        # (scanning the provider for "newest untracked instance" would race
        # the other replicas' healthy in-flight launches)
        self.crash_nodes: Dict[str, str] = {}  # guarded-by: self._mu
        # set when an armed crash fires — the scenario's cue to kill the
        # replica whose launch thread just died
        self.crashed = threading.Event()

    def arm(self, point: str) -> None:
        if point not in self.POINTS:
            raise ValueError(f"unknown crash point {point!r}")
        with self._mu:
            self._armed = point
        self.crashed.clear()

    def _maybe_crash(self, point: str, node_name: str) -> None:
        with self._mu:
            if self._armed != point:
                return
            self._armed = None
            self.crashes[point] = self.crashes.get(point, 0) + 1
            self.crash_nodes[point] = node_name
        self.crashed.set()
        raise LaunchCrash(f"simulated crash {point} (node {node_name})")

    def create(self, kind: str, obj):
        if kind == "nodes":
            self._maybe_crash("before_node_write", obj.metadata.name)
        out = self._cluster.create(kind, obj)
        if kind == "nodes":
            self._maybe_crash("after_node_write", obj.metadata.name)
        return out

    def __getattr__(self, name: str):
        return getattr(self._cluster, name)


# ---------------------------------------------------------------------------
# fleet-scale scenarios (docs/fleet.md): replica-kill and sidecar-kill
# ---------------------------------------------------------------------------


class SidecarChaos:
    """A pool of in-process solver sidecars with kill/restart controls.

    ``kill`` stops a member's gRPC server with zero grace — in-flight RPCs
    fail exactly like a SIGKILL'd pod's would. ``restart`` serves the SAME
    address again with a FRESH ``SolverService`` (empty session store), so
    clients that remembered the address's sessions hit NEEDS_CATALOG, the
    restart-recovery path the pool's failover ladder must absorb.

    ``policies`` (member index -> :class:`ChaosPolicy`) — or the ``policy``
    argument to :meth:`restart` — wraps that member's service in a chaos
    proxy, which is how the corruption-storm leg makes exactly the
    SERVING member emit corrupt frames; the proxies are kept in
    ``self.proxies`` so the leg can read injection counters and retarget
    ``proxy.policy`` between phases."""

    def __init__(
        self,
        n: int = 2,
        max_workers: int = 4,
        policies: Optional[Dict[int, ChaosPolicy]] = None,
    ):
        from karpenter_tpu.solver.service import serve

        self._serve = serve
        self._max_workers = max_workers
        self.servers: Dict[str, object] = {}
        self.proxies: Dict[str, ChaosProxy] = {}
        self.addresses: list = []
        for i in range(n):
            address = f"127.0.0.1:{self._free_port()}"
            self.addresses.append(address)
            self.servers[address] = self._serve_member(
                address, (policies or {}).get(i)
            )

    def _serve_member(self, address: str, policy: Optional[ChaosPolicy]):
        from karpenter_tpu.solver.service import SolverService

        service = SolverService()
        if policy is not None:
            service = chaos_wrap(service, policy)
            self.proxies[address] = service
        else:
            self.proxies.pop(address, None)
        return self._serve(
            address, max_workers=self._max_workers, service=service
        )

    @staticmethod
    def _free_port() -> int:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    @property
    def address_spec(self) -> str:
        """The comma-joined pool spec ``--solver-service-address`` takes."""
        return ",".join(self.addresses)

    def busiest(self) -> str:
        """The member holding the most pinned sessions — killing IT (not a
        cold spare) is what actually exercises failover + re-upload."""
        return max(
            self.servers,
            key=lambda a: self.servers[a].solver_service.session_count(),
        )

    def kill(self, address: str) -> None:
        server = self.servers.pop(address, None)
        if server is not None:
            server.stop(grace=0)

    def restart(
        self, address: str, policy: Optional[ChaosPolicy] = None
    ) -> None:
        """Fresh process-equivalent on the same address: empty session
        store, immediate readiness. ``policy`` restarts the member behind
        a chaos proxy (the corruption-storm leg's way of corrupting the
        member the ring actually routes to, without moving the ring)."""
        self.kill(address)
        self.servers[address] = self._serve_member(address, policy)

    def stop_all(self) -> None:
        for address in list(self.servers):
            self.kill(address)


class ReplicaChaos:
    """Controller-replica kill/restart over a shared cluster + lease set.

    Replicas are ``main.Runtime`` objects (each with a ``fleet.ShardManager``).
    ``kill`` is a CRASH: the shard manager dies without releasing its
    leases, so survivors must wait out the lease duration and take the dead
    replica's shards over — the rebalance-on-death path the acceptance
    criteria time-bound to 2x the lease duration."""

    def __init__(self):
        self.replicas: Dict[str, object] = {}
        self.killed: Dict[str, object] = {}

    def add(self, name: str, runtime) -> None:
        self.replicas[name] = runtime

    def kill(self, name: str) -> None:
        runtime = self.replicas.pop(name)
        self.killed[name] = runtime
        if runtime.ownership is not None:
            runtime.ownership.crash()  # no lease release: a real SIGKILL
        runtime.stop()

    def owner_named(self, shard: str):
        """(replica name, runtime) currently owning ``shard`` among the
        LIVE replicas, or (None, None)."""
        for name, runtime in self.replicas.items():
            if runtime.ownership is not None and runtime.ownership.owns(shard):
                return name, runtime
        return None, None

    def owned_shards(self) -> Dict[str, frozenset]:
        return {
            name: frozenset(rt.ownership.owned())
            for name, rt in self.replicas.items()
            if rt.ownership is not None
        }

    def stop_all(self) -> None:
        for name in list(self.replicas):
            self.replicas.pop(name).stop()


class ArrivalPattern:
    """Seeded diurnal + flash-crowd pod-arrival generator.

    The forecast-storm bench leg and the forecaster tests need a demand
    shape with both of the signals predictive provisioning exists for: a
    smooth periodic baseline the seasonal model can learn, and sudden
    flash crowds that punish purely-reactive provisioning with a full
    cold launch-to-ready tail. ``schedule(duration_s)`` compiles the
    whole run up front into ``[(t_offset_s, n_pods), ...]`` ticks —
    reproducible bit-for-bit from the seed, so a bench regression replays
    the exact same storm.

    The baseline is a sinusoid (one ``period_s`` = one compressed "day"),
    Poisson-ish jittered per tick; each flash crowd is a burst of
    ``flash_pods`` spread over ``flash_len_s`` starting at its offset."""

    def __init__(
        self,
        base_pods_per_tick: float = 4.0,
        amplitude: float = 0.75,
        period_s: float = 240.0,
        tick_s: float = 5.0,
        flash_at: Sequence[float] = (),
        flash_pods: int = 40,
        flash_len_s: float = 15.0,
        seed: int = 0,
    ):
        self.base = float(base_pods_per_tick)
        self.amplitude = min(max(float(amplitude), 0.0), 1.0)
        self.period_s = float(period_s)
        self.tick_s = float(tick_s)
        self.flash_at = tuple(float(t) for t in flash_at)
        self.flash_pods = int(flash_pods)
        self.flash_len_s = float(flash_len_s)
        self.seed = int(seed)

    def in_flash(self, t: float) -> bool:
        """True when offset ``t`` falls inside a flash-crowd window —
        how the bench separates the spike tail from the baseline."""
        return any(
            start <= t < start + self.flash_len_s for start in self.flash_at
        )

    def rate_at(self, t: float) -> float:
        """The noiseless diurnal baseline (pods per tick) at offset ``t``
        — what a perfect seasonal forecaster would predict."""
        phase = 2.0 * math.pi * (t / self.period_s)
        return self.base * (1.0 + self.amplitude * math.sin(phase))

    def schedule(self, duration_s: float) -> List[Tuple[float, int]]:
        """``[(t_offset_s, n_pods), ...]`` ticks covering ``duration_s``,
        flash bursts folded in. Zero-pod ticks are kept: silence is
        signal to the forecaster (rates must decay, not freeze)."""
        rng = random.Random(self.seed)
        ticks: List[Tuple[float, int]] = []
        t = 0.0
        while t < duration_s:
            lam = max(self.rate_at(t), 0.0)
            # cheap Poisson-ish draw: uniform jitter of +-50% keeps the
            # variance the EWMA band must cover without scipy
            n = int(round(lam * (0.5 + rng.random())))
            ticks.append((t, max(n, 0)))
            t += self.tick_s
        for start in self.flash_at:
            if start >= duration_s:
                continue
            burst_ticks = max(int(self.flash_len_s / self.tick_s), 1)
            per_tick = max(self.flash_pods // burst_ticks, 1)
            for i in range(burst_ticks):
                at = start + i * self.tick_s
                if at >= duration_s:
                    break
                ticks.append((at, per_tick))
        ticks.sort(key=lambda p: p[0])
        return ticks

    def total_pods(self, duration_s: float) -> int:
        return sum(n for _, n in self.schedule(duration_s))
