"""Chaos-injection harness for the cloud control-plane doubles.

The one-shot ``SimCloudAPI.inject_error`` can stage exactly one failure per
method — enough for unit tests, useless for proving the resilience layer
(retries, breakers, budgets) holds up under a *sustained* failure regime.
``ChaosPolicy`` + ``chaos_wrap`` turn any control-plane double
(``SimCloudAPI``, ``SimGkeAPI`` — and, by wrapping the double handed to
``CloudAPIServer``/``GkeAPIServer``, the HTTP wire too: injected errors
cross as 5xx/429/409) into a statistically misbehaving dependency:

- a per-call **error probability** (optionally per method), alternating
  injected control-plane failures with throttles;
- an **injected latency** distribution calibrated by its p95 (exponential,
  tail-capped so a single sample can't stall a test past its budget);
- **ICE storms**: windows during which every ``create_fleet`` override
  answers insufficient-capacity (the typed all-ICE error, carrying the
  overrides, exactly like a real exhausted region);
- **blackouts**: windows during which every wrapped method fails;
- a **seeded RNG** so a chaos run is reproducible bit-for-bit, and
  per-method injection counters so tests can assert chaos actually fired.

Programming/fault-injection helpers (``inject_error``,
``send_disruption_notice``, ``set_stockout`` …) and attribute access pass
through unwrapped: chaos applies to the control-plane *calls*, not to the
test's ability to program the double.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

# The control-plane surfaces chaos applies to. Anything else (programming
# helpers, attributes, the disruption injectors) passes through untouched.
CHAOS_METHODS = frozenset({
    # SimCloudAPI
    "describe_instance_types", "describe_subnets", "describe_security_groups",
    "ensure_launch_template", "delete_launch_template", "create_fleet",
    "describe_instances", "terminate_instances", "poll_disruptions",
    # SimGkeAPI
    "create_node_pool", "delete_node_pool", "delete_instance",
    # solver sidecar (service.SolverService) — a chaos-wrapped service
    # handed to service.serve() simulates a slow/failing device solve, the
    # pipeline-smoke test's way of proving encode(i+1) hides under solve(i)
    "solve_bytes", "open_session_bytes",
})

# exponential p95 = mean * ln(20); invert to calibrate the mean from a p95
_LN20 = 2.9957322735539909


@dataclass(frozen=True)
class ChaosWindow:
    """Half-open [start, end) window in seconds since the policy armed."""

    start: float
    end: float

    def contains(self, t: float) -> bool:
        return self.start <= t < self.end


@dataclass
class ChaosPolicy:
    """What misbehavior to inject, and how much."""

    error_rate: float = 0.0          # per-call failure probability
    latency_p95: float = 0.0         # seconds; 0 = no injected latency
    # deterministic per-call latency floor (seconds), added before any
    # random draw: overlap tests need a KNOWN in-flight time to hide host
    # work under, which an exponential draw can't guarantee
    latency_floor: float = 0.0
    throttle_fraction: float = 0.25  # this share of injected errors throttle (429)
    ice_storms: Sequence[ChaosWindow] = ()
    blackouts: Sequence[ChaosWindow] = ()
    seed: int = 0
    # restrict chaos to these methods (None = every CHAOS_METHODS member)
    methods: Optional[frozenset] = None
    # cap one latency sample so a tail draw can't stall a test (× p95)
    latency_cap_factor: float = 4.0

    def applies_to(self, method: str) -> bool:
        if method not in CHAOS_METHODS:
            return False
        return self.methods is None or method in self.methods


class ChaosProxy:
    """Wraps a control-plane double with a :class:`ChaosPolicy`.

    Duck-typed: any object whose public methods appear in ``CHAOS_METHODS``
    gets those calls intercepted; everything else proxies through, so the
    wrapped double still serves ``CloudAPIServer``/``GkeAPIServer`` and the
    tests' programming surface unchanged.
    """

    def __init__(self, delegate, policy: ChaosPolicy, clock=time.monotonic):
        import random

        self._delegate = delegate
        self.policy = policy
        self._clock = clock
        self._t0 = clock()
        # one lock around the RNG: chaos fires from server handler threads
        # and controller threads at once, and a seeded run must stay
        # deterministic in its draw SEQUENCE (interleaving may still vary)
        self._rng = random.Random(policy.seed)
        self._rng_mu = threading.Lock()
        self.injected: Dict[str, int] = {}   # method -> injected failures
        self.delayed: Dict[str, int] = {}    # method -> latency injections
        self._count_mu = threading.Lock()

    # -- bookkeeping --------------------------------------------------------
    def _note(self, table: Dict[str, int], method: str) -> None:
        with self._count_mu:
            table[method] = table.get(method, 0) + 1

    def injected_total(self) -> int:
        with self._count_mu:
            return sum(self.injected.values())

    def elapsed(self) -> float:
        return self._clock() - self._t0

    # -- the wrap -----------------------------------------------------------
    def __getattr__(self, name: str):
        attr = getattr(self._delegate, name)
        if not callable(attr) or not self.policy.applies_to(name):
            return attr

        def chaotic(*args, **kwargs):
            self._maybe_disturb(name, args)
            return attr(*args, **kwargs)

        return chaotic

    def _maybe_disturb(self, method: str, args: tuple) -> None:
        from karpenter_tpu.cloudprovider.httpapi import ThrottlingError
        from karpenter_tpu.cloudprovider.simulated import (
            CloudAPIError,
            InsufficientCapacityError,
        )

        now = self.elapsed()
        policy = self.policy
        with self._rng_mu:
            roll = self._rng.random()
            throttle = self._rng.random() < policy.throttle_fraction
            delay = 0.0
            if policy.latency_p95 > 0.0:
                delay = min(
                    self._rng.expovariate(_LN20 / policy.latency_p95),
                    policy.latency_p95 * policy.latency_cap_factor,
                )
        delay += policy.latency_floor
        if delay > 0.0:
            self._note(self.delayed, method)
            time.sleep(delay)
        if any(w.contains(now) for w in policy.blackouts):
            self._note(self.injected, method)
            raise CloudAPIError(f"chaos blackout: {method} unavailable")
        if method == "create_fleet" and any(
            w.contains(now) for w in policy.ice_storms
        ):
            self._note(self.injected, method)
            overrides = [
                (args[0], it, zone) for (_lt, it, zone) in (args[1] if len(args) > 1 else [])
            ]
            raise InsufficientCapacityError(
                "chaos ICE storm: all pools exhausted", overrides=overrides
            )
        if roll < policy.error_rate:
            self._note(self.injected, method)
            if throttle:
                raise ThrottlingError(retry_after=0.01)
            raise CloudAPIError(f"chaos: injected {method} failure")


def chaos_wrap(api, policy: ChaosPolicy, clock=time.monotonic) -> ChaosProxy:
    """Wrap a ``SimCloudAPI``/``SimGkeAPI`` (or anything speaking their
    method protocols) in a chaos proxy. The result is a drop-in wherever
    the bare double went — ``SimulatedCloudProvider(api=...)``,
    ``GkeCloudProvider(api=...)``, ``CloudAPIServer(api=...)``."""
    return ChaosProxy(api, policy, clock=clock)


# ---------------------------------------------------------------------------
# crash-consistency scenarios (docs/launch-journal.md): kill a replica
# between the launch path's three writes (cloud create → Node object → bind)
# ---------------------------------------------------------------------------


class LaunchCrash(BaseException):
    """Simulated process death at an armed launch-path point.

    Deliberately a ``BaseException``: the provisioning worker's launch and
    run loops contain ``Exception`` (a failed launch requeues its pods and
    the loop continues), but a CRASH kills the thread outright — nothing
    runs after the armed point, exactly like a SIGKILL between two writes.
    The journal entry the launch recorded beforehand is the only survivor.
    """


class LaunchCrashCluster:
    """Cluster proxy that simulates a replica dying mid-launch.

    Wraps the (shared) cluster a runtime is built over and intercepts the
    Node write the launch path makes; everything else proxies through, so
    the OTHER replicas of a fleet scenario keep using the bare cluster.

    Armable one-shot points, named for the crash windows the acceptance
    criteria call out:

    - ``before_node_write`` — the cloud create committed (instance exists,
      token stamped, journal entry in ``intent``) but the Node object was
      never written: the orphan the GC sweep must ADOPT.
    - ``after_node_write`` — the Node object landed but no pod was bound
      (journal entry still unresolved): recovery must confirm the Node
      already tracks the instance and resolve, with the pods re-entering
      selection on their own.
    """

    POINTS = ("before_node_write", "after_node_write")

    def __init__(self, cluster):
        self._cluster = cluster
        self._mu = threading.Lock()
        self._armed: Optional[str] = None  # guarded-by: self._mu
        self.crashes: Dict[str, int] = {}  # point -> fired count; guarded-by: self._mu
        # point -> node/instance name the interrupted write was for — the
        # scenario's authoritative handle on WHICH instance was orphaned
        # (scanning the provider for "newest untracked instance" would race
        # the other replicas' healthy in-flight launches)
        self.crash_nodes: Dict[str, str] = {}  # guarded-by: self._mu
        # set when an armed crash fires — the scenario's cue to kill the
        # replica whose launch thread just died
        self.crashed = threading.Event()

    def arm(self, point: str) -> None:
        if point not in self.POINTS:
            raise ValueError(f"unknown crash point {point!r}")
        with self._mu:
            self._armed = point
        self.crashed.clear()

    def _maybe_crash(self, point: str, node_name: str) -> None:
        with self._mu:
            if self._armed != point:
                return
            self._armed = None
            self.crashes[point] = self.crashes.get(point, 0) + 1
            self.crash_nodes[point] = node_name
        self.crashed.set()
        raise LaunchCrash(f"simulated crash {point} (node {node_name})")

    def create(self, kind: str, obj):
        if kind == "nodes":
            self._maybe_crash("before_node_write", obj.metadata.name)
        out = self._cluster.create(kind, obj)
        if kind == "nodes":
            self._maybe_crash("after_node_write", obj.metadata.name)
        return out

    def __getattr__(self, name: str):
        return getattr(self._cluster, name)


# ---------------------------------------------------------------------------
# fleet-scale scenarios (docs/fleet.md): replica-kill and sidecar-kill
# ---------------------------------------------------------------------------


class SidecarChaos:
    """A pool of in-process solver sidecars with kill/restart controls.

    ``kill`` stops a member's gRPC server with zero grace — in-flight RPCs
    fail exactly like a SIGKILL'd pod's would. ``restart`` serves the SAME
    address again with a FRESH ``SolverService`` (empty session store), so
    clients that remembered the address's sessions hit NEEDS_CATALOG, the
    restart-recovery path the pool's failover ladder must absorb."""

    def __init__(self, n: int = 2, max_workers: int = 4):
        from karpenter_tpu.solver.service import serve

        self._serve = serve
        self._max_workers = max_workers
        self.servers: Dict[str, object] = {}
        self.addresses: list = []
        for _ in range(n):
            address = f"127.0.0.1:{self._free_port()}"
            self.addresses.append(address)
            self.servers[address] = serve(address, max_workers=max_workers)

    @staticmethod
    def _free_port() -> int:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    @property
    def address_spec(self) -> str:
        """The comma-joined pool spec ``--solver-service-address`` takes."""
        return ",".join(self.addresses)

    def busiest(self) -> str:
        """The member holding the most pinned sessions — killing IT (not a
        cold spare) is what actually exercises failover + re-upload."""
        return max(
            self.servers,
            key=lambda a: self.servers[a].solver_service.session_count(),
        )

    def kill(self, address: str) -> None:
        server = self.servers.pop(address, None)
        if server is not None:
            server.stop(grace=0)

    def restart(self, address: str) -> None:
        """Fresh process-equivalent on the same address: empty session
        store, immediate readiness."""
        self.kill(address)
        self.servers[address] = self._serve(
            address, max_workers=self._max_workers
        )

    def stop_all(self) -> None:
        for address in list(self.servers):
            self.kill(address)


class ReplicaChaos:
    """Controller-replica kill/restart over a shared cluster + lease set.

    Replicas are ``main.Runtime`` objects (each with a ``fleet.ShardManager``).
    ``kill`` is a CRASH: the shard manager dies without releasing its
    leases, so survivors must wait out the lease duration and take the dead
    replica's shards over — the rebalance-on-death path the acceptance
    criteria time-bound to 2x the lease duration."""

    def __init__(self):
        self.replicas: Dict[str, object] = {}
        self.killed: Dict[str, object] = {}

    def add(self, name: str, runtime) -> None:
        self.replicas[name] = runtime

    def kill(self, name: str) -> None:
        runtime = self.replicas.pop(name)
        self.killed[name] = runtime
        if runtime.ownership is not None:
            runtime.ownership.crash()  # no lease release: a real SIGKILL
        runtime.stop()

    def owner_named(self, shard: str):
        """(replica name, runtime) currently owning ``shard`` among the
        LIVE replicas, or (None, None)."""
        for name, runtime in self.replicas.items():
            if runtime.ownership is not None and runtime.ownership.owns(shard):
                return name, runtime
        return None, None

    def owned_shards(self) -> Dict[str, frozenset]:
        return {
            name: frozenset(rt.ownership.owned())
            for name, rt in self.replicas.items()
            if rt.ownership is not None
        }

    def stop_all(self) -> None:
        for name in list(self.replicas):
            self.replicas.pop(name).stop()
